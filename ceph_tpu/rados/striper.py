"""radosstriper: stripe one logical object across many RADOS objects.

Role-equivalent of the reference's libradosstriper
(src/libradosstriper/RadosStriperImpl.cc): a logical object is cut into
`object_size`-byte pieces named ``<soid>.%016d``; a header object
``<soid>`` carries the striping layout + total size in xattr-style
metadata so readers reassemble without listing.  This is the same layout
discipline RBD and CephFS use for their data objects.
"""

from __future__ import annotations

import asyncio
import json
from typing import List, Optional

from ceph_tpu.rados.client import RadosError
from ceph_tpu.rados.librados import IoCtx

DEFAULT_OBJECT_SIZE = 1 << 22  # 4 MiB, the reference default


class RadosStriper:
    def __init__(self, ioctx: IoCtx, object_size: int = DEFAULT_OBJECT_SIZE):
        self.ioctx = ioctx
        self.object_size = object_size

    @staticmethod
    def _piece(soid: str, index: int) -> str:
        return f"{soid}.{index:016d}"

    def _header(self, soid: str) -> str:
        return f"{soid}.__striper__"

    async def write(self, soid: str, data: bytes) -> None:
        """Full-object striped write: pieces in parallel + header
        (layout + size)."""
        # the previous header (one tiny read) tells us exactly which tail
        # pieces a shrinking rewrite must trim — never a pool listing
        old_pieces = 0
        try:
            old_pieces = json.loads(
                await self.ioctx.read(self._header(soid)))["pieces"]
        except (RadosError, KeyError, ValueError):
            pass
        n = max(1, (len(data) + self.object_size - 1) // self.object_size)
        try:
            await asyncio.gather(*(
                self.ioctx.write_full(
                    self._piece(soid, i),
                    data[i * self.object_size:(i + 1) * self.object_size])
                for i in range(n)
            ))
        except BaseException:
            # pieces 0..old_n may now hold MIXED generations: mark the
            # object unreadable (size -1 tombstone) rather than let reads
            # stitch old and new bytes together, then drop this attempt's
            # orphan tail pieces
            try:
                await self.ioctx.write_full(
                    self._header(soid),
                    json.dumps({"object_size": self.object_size,
                                "size": -1,
                                "pieces": max(old_pieces, n)}).encode())
            except Exception:
                pass
            await asyncio.gather(*(
                self.ioctx.remove(self._piece(soid, i))
                for i in range(max(0, old_pieces), n)
            ), return_exceptions=True)
            raise
        header = {"object_size": self.object_size, "size": len(data),
                  "pieces": n}
        await self.ioctx.write_full(self._header(soid),
                                    json.dumps(header).encode())
        if old_pieces > n:
            await asyncio.gather(*(
                self.ioctx.remove(self._piece(soid, i))
                for i in range(n, old_pieces)
            ), return_exceptions=True)

    async def read(self, soid: str) -> bytes:
        header = json.loads(await self.ioctx.read(self._header(soid)))
        if header.get("size", 0) < 0:
            raise RadosError(f"{soid}: torn by an interrupted write")
        pieces = await asyncio.gather(*(
            self.ioctx.read(self._piece(soid, i))
            for i in range(header["pieces"])
        ))
        return b"".join(pieces)[:header["size"]]

    async def read_range(self, soid: str, off: int, length: int) -> bytes:
        """Partial read: only the pieces overlapping [off, off+length)
        are fetched (reference libradosstriper read path: extent →
        per-object extents via the layout, no full-object
        materialization).  Clamped to the object size."""
        header = json.loads(await self.ioctx.read(self._header(soid)))
        if header.get("size", 0) < 0:
            raise RadosError(f"{soid}: torn by an interrupted write")
        size = header["size"]
        osize = header["object_size"]
        end = min(off + max(0, length), size)
        if off >= end:
            return b""
        first, last = off // osize, (end - 1) // osize
        pieces = await asyncio.gather(*(
            self.ioctx.read(self._piece(soid, i))
            for i in range(first, last + 1)
        ))
        base = first * osize
        return b"".join(pieces)[off - base:end - base]

    async def stat(self, soid: str) -> dict:
        return json.loads(await self.ioctx.read(self._header(soid)))

    async def remove(self, soid: str) -> None:
        try:
            header = json.loads(await self.ioctx.read(self._header(soid)))
        except RadosError:
            return
        for i in range(header["pieces"]):
            try:
                await self.ioctx.remove(self._piece(soid, i))
            except RadosError:
                pass
        await self.ioctx.remove(self._header(soid))

    async def list(self) -> List[str]:
        suffix = ".__striper__"
        return sorted(
            o[: -len(suffix)]
            for o in await self.ioctx.list_objects()
            if o.endswith(suffix)
        )
