"""librados-style public API: cluster handle + per-pool IoCtx.

Role-equivalent of the reference's librados (reference
src/librados/librados_c.cc, IoCtxImpl.cc): applications connect a
:class:`Rados` handle, open an :class:`IoCtx` per pool (by name), and do
sync or async object I/O — the async completions mirror rados_aio_*
(IoCtxImpl::aio_read/aio_write bridging to Objecter completions).  The
underlying engine is RadosClient (the Objecter role: client-side
placement, resend across epochs, reqid idempotency).
"""

from __future__ import annotations

import asyncio
import errno as _errno
from typing import Any, Dict, List, Optional

from ceph_tpu.rados.client import RadosClient, RadosError


class Completion:
    """rados_completion_t role: await it, or poll is_complete()."""

    def __init__(self, task: "asyncio.Task"):
        self._task = task

    def is_complete(self) -> bool:
        return self._task.done()

    async def wait(self) -> Any:
        return await self._task

    def result(self) -> Any:
        return self._task.result()


class IoCtx:
    """Per-pool I/O context (librados::IoCtx role)."""

    def __init__(self, rados: "Rados", pool_id: int, pool_name: str):
        self._rados = rados
        self.pool_id = pool_id
        self.pool_name = pool_name
        # self-managed snapshot state (librados set_snap_write_context /
        # snap_set_read roles): writes carry the context; reads resolve
        # at the read snap when set
        self._snapc_seq = 0
        self._snapc_snaps: List[int] = []
        self._snap_read = 0
        # rados namespace (reference rados_ioctx_set_namespace /
        # object_locator_t nspace): part of object IDENTITY — the same
        # name in two namespaces is two objects, placed independently
        self._nspace = ""

    @property
    def _c(self) -> RadosClient:
        return self._rados._client

    # -- namespaces (reference rados_ioctx_set_namespace) --------------------

    def set_namespace(self, nspace: str) -> None:
        """All subsequent I/O on this ioctx targets (nspace, name)
        identities; "" returns to the default namespace and the
        ALL_NSPACES sentinel makes listings span every namespace
        (I/O in that state is rejected, as in the reference)."""
        from ceph_tpu.rados.types import ALL_NSPACES, NS_SEP, SNAP_SEP

        if nspace != ALL_NSPACES and (NS_SEP in nspace
                                      or SNAP_SEP in nspace):
            raise RadosError("invalid namespace", code=-_errno.EINVAL)
        self._nspace = nspace

    def get_namespace(self) -> str:
        return self._nspace

    def _full(self, oid: str) -> str:
        """Compose the wire object name for this ioctx's namespace;
        the separator (and the all-namespaces sentinel) cannot ride in
        from user names."""
        from ceph_tpu.rados.types import ALL_NSPACES, NS_SEP, make_oid

        if NS_SEP in oid:
            raise RadosError("oid contains the reserved namespace "
                             "separator", code=-_errno.EINVAL)
        if self._nspace == ALL_NSPACES:
            raise RadosError("I/O requires a concrete namespace "
                             "(ioctx is set to ALL_NSPACES)",
                             code=-_errno.EINVAL)
        return make_oid(self._nspace, oid)

    # -- self-managed snapshots (reference rados_ioctx_selfmanaged_*) --------

    async def selfmanaged_snap_create(self) -> int:
        """Allocate a snap id and fold it into this ioctx's write
        context."""
        snap_id = await self._c.selfmanaged_snap_create(self.pool_id)
        self.set_snap_write_context(
            snap_id, [snap_id] + list(self._snapc_snaps))
        return snap_id

    async def selfmanaged_snap_remove(self, snap_id: int) -> None:
        await self._c.selfmanaged_snap_remove(self.pool_id, snap_id)
        self._snapc_snaps = [s for s in self._snapc_snaps if s != snap_id]

    async def selfmanaged_snap_rollback(self, oid: str,
                                        snap_id: int) -> None:
        """Restore the head to its state at `snap_id` (reference
        rollback: read-at-snap -> write head; an object absent at the
        snap is removed)."""
        await self._c.rollback_object(self.pool_id, self._full(oid),
                                      snap_id, snapc=self._snapc)

    # -- pool snapshots (reference rados_ioctx_snap_create / mksnap) ---------

    async def snap_create(self, name: str) -> int:
        """Mon-managed POOL snapshot (reference `rados mksnap`): the
        whole pool's state becomes readable at the returned snap id;
        mixing with self-managed snaps is refused by the mon
        (-EINVAL)."""
        return await self._c.pool_snap_create(self.pool_id, name)

    async def snap_remove(self, name: str) -> None:
        await self._c.pool_snap_remove(self.pool_id, name)

    async def snap_list(self) -> Dict[str, int]:
        return await self._c.pool_snap_list(self.pool_id)

    async def snap_lookup(self, name: str) -> int:
        snaps = await self._c.pool_snap_list(self.pool_id)
        if name not in snaps:
            raise RadosError(f"no pool snap {name!r}",
                             code=-_errno.ENOENT)
        return snaps[name]

    async def snap_rollback(self, oid: str, name: str) -> None:
        """Restore one object's head to its state at the named pool
        snapshot (reference `rados rollback <obj> <snap>`: per-object,
        not pool-wide)."""
        sid = await self.snap_lookup(name)
        await self._c.rollback_object(self.pool_id, self._full(oid), sid)

    async def allocate_snap_id(self) -> int:
        """Allocate a snap id WITHOUT touching this ioctx's write
        context — services managing many volumes over one ioctx (RBD)
        build per-volume contexts themselves."""
        return await self._c.selfmanaged_snap_create(self.pool_id)

    async def release_snap_id(self, snap_id: int) -> None:
        await self._c.selfmanaged_snap_remove(self.pool_id, snap_id)

    def set_snap_write_context(self, seq: int, snaps: List[int]) -> None:
        """snaps must be DESCENDING (newest first), seq >= snaps[0]."""
        self._snapc_seq = int(seq)
        self._snapc_snaps = sorted((int(s) for s in snaps), reverse=True)

    def snap_set_read(self, snap_id: int) -> None:
        """0 = head; else reads resolve at that snap."""
        self._snap_read = int(snap_id)

    @property
    def _snapc(self):
        # None lets the client supply the pool's SnapContext for a
        # pool-snaps-mode pool (client._write_snapc — ONE fallback for
        # every writer path, ioctx or raw)
        if self._snapc_seq:
            return (self._snapc_seq, self._snapc_snaps)
        return None

    # -- sync ops ------------------------------------------------------------
    # per-call snapc/snap overrides let services (RBD) manage MANY
    # logical volumes' contexts over one shared ioctx

    async def write_full(self, oid: str, data: bytes, snapc=None) -> None:
        await self._c.put(self.pool_id, self._full(oid), data,
                          snapc=snapc if snapc is not None else self._snapc)

    async def write(self, oid: str, data: bytes, offset: int = 0,
                    snapc=None) -> None:
        await self._c.put(self.pool_id, self._full(oid), data, offset=offset,
                          snapc=snapc if snapc is not None else self._snapc)

    async def read(self, oid: str, snap: Optional[int] = None) -> bytes:
        return await self._c.get(
            self.pool_id, self._full(oid),
            snap=snap if snap is not None else self._snap_read)

    async def remove(self, oid: str, snapc=None) -> None:
        await self._c.delete(self.pool_id, self._full(oid),
                             snapc=snapc if snapc is not None else self._snapc)

    async def stat(self, oid: str) -> Dict[str, int]:
        """Size/version from shard metadata — no payload transfer."""
        from ceph_tpu.rados.types import MOSDOp

        reply = await self._c._op(MOSDOp(op="stat", pool_id=self.pool_id,
                                         oid=self._full(oid)))
        return {"size": int(reply.data), "version": reply.version}

    async def list_objects(self) -> List[str]:
        """Objects in THIS ioctx's namespace, bare names; with the
        ALL_NSPACES sentinel set, every namespace's WIRE names (callers
        split them with types.split_ns)."""
        from ceph_tpu.rados.types import ALL_NSPACES, split_ns

        wire = await self._c.list_objects(self.pool_id,
                                          nspace=self._nspace)
        if self._nspace == ALL_NSPACES:
            return wire
        return [split_ns(o)[1] for o in wire]

    async def execute(self, oid: str, cls: str, method: str,
                      inp: bytes = b"") -> Any:
        """Object-class call (rados_exec role); EC pools raise
        EOPNOTSUPP exactly as the reference does."""
        import pickle

        from ceph_tpu.rados.types import MOSDOp

        reply = await self._c._op(MOSDOp(op="call", pool_id=self.pool_id,
                                         oid=self._full(oid), data=inp,
                                         cls=cls, method=method), retries=3)
        return pickle.loads(reply.data)

    # -- xattr / omap conveniences (rados_{set,get}xattr, rados_omap_*) -----
    # each is a one-sub-op compound (the multi executor is the single
    # server-side metadata path, so these are atomic with cls calls)

    async def setxattr(self, oid: str, name: str, value: bytes) -> None:
        await self._c.multi(self.pool_id, self._full(oid),
                            [("setxattr", {"name": name,
                                           "value": bytes(value)})],
                            snapc=self._snapc)

    async def getxattr(self, oid: str, name: str) -> bytes:
        results, _v = await self._c.multi(
            self.pool_id, self._full(oid), [("getxattr", {"name": name})])
        return results[0][1]

    async def rmxattr(self, oid: str, name: str) -> None:
        await self._c.multi(self.pool_id, self._full(oid),
                            [("rmxattr", {"name": name})],
                            snapc=self._snapc)

    async def getxattrs(self, oid: str) -> Dict[str, bytes]:
        results, _v = await self._c.multi(self.pool_id, self._full(oid),
                                          [("getxattrs", {})])
        return results[0][1]

    async def omap_set(self, oid: str, entries: Dict[str, bytes]) -> None:
        await self._c.multi(self.pool_id, self._full(oid),
                            [("omap_set", {"entries": dict(entries)})],
                            snapc=self._snapc)

    async def omap_get_vals(self, oid: str) -> Dict[str, bytes]:
        results, _v = await self._c.multi(self.pool_id, self._full(oid),
                                          [("omap_get_vals", {})])
        return results[0][1]

    async def omap_rm_keys(self, oid: str, keys) -> None:
        await self._c.multi(self.pool_id, self._full(oid),
                            [("omap_rm_keys", {"keys": list(keys)})],
                            snapc=self._snapc)

    async def operate(self, oid: str, op) -> list:
        """Execute a neorados WriteOp/ReadOp through this ioctx
        (librados operate/operate_read role over the same engine)."""
        results, _v = await self._c.multi(self.pool_id, self._full(oid),
                                          op._ops, snapc=self._snapc)
        return results

    async def watch(self, oid: str, callback) -> None:
        await self._c.watch(self.pool_id, self._full(oid), callback)

    async def unwatch(self, oid: str) -> None:
        await self._c.unwatch(self.pool_id, self._full(oid))

    async def notify(self, oid: str, payload: bytes = b"") -> List:
        return await self._c.notify(self.pool_id, self._full(oid), payload)

    # -- async (aio_*) -------------------------------------------------------

    def aio_write(self, oid: str, data: bytes) -> Completion:
        return Completion(asyncio.get_running_loop().create_task(
            self.write_full(oid, data)))

    def aio_read(self, oid: str) -> Completion:
        return Completion(asyncio.get_running_loop().create_task(
            self.read(oid)))

    def aio_remove(self, oid: str) -> Completion:
        return Completion(asyncio.get_running_loop().create_task(
            self.remove(oid)))


class Rados:
    """Cluster handle (rados_t role): connect, open pools by name."""

    def __init__(self, mon_addr, conf: Optional[dict] = None):
        self._client = RadosClient(mon_addr, conf)
        self.connected = False

    async def connect(self) -> "Rados":
        await self._client.start()
        await self._client.refresh_map()
        self.connected = True
        return self

    async def shutdown(self) -> None:
        await self._client.stop()
        self.connected = False

    async def open_ioctx(self, pool_name: str) -> IoCtx:
        await self._client.refresh_map()
        pool = self._client.osdmap.pool_by_name(pool_name)
        if pool is None:
            raise RadosError(f"pool {pool_name!r} does not exist")
        return IoCtx(self, pool.pool_id, pool_name)

    async def pool_create(self, name: str, pool_type: str = "ec",
                          pg_num: int = 8,
                          profile: Optional[Dict[str, str]] = None) -> int:
        return await self._client.create_pool(name, pool_type, pg_num,
                                              profile)

    async def pool_list(self) -> List[str]:
        await self._client.refresh_map()
        return sorted(p.name for p in self._client.osdmap.pools.values())

    async def config_set(self, key: str, value: str) -> None:
        await self._client.config_set(key, value)

    async def mon_command(self, prefix: str, **kwargs) -> Any:
        """Tiny `ceph` command surface over typed client calls."""
        if prefix == "osd pool ls":
            return await self.pool_list()
        if prefix == "config get":
            return await self._client.config_get(kwargs.get("key", ""))
        raise RadosError(f"unknown mon command {prefix!r}")
