"""BlueStore-lite: block-file object store with WAL, checksums, allocator.

Role-equivalent of the reference's BlueStore (reference
src/os/bluestore/BlueStore.cc): object data lives in one raw block file
carved by an extent allocator; all metadata (object -> extents, per-extent
crc32c checksums, shard meta, xattrs, omap) lives in a KeyValueDB whose WAL
provides the commit point — a transaction is durable exactly when its
metadata batch hits the KV WAL.  Small writes are DEFERRED
(bluestore_prefer_deferred_size): the data rides inside the KV record and
is flushed to the block file after commit, saving the block-file sync on
the latency path; large writes go to freshly allocated extents first
(copy-on-write — crash before KV commit leaves the old object intact),
then the metadata flips atomically.

Checksums: per-extent, algorithm selected by bluestore_csum_type
(crc32c default, zlib, none — reference csum_type per blob), verified
on every read BEFORE decompression; bluestore_debug_inject_read_err /
_csum_err_probability inject failures for the EIO-handling tests
(reference src/common/options/global.yaml.in:4977,5017).

Compression (reference BlueStore _do_write compression at blob
granularity): per-POOL mode/algorithm from pool opts (`ceph osd pool
set NAME compression_mode aggressive` -> pg_pool_t::opts -> OSDMap ->
set_pool_opts here), falling back to bluestore_compression_mode/
_algorithm conf.  zlib / zstd / lzma; a blob is stored compressed only
when >= bluestore_compression_min_blob_size and the result beats
bluestore_compression_required_ratio (default 0.875) — otherwise raw,
exactly the reference's required-ratio discipline.  Checksums cover
the STORED (compressed) bytes, so a corrupted compressed extent fails
the csum before the decompressor ever sees it.

Recovery contract: open() replays the KV WAL (WalDB does this), then
flushes any deferred writes recorded-but-not-flushed.  The allocator
rebuilds its free map from the extent metadata.
"""

from __future__ import annotations

import os
import pickle
import random
import zlib

from ceph_tpu.utils.checksum import checksum
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from ceph_tpu.rados.kv import KeyValueDB, MemDB, WalDB, WriteBatch
from ceph_tpu.rados.store import (ENOSPCError,  # noqa: F401 (re-export)
                                  Key, ObjectStore, ShardMeta, Transaction,
                                  unwrap as store_unwrap)

PREFIX_OBJ = "O"  # object metadata (extents, csums, ShardMeta, xattrs)
PREFIX_DEFERRED = "D"  # deferred write payloads awaiting block flush
PREFIX_OMAP = "M"  # per-object sorted key/value (PG log lives here)
PREFIX_SUPER = "S"  # store-wide state (size watermark)


class EIOError(IOError):
    """Read failed checksum / injected EIO (the OSD turns this into the
    shard-level error path the reference tests with test-erasure-eio.sh)."""


@dataclass
class _Onode:
    """Object metadata record (BlueStore onode role)."""

    extents: List[Tuple[int, int]] = field(default_factory=list)  # (off, len)
    csums: List[int] = field(default_factory=list)  # per-extent, of STORED bytes
    meta: ShardMeta = field(default_factory=ShardMeta)
    deferred: bool = False  # data still only in the KV (deferred write)
    xattrs: Dict[str, bytes] = field(default_factory=dict)
    # blob compression (reference bluestore_blob_t compressed flag):
    # algorithm name or None; raw_len pins the decompressed size
    compression: Optional[str] = None
    raw_len: int = -1
    csum_type: str = "crc32c"


# gated like auth.py's `cryptography` import: hosts without `zstandard`
# still run every non-zstd cluster shape — only the actual use of a
# zstd-compressed blob raises (writes degrade to raw with a warning at
# the caller; reads of an EXISTING zstd blob must raise, never return
# garbage)
try:
    import zstandard as _zstandard
except ImportError:
    _zstandard = None


def _require_zstd():
    if _zstandard is None:
        raise ImportError(
            "the `zstandard` package is required for zstd-compressed "
            "blobs but is not installed; pick compression_algorithm "
            "zlib/lzma or install zstandard")
    return _zstandard


def _compress(algo: str, raw) -> bytes:
    if algo == "zstd":
        return _require_zstd().ZstdCompressor(level=1).compress(bytes(raw))
    if algo == "lzma":
        import lzma

        return lzma.compress(bytes(raw), preset=0)
    return zlib.compress(bytes(raw), 1)


def _decompress(algo: str, data: bytes) -> bytes:
    if algo == "zstd":
        return _require_zstd().ZstdDecompressor().decompress(data)
    if algo == "lzma":
        import lzma

        return lzma.decompress(data)
    return zlib.decompress(data)


def _okey(key: Key) -> str:
    pid, oid, shard = key
    return f"{pid}/{oid.encode().hex()}/{shard}"


def _unokey(s: str) -> Key:
    pid, oid_hex, shard = s.split("/")
    return int(pid), bytes.fromhex(oid_hex).decode(), int(shard)


class Allocator:
    """Free-extent allocator (AvlAllocator role): first-fit with merge."""

    def __init__(self, size: int):
        self.size = size
        self.free: List[Tuple[int, int]] = [(0, size)] if size else []

    def allocate(self, want: int) -> int:
        for i, (off, length) in enumerate(self.free):
            if length >= want:
                if length == want:
                    self.free.pop(i)
                else:
                    self.free[i] = (off + want, length - want)
                return off
        # grow the device (file-backed: sparse growth is free); the grown
        # region beyond this allocation joins the free list
        off = self.size
        grow = max(want, 1 << 20)
        self.size += grow
        if grow > want:
            self.release(off + want, grow - want)
        return off

    def release(self, off: int, length: int) -> None:
        self.free.append((off, length))
        self.free.sort()
        merged: List[Tuple[int, int]] = []
        for o, l in self.free:
            if merged and merged[-1][0] + merged[-1][1] == o:
                merged[-1] = (merged[-1][0], merged[-1][1] + l)
            else:
                merged.append((o, l))
        self.free = merged

    def reserve(self, off: int, length: int) -> None:
        """Mark [off, off+len) used (startup rebuild)."""
        out = []
        for o, l in self.free:
            if off >= o + l or off + length <= o:
                out.append((o, l))
                continue
            if o < off:
                out.append((o, off - o))
            if off + length < o + l:
                out.append((off + length, o + l - off - length))
        self.free = out
        self.size = max(self.size, off + length)


class BlueStore(ObjectStore):
    def __init__(self, path: Optional[str] = None,
                 conf: Optional[dict] = None,
                 db: Optional[KeyValueDB] = None):
        self.conf = conf or {}
        self.path = path
        if path is not None:
            os.makedirs(path, exist_ok=True)
            self.db: KeyValueDB = db or WalDB(os.path.join(path, "db"))
            self._block_path = os.path.join(path, "block")
            if not os.path.exists(self._block_path):
                open(self._block_path, "wb").close()
            # r+b: positioned writes (a+b would append regardless of seek)
            self._block = open(self._block_path, "r+b")
        else:
            self.db = db or MemDB()
            self._block = None
            self._blob: Dict[int, bytes] = {}  # off -> data (RAM mode)
        self.alloc = Allocator(0)
        # configured byte ceiling + failsafe (reference bluestore
        # bluefs/statfs capacity + osd_failsafe_full_ratio): 0 = grow
        # forever (the pre-capacity behavior, default)
        self.capacity_bytes = int(self.conf.get(
            "osd_store_capacity_bytes", 0) or 0)
        self.failsafe_ratio = float(self.conf.get(
            "osd_failsafe_full_ratio", 0.97) or 0.97)
        self._onodes: Dict[Key, _Onode] = {}
        # per-pool store options pushed from the OSDMap (pg_pool_t::opts
        # role): compression_mode/algorithm/ratio/min_blob_size
        self.pool_opts: Dict[int, Dict[str, str]] = {}
        self._compress_warned: set = set()
        # committed-but-unflushed deferred writes, drained in batches off
        # the commit latency path (bluestore deferred_batch semantics)
        self._deferred_pending: List[Tuple[Key, _Onode, bytes]] = []
        self._deferred_batch_max = 16
        self._load()
        self._flush_deferred()

    # -- startup -------------------------------------------------------------

    def _load(self) -> None:
        for k, v in self.db.iterate(PREFIX_OBJ):
            onode: _Onode = pickle.loads(v)
            key = _unokey(k)
            self._onodes[key] = onode
            for off, length in onode.extents:
                self.alloc.reserve(off, length)

    def _flush_deferred(self) -> None:
        """Finish deferred writes that committed but weren't flushed to the
        block file before shutdown (BlueStore deferred replay)."""
        for k, v in list(self.db.iterate(PREFIX_DEFERRED)):
            key = _unokey(k)
            onode = self._onodes.get(key)
            if onode is not None and onode.deferred:
                self._write_extents(onode.extents, v)
                onode.deferred = False
                batch = WriteBatch()
                batch.set(PREFIX_OBJ, _okey(key),
                          pickle.dumps(onode, protocol=5))
                batch.rm(PREFIX_DEFERRED, k)
                self.db.submit(batch)
            else:
                batch = WriteBatch()
                batch.rm(PREFIX_DEFERRED, k)
                self.db.submit(batch)

    # -- block IO ------------------------------------------------------------

    def _write_extents(self, extents: List[Tuple[int, int]], data: bytes) -> None:
        pos = 0
        for off, length in extents:
            piece = data[pos:pos + length]
            if self._block is not None:
                self._block.seek(off)
                self._block.write(piece)
            else:
                self._blob[off] = piece
            pos += length
        if self._block is not None:
            self._block.flush()

    def _read_extents(self, extents: List[Tuple[int, int]]) -> bytes:
        out = []
        for off, length in extents:
            if self._block is not None:
                self._block.seek(off)
                out.append(self._block.read(length))
            else:
                out.append(self._blob.get(off, b"")[:length])
        return b"".join(out)

    # -- ObjectStore interface -----------------------------------------------

    def queue_transaction(self, txn: Transaction,
                          on_commit: Optional[Callable[[], None]] = None) -> None:
        """Apply atomically: ONE KV batch is the commit point for every
        write/delete in the transaction (ObjectStore::queue_transactions
        with register_on_commit semantics)."""
        prefer_deferred = int(self.conf.get("bluestore_prefer_deferred_size",
                                            32768) or 0)
        # failsafe BEFORE any mutation (KV batch, allocator, block file):
        # a refused transaction leaves the store byte-identical.  The
        # common no-ceiling config skips both sums (the free-list walk
        # would otherwise tax every write for a guaranteed no-op check).
        if self.capacity_bytes:
            self._check_failsafe(
                sum(len(store_unwrap(c)) for _k, c, _m in txn.writes),
                self.alloc.size - sum(l for _, l in self.alloc.free))
        batch = WriteBatch()
        freed: List[Tuple[int, int]] = []
        for key in txn.deletes:
            onode = self._onodes.pop(key, None)
            if onode is not None:
                freed.extend(onode.extents)
            batch.rm(PREFIX_OBJ, _okey(key))
            batch.rm(PREFIX_DEFERRED, _okey(key))
            batch.rm_prefix(PREFIX_OMAP + _okey(key))
        for key, entries in txn.omap_sets:
            for k, v in entries.items():
                batch.set(PREFIX_OMAP + _okey(key), k, v)
        for key, keys in txn.omap_rms:
            for k in keys:
                batch.rm(PREFIX_OMAP + _okey(key), k)
        deferred_flush: List[Tuple[Key, _Onode, bytes]] = []
        for key, chunk, meta in txn.writes:
            chunk = store_unwrap(chunk)  # disk store copies to media anyway
            old = self._onodes.get(key)
            if old is not None:
                freed.extend(old.extents)
            onode = _Onode(meta=meta,
                           xattrs=dict(old.xattrs) if old else {})
            # blob compression decision (reference _do_write + the
            # required-ratio gate): per-pool opts override global conf
            raw_len = len(chunk)
            popts = self.pool_opts.get(key[0], {})
            mode = popts.get("compression_mode",
                             self.conf.get("bluestore_compression_mode",
                                           "none")) or "none"
            # passive = compress only on a client compressible-hint
            # (reference alloc-hint plumbing); no hints exist in this
            # transaction format, so passive stores raw — treating it
            # as aggressive would invert its documented meaning
            if mode in ("aggressive", "force"):
                algo = popts.get(
                    "compression_algorithm",
                    self.conf.get("bluestore_compression_algorithm",
                                  "zlib"))
                min_blob = int(popts.get(
                    "compression_min_blob_size",
                    self.conf.get("bluestore_compression_min_blob_size",
                                  4096)))
                ratio = float(popts.get(
                    "compression_required_ratio",
                    self.conf.get("bluestore_compression_required_ratio",
                                  0.875)))
                if raw_len >= min_blob:
                    try:
                        cand = _compress(algo, chunk)
                    except Exception as e:
                        cand = None
                        # loudly, once per (pool, algo): a missing
                        # compressor module must not silently store a
                        # "compressed" pool raw forever
                        warn_key = (key[0], algo)
                        if warn_key not in self._compress_warned:
                            self._compress_warned.add(warn_key)
                            print(f"bluestore: pool {key[0]} "
                                  f"compression_algorithm={algo} "
                                  f"unavailable ({e}); storing raw")
                    if cand is not None and len(cand) <= raw_len * ratio:
                        chunk = cand
                        onode.compression = algo
                        onode.raw_len = raw_len
            onode.csum_type = str(self.conf.get("bluestore_csum_type",
                                                "crc32c") or "crc32c")
            off = self.alloc.allocate(max(1, len(chunk)))
            onode.extents = [(off, len(chunk))]
            onode.csums = [self._csum(onode.csum_type, chunk)]
            if len(chunk) <= prefer_deferred:
                # deferred: payload rides the KV WAL (pickled) — needs
                # real bytes, a memoryview cannot serialize
                if not isinstance(chunk, bytes):
                    chunk = bytes(chunk)
                onode.deferred = True
                batch.set(PREFIX_DEFERRED, _okey(key), chunk)
                deferred_flush.append((key, onode, chunk))
            else:
                # large write: data to fresh extents BEFORE commit (COW)
                self._write_extents(onode.extents, chunk)
            self._onodes[key] = onode
            batch.set(PREFIX_OBJ, _okey(key), pickle.dumps(onode, protocol=5))
        self.db.submit(batch)  # <- THE commit point
        if on_commit is not None:
            on_commit()
        # post-commit: deferred payloads drain in batches so a small write
        # costs ONE fsync on the latency path (the open-time replay covers
        # anything pending at a crash)
        self._deferred_pending.extend(deferred_flush)
        if len(self._deferred_pending) >= self._deferred_batch_max:
            self.flush_deferred_batch()
        for off, length in freed:
            self.alloc.release(off, length)

    def flush_deferred_batch(self) -> None:
        if not self._deferred_pending:
            return
        pending, self._deferred_pending = self._deferred_pending, []
        b2 = WriteBatch()
        for key, onode, chunk in pending:
            if self._onodes.get(key) is not onode:
                continue  # overwritten/deleted since; its extents are gone
            self._write_extents(onode.extents, chunk)
            onode.deferred = False
            b2.set(PREFIX_OBJ, _okey(key), pickle.dumps(onode, protocol=5))
            b2.rm(PREFIX_DEFERRED, _okey(key))
        if b2.ops:
            self.db.submit(b2)

    @staticmethod
    def _csum(ctype: str, data) -> int:
        if ctype == "none":
            return 0
        if ctype == "zlib":
            return zlib.crc32(bytes(data)) & 0xFFFFFFFF
        return checksum(data) & 0xFFFFFFFF

    def set_pool_opts(self, pool_id: int, opts: Dict[str, str]) -> None:
        """OSDMap pool-opts push (pg_pool_t::opts role)."""
        if opts:
            self.pool_opts[pool_id] = dict(opts)
        else:
            self.pool_opts.pop(pool_id, None)

    def read(self, key: Key) -> Optional[Tuple[bytes, ShardMeta]]:
        onode = self._onodes.get(key)
        if onode is None:
            return None
        if self.conf.get("bluestore_debug_inject_read_err", False):
            raise EIOError(f"injected read error on {key}")
        if onode.deferred:
            data = self.db.get(PREFIX_DEFERRED, _okey(key)) or b""
        else:
            data = self._read_extents(onode.extents)
        prob = float(self.conf.get(
            "bluestore_debug_inject_csum_err_probability", 0.0) or 0.0)
        if prob and random.random() < prob:
            raise EIOError(f"injected csum error on {key}")
        # verify BEFORE decompression, over the stored bytes: a
        # corrupted compressed extent must fail here, never feed the
        # decompressor garbage (pre-selection onode pickles lack the
        # csum_type field; verify_any keeps them readable)
        if getattr(onode, "csum_type", "crc32c") != "none":
            pos = 0
            for (off, length), want in zip(onode.extents, onode.csums):
                from ceph_tpu.utils.checksum import verify_any

                if not verify_any(data[pos:pos + length], want):
                    raise EIOError(f"checksum mismatch on {key} @{off}")
                pos += length
        comp = getattr(onode, "compression", None)
        if comp:
            try:
                data = _decompress(comp, data)
            except Exception as e:
                raise EIOError(
                    f"decompression failed on {key} ({comp}): {e}")
            raw_len = getattr(onode, "raw_len", -1)
            if raw_len >= 0 and len(data) != raw_len:
                raise EIOError(
                    f"decompressed length mismatch on {key}: "
                    f"{len(data)} != {raw_len}")
        return data, onode.meta

    def list_objects(self, pool_id: int) -> Iterable[Tuple[str, int]]:
        for (pid, oid, shard) in list(self._onodes):
            if pid == pool_id:
                yield oid, shard

    def list_pools(self) -> Iterable[int]:
        return sorted({pid for (pid, _o, _s) in self._onodes})

    # -- xattrs / omap (HashInfo + PG log substrate) -------------------------

    def setattr(self, key: Key, name: str, value: bytes) -> None:
        onode = self._onodes.get(key)
        if onode is None:
            onode = _Onode()
            self._onodes[key] = onode
        onode.xattrs[name] = value
        batch = WriteBatch()
        batch.set(PREFIX_OBJ, _okey(key), pickle.dumps(onode, protocol=5))
        self.db.submit(batch)

    def getattr(self, key: Key, name: str) -> Optional[bytes]:
        onode = self._onodes.get(key)
        return onode.xattrs.get(name) if onode else None

    def rmattr(self, key: Key, name: str) -> None:
        onode = self._onodes.get(key)
        if onode is None or name not in onode.xattrs:
            return
        del onode.xattrs[name]
        batch = WriteBatch()
        batch.set(PREFIX_OBJ, _okey(key), pickle.dumps(onode, protocol=5))
        self.db.submit(batch)

    def getattrs(self, key: Key) -> Dict[str, bytes]:
        onode = self._onodes.get(key)
        return dict(onode.xattrs) if onode else {}

    def omap_set(self, key: Key, entries: Dict[str, bytes]) -> None:
        batch = WriteBatch()
        for k, v in entries.items():
            batch.set(PREFIX_OMAP + _okey(key), k, v)
        self.db.submit(batch)

    def omap_get(self, key: Key) -> Dict[str, bytes]:
        return dict(self.db.iterate(PREFIX_OMAP + _okey(key)))

    def omap_rm(self, key: Key, keys: List[str]) -> None:
        batch = WriteBatch()
        for k in keys:
            batch.rm(PREFIX_OMAP + _okey(key), k)
        self.db.submit(batch)

    # -- admin ----------------------------------------------------------------

    def statfs(self) -> Dict[str, int]:
        free = sum(l for _, l in self.alloc.free)
        used = self.alloc.size - free
        total = int(self.capacity_bytes or 0)
        # uniform shape first (total/used/avail, total==0 = unlimited);
        # size/free kept for the allocator-view consumers
        return {"total": total, "used": used,
                "avail": max(0, total - used) if total else 0,
                "num_objects": len(self._onodes),
                "size": self.alloc.size, "free": free}

    def close(self) -> None:
        self.flush_deferred_batch()
        self.db.close()
        if self._block is not None:
            self._block.close()
