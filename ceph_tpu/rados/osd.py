"""OSD daemon: the EC data plane.

Role-equivalent of the reference's OSD + ECBackend (reference
src/osd/OSD.cc, src/osd/ECBackend.cc): boots against the mon, heartbeats,
and for PGs where it is primary drives the EC pipeline in the reference's
order — submit -> write plan -> encode -> per-shard fan-out -> commit
gather -> client ack (ECBackend.cc:1525 -> 1889 -> 1989 -> 2159) — with the
TPU twist that encode/decode ride the pool codec's device dispatch (and the
codec's batching, plugin=tpu).  Degraded reads reconstruct transparently
(objects_read_and_reconstruct, ECBackend.cc:2401); recovery re-creates
missing shards on the current acting set and pushes them (RecoveryOp
IDLE->READING->WRITING, ECBackend.cc:590-745).

Client and sub-ops ride a sharded op queue (op_shardedwq, OSD.h:1590) with
a pluggable WPQ/mClock scheduler (osd_op_queue); PG id pins an op to a
shard so per-PG ordering holds.  Liveness is two-tier like the reference:
OSD<->OSD heartbeats (OSD::heartbeat OSD.cc:5837, handle_osd_ping :5417)
produce MOSDFailure reports to the mon when a peer misses its grace, and
the mon's own laggard scan is the fallback.  Per-daemon observability:
perf counters, TrackedOp timelines, and an optional admin socket
(`status`, `perf dump`, `dump_ops_in_flight`).

Write path bookkeeping matches the reference's shape: every mutation
appends a PG log entry (src/osd/PGLog.cc) on each acting shard in the same
store transaction as the data; client resends dedupe against the log's
reqid set; recovery is two-phase — log-driven delta recovery for peers
whose logs overlap, backfill scan otherwise.  Partial overwrites take the
read-modify-write path with a primary-side extent cache
(try_state_to_reads + ExtentCache roles); deep scrub recomputes shard crcs
against stored meta and repairs mismatches (be_deep_scrub).
"""

from __future__ import annotations

import asyncio
import contextlib
import errno
import json
import os
import pickle
import random
import threading
import time
import uuid
import zlib
from collections import deque
from typing import Any, Dict, List, Optional, Set, Tuple

import numpy as np

from ceph_tpu.common.context import Context
from ceph_tpu.common.perf_counters import PerfCountersBuilder
from ceph_tpu.ec.interface import ErasureCodeError
from ceph_tpu.ec.registry import registry
from ceph_tpu.rados.crush import CRUSH_ITEM_NONE
from ceph_tpu.rados.extent_cache import ExtentCache
from ceph_tpu.utils.checksum import verify_any as crc_verify_any
from ceph_tpu.rados.ecutil import (HashInfo, StripeInfo,
                                   batched_encode_async,
                                   batched_encode_group_async,
                                   decode_object_async,
                                   planar_eligible, planar_encode_async,
                                   planar_object_bytes, planar_rows,
                                   planar_shard_bytes)
from ceph_tpu.rados.clog import (LogClient, build_crash_report,
                                 replay_crash_spool, spool_crash)
from ceph_tpu.rados.messenger import (TRANSPORT_ERRORS, BufferList,
                                      Messenger, as_bytes)
from ceph_tpu.rados.monclient import MonTargets
from ceph_tpu.rados.peering import (
    ACTIVE,
    BACKFILLING,
    CLEAN,
    GET_INFO,
    GET_LOG,
    GET_MISSING,
    RECOVERING,
    WAIT_LOCAL_RESERVE,
    WAIT_REMOTE_RESERVE,
    PGMachine,
    ReservationSlots,
)
from ceph_tpu.rados.pagestore import CacheDirtyRecord
from ceph_tpu.rados.pglog import ZERO, LogEntry, PGLog, pack_eversion
from ceph_tpu.rados.qos import (QosParams, QosTracker, build_scheduler_perf,
                                pool_qos, primary_spread, qos_op_cost,
                                tenant_class)
from ceph_tpu.rados.scheduler import (
    CLASS_BEST_EFFORT,
    CLASS_CLIENT,
    CLASS_FLUSH,
    CLASS_REBALANCE,
    CLASS_RECOVERY,
    CLASS_SCRUB,
    ShardedOpQueue,
)
from ceph_tpu.rados.store import (ENOSPCError, MemStore, ObjectStore,
                                  ShardMeta, Transaction, shard_crc,
                                  Owned as StoreOwned)
from ceph_tpu.rados.tiering import (HitSetArchive, PromoteThrottle,
                                    build_tier_perf, eviction_candidates)
from ceph_tpu.rados.auth import TicketKeyring
from ceph_tpu.rados.types import (
    MAuthRotating,
    MAuthRotatingReply,
    MAuthTicket,
    MAuthTicketReply,
    MBackfillReserve,
    MBackfillReserveReply,
    MCacheDirty,
    MCacheDirtyAck,
    MCommand,
    MCommandReply,
    MCrashReportAck,
    MECSubRollback,
    MBootReply,
    MGetMap,
    MLogAck,
    MECSubDelete,
    MECSubRead,
    MECSubReadReply,
    MECSubWrite,
    MECSubWriteReply,
    MFetchShards,
    MFetchShardsReply,
    MListShards,
    MListShardsReply,
    MMapReply,
    MOSDFailure,
    MOSDOp,
    MOSDOpReply,
    MOSDBackoff,
    MOSDPGHitSet,
    MOSDPGTemp,
    MOSDPing,
    MOsdBoot,
    MPGInfoReply,
    MPGInfoReq,
    MPGLogReply,
    MPGLogReq,
    MPing,
    FULL_SEVERITY,
    is_delete_only_multi,
    is_read_only_multi,
    MPushShard,
    MNotifyAck,
    MScrubShard,
    MScrubShardReply,
    MSetOmap,
    MSetXattrs,
    MWatchNotify,
    OSDMap,
    PoolInfo,
    osd_crush_weight,
    ALL_NSPACES,
    is_snap_clone,
    snap_clone_oid,
    snap_head,
    split_ns,
)


def _ns_match(oid: str, nspace: str) -> bool:
    """Listing namespace filter (reference pgnls oloc nspace): "" means
    the DEFAULT namespace only; the ALL_NSPACES sentinel matches
    everything."""
    return nspace == ALL_NSPACES or split_ns(oid)[0] == nspace


PGMETA_PREFIX = "__pgmeta_"  # per-PG metadata object carrying the PG log

# rollback slot: each shard keeps its PREVIOUS version at shard+PREV_SLOT
# (the reference retains old extents as rollback info in the EC
# transaction, ECBackend rollback_append/ECTransaction) so a failed
# overwrite that lands on some shards cannot destroy the last complete
# version of the object
PREV_SLOT = 1 << 20

# ONE stripe-batching queue per process, shared by every OSD instance in
# it: the device is a process-level resource, and cross-daemon coalescing
# (a vstart cluster runs many OSDs in one process) only helps — more
# concurrent stripes per dispatch.  Lazy: processes that never touch an
# EC pool never start the worker thread.
_BATCH_QUEUE = None
_BATCH_QUEUE_LOCK = threading.Lock()


def shared_batching_queue():
    """The process queue, or None when batching through the device would
    LOSE: on a CPU-only backend the codecs' numpy table paths beat a
    JAX round-trip (and its per-shape compiles), so the queue engages
    only when an accelerator is actually the default backend.
    CEPH_TPU_FORCE_BATCH=1 overrides (tests exercising coalescing on the
    CPU backend; perf experiments)."""
    global _BATCH_QUEUE
    import os as _os

    if _os.environ.get("CEPH_TPU_FORCE_BATCH") != "1":
        # an EXPLICIT JAX_PLATFORMS=cpu is an operator decision (tests,
        # CPU-only deployments) and wins outright — on some hosts a
        # sitecustomize-registered accelerator plugin overrides the
        # platform selection, so the probe would still report the
        # accelerator and silently route every EC op through it
        if _os.environ.get("JAX_PLATFORMS", "") == "cpu":
            return None
        from ceph_tpu.utils.jaxdev import probe_backend

        if probe_backend() in ("cpu", "unavailable"):
            return None
    with _BATCH_QUEUE_LOCK:
        if _BATCH_QUEUE is None:
            from ceph_tpu.parallel.service import BatchingQueue

            _BATCH_QUEUE = BatchingQueue()
        return _BATCH_QUEUE


_PLANAR_STORE = None


def shared_planar_store(capacity_bytes: int = 0, page_bytes: int = 0,
                        paged: Optional[bool] = None,
                        device: Optional[bool] = None,
                        prewarm: bool = False):
    """The process-wide resident store behind the cache tier.  Engages
    under the same conditions as the batching queue — an accelerator
    backend (or CEPH_TPU_FORCE_BATCH=1 for CPU tests); None otherwise.
    All in-process OSDs share one HBM budget; keys are namespaced per
    OSD.

    Two flavors behind one surface (the residency protocol:
    put_planar/touch/gather_rows/drop/memo): the PAGED store
    (ceph_tpu/rados/pagestore.py — page table, ragged tails, per-page
    dirty bits; the default, and the only flavor that can run
    writeback) and the r10 monolithic PlanarShardStore
    (osd_tier_pagestore=false or CEPH_TPU_PAGESTORE=0 — the bench A/B
    arm).  The FIRST creator decides the flavor for the process; later
    callers only ever raise the shared byte budget.

    ``device`` gates the paged store's DEVICE arm (jax.Array sub-slabs,
    jitted installs/gathers — ceph_tpu/ops/slab.py): None = auto
    (device arm iff a real backend is live), False = pinned host arm
    (osd_tier_device_slab=false); CEPH_TPU_DEVICE_SLAB=1/0 overrides
    either way inside the store."""
    global _PLANAR_STORE
    queue = shared_batching_queue()
    if queue is None:
        return None
    with _BATCH_QUEUE_LOCK:
        if _PLANAR_STORE is None:
            use_paged = True if paged is None else bool(paged)
            if os.environ.get("CEPH_TPU_PAGESTORE", "") == "0":
                use_paged = False
            if use_paged:
                from ceph_tpu.rados.pagestore import PagedResidentStore

                _PLANAR_STORE = PagedResidentStore(
                    capacity_bytes=capacity_bytes or (256 << 20),
                    page_bytes=page_bytes or (64 << 10), queue=queue,
                    device=device, prewarm=prewarm)
            else:
                from ceph_tpu.parallel.service import PlanarShardStore

                _PLANAR_STORE = PlanarShardStore(
                    capacity_bytes=capacity_bytes or (256 << 20),
                    queue=queue)
        elif capacity_bytes and capacity_bytes > _PLANAR_STORE.capacity_bytes:
            # the budget is one shared HBM pool: any daemon asking for
            # more raises it (first-wins would silently drop the knob)
            _PLANAR_STORE.capacity_bytes = capacity_bytes
        return _PLANAR_STORE


class OSD:
    def __init__(
        self,
        mon_addr: Tuple[str, int],
        store: Optional[ObjectStore] = None,
        conf: Optional[dict] = None,
        osd_id: int = -1,
    ):
        self.conf = conf or {}
        # one mon addr or a monmap list; RPCs rotate on mon failure
        self.mons = MonTargets(mon_addr)
        self.store = store or MemStore()
        self.osd_id = osd_id
        self.messenger = Messenger(f"osd.{osd_id}", self.conf, entity_type="osd")
        self.osdmap: Optional[OSDMap] = None
        self._codecs: Dict[int, object] = {}
        self._sinfos: Dict[int, StripeInfo] = {}
        self._pending: Dict[str, asyncio.Future] = {}
        self._collectors: Dict[str, asyncio.Queue] = {}
        self._ping_task: Optional[asyncio.Task] = None
        self._hb_task: Optional[asyncio.Task] = None
        self._repair_task: Optional[asyncio.Task] = None
        # metadata-replication retry queue (per peer, FIFO — ordering
        # matters: an omap clear+set sequence applied out of order is a
        # different omap).  A transient send failure must NOT leave a
        # replica permanently stale: RGW bucket indexes and cls lock
        # state ride this path, and a failover primary would serve the
        # stale copy.
        self._meta_repl_pending: Dict[int, deque] = {}
        self._meta_repl_task: Optional[asyncio.Task] = None
        self.addr: Optional[Tuple[str, int]] = None
        self._stopped = False
        # observability (CephContext role): perf counters + op tracker;
        # the admin socket starts only when admin_socket_dir is configured
        self.ctx = Context(f"osd.{osd_id}",
                           conf if isinstance(conf, dict) else None)
        # the messenger's douts ride this daemon's log (debug_ms levels,
        # runtime-mutable via asok/`ceph tell` config set)
        self.messenger.log = self.ctx.log
        # cluster-log client (LogClient role): clog.info/warn/error land
        # in the mon's paxos-replicated cluster log; renamed + started
        # once the boot reply fixes our id
        self.clog = LogClient(self.messenger, self.mons, f"osd.{osd_id}",
                              self.conf, local_log=self.ctx.log)
        # crash telemetry: reports spool here when the mon is
        # unreachable (replayed at next boot); the dev inject flag makes
        # the next ping tick die — the crash-plane CI gate's trigger
        self._crash_dir = str(self.conf.get("crash_dir", "") or "")
        self._inject_crash = bool(
            self.conf.get("osd_debug_inject_crash", False))
        self._fatal_task: Optional[asyncio.Task] = None
        # stamp trace-id/parent-span context onto outbound data-plane
        # messages (cross-daemon stitching); decode always tolerates
        # absent fields, so this only gates the SENDING side
        self._trace_on = bool(self.conf.get("ms_trace_propagation", True))
        self.perf = self.ctx.perf.add(
            PerfCountersBuilder("osd")
            .add_u64_counter("op", "client ops")
            .add_u64_counter("op_w", "client writes")
            .add_u64_counter("op_r", "client reads")
            .add_time_avg("op_lat", "client op latency")
            .add_u64_counter("subop_w", "EC sub-writes applied")
            .add_u64_counter("subop_r", "EC sub-reads served")
            .add_u64_counter("pools_purged",
                             "deleted pools locally purged")
            .add_u64_counter("rmw_partial", "stripe-scoped partial overwrites")
            .add_u64_counter("rmw_extent_hits",
                             "RMW reads served from the extent cache")
            .add_u64_counter("planar_read_hits",
                             "reads served from planar HBM residents "
                             "with zero shard reads")
            .add_u64_counter("rmw_read_bytes", "bytes read for stripe RMW")
            .add_u64_counter("recovery_subchunk_bytes",
                             "helper bytes read by sub-chunk repair")
            .add_u64_counter("recovery_push", "recovery shards pushed")
            .add_u64_counter("stray_purged", "stray shards purged after backfill")
            .add_u64_counter("unfound_reverted",
                             "shards reverted to rollback slots (unfound)")
            .add_u64_counter("recovery_errors", "repair rounds that errored")
            .add_u64_counter("op_queued", "ops entering the sharded queue")
            .add_u64_counter("op_dequeued", "ops drained")
            .add_time_avg("op_queue_lat", "op service time")
            .add_u64_counter("heartbeat_failures", "peer failures reported")
            .add_u64_counter("backoffs_sent",
                             "MOSDBackoff blocks sent (op dropped, client "
                             "parks until release)")
            .add_u64_counter("backoffs_released",
                             "MOSDBackoff unblocks sent")
            .add_u64_counter("meta_repl_dropped",
                             "metadata replications dropped on queue "
                             "overflow (replica stale until scrub)")
            .add_u64_counter("op_unexpected_error",
                             "ops failed by an unclassified exception")
            .add_u64_counter("full_rejects",
                             "writes refused typed ENOSPC (FULL acting "
                             "member or local failsafe)")
            .add_u64_counter("backfill_toofull_refusals",
                             "backfill reservations refused because this "
                             "OSD is past its backfillfull ratio")
            .add_u64_counter("backfill_bytes_moved",
                             "shard bytes pushed by backfill/recovery "
                             "sweeps this OSD led")
            .add_u64_counter("rebalance_push",
                             "shards pushed by pure REBALANCE sweeps "
                             "(membership/weight change, no redundancy "
                             "loss)")
            .add_u64_counter("rebalance_bytes_moved",
                             "shard bytes moved by pure rebalance sweeps "
                             "(the bench arm's MB/s-moved numerator)")
            .add_u64_counter("scrub_errors_found",
                             "shard mismatches found by deep scrub "
                             "(crc/hinfo/absence)")
            .add_u64_counter("scrub_repaired",
                             "scrub-found shards repaired by re-encode "
                             "+ push")
            .add_u64("ec_batch_ops",
                     "requests submitted to the shared queue (gauge)")
            .add_u64("ec_batch_dispatches",
                     "device dispatches issued by the shared queue (gauge)")
            .add_u64("ec_batch_bytes",
                     "bytes pushed through the shared queue (gauge)")
            .create_perf_counters()
        )
        # the `osd_scheduler` set: per-class queue flow, the dmClock
        # serving split, and the QoS shed counter — one set per daemon
        # (the queue's shards share it), riding perf dump -> mgr /metrics
        self.sched_perf = self.ctx.perf.add(build_scheduler_perf())
        self.op_queue = ShardedOpQueue(
            int(self.conf.get("osd_op_num_shards", 4) or 4), self.conf,
            perf=self.perf, sched_perf=self.sched_perf)
        # OSD-level per-client admission tracker (qos.QosTracker): sees
        # every arriving client data op at FULL offered rate (per-shard
        # scheduler states each see ~1/n_shards), so the saturation shed
        # can name the most over-limit client
        self.qos = QosTracker(
            int(self.conf.get("osd_qos_max_clients", 4096) or 4096),
            arrears_cap=float(
                self.conf.get("osd_qos_arrears_cap", 2.0) or 2.0))
        # OSD<->OSD heartbeat state (two-tier failure detection);
        # _hb_reported maps peer -> last MOSDFailure stamp so reports
        # re-send while the peer stays silent (evidence at the mon expires)
        self._hb_last: Dict[int, float] = {}
        self._hb_reported: Dict[int, float] = {}
        # per-PG logs (src/osd/PGLog.cc role), lazily loaded from omap
        self._pglogs: Dict[Tuple[int, int], PGLog] = {}
        # reqids whose write failed min_size: a resend must RE-EXECUTE,
        # not be acked as a dup
        self._failed_writes: Set[str] = set()
        # class-call results by reqid (non-idempotent methods must not
        # re-execute on a resend); notify resends arriving while the first
        # execution is still gathering await its future
        self._call_results: Dict[str, MOSDOpReply] = {}
        self._notify_inflight: Dict[str, asyncio.Future] = {}
        # per-object critical sections for in-OSD class calls (the
        # ClassHandler PG-lock role; see _do_call): (pool, oid) ->
        # [lock, refcount] — refcounted so eviction can never orphan a
        # lock some waiter still holds a reference to
        self._cls_locks: Dict[Tuple[int, str], list] = {}
        # (pool, oid) -> {watcher addr} (reference Watch registry; watchers
        # re-register after a primary change, as librados clients do)
        self._watchers: Dict[Tuple[int, str], Set[Tuple[str, int]]] = {}
        # primary-side cache of decoded objects pinned across RMW rounds
        # (src/osd/ExtentCache.{h,cc} role)
        self._extent_cache = ExtentCache(max_objects=64)
        # acting set of the last DIFFERENT interval per PG: the set a
        # pg_temp request points the mon at when a remapped PG needs
        # backfill (the data lives with the prior interval's members)
        self._prior_acting: Dict[Tuple[int, int], List[int]] = {}
        # peering statecharts for PGs this OSD leads (reference
        # PeeringState machine per PG) + reservation throttles bounding
        # concurrent recovery (reference local/remote AsyncReserver,
        # osd_max_backfills) + per-PG membership history since the PG was
        # last clean (past_intervals role: the OSDs that may hold shards,
        # the scope set for deletes/hunts/backfill instead of O(cluster)
        # broadcasts)
        self._pg_machines: Dict[Tuple[int, int], PGMachine] = {}
        # default 4 (reference defaults to 1, but its recovery pipeline is
        # object-granular and overlaps with IO; our per-PG sweep is
        # coarser, so a 1-slot default starves replenishment under churn)
        max_backfills = int(self.conf.get("osd_max_backfills", 4) or 1)
        self._local_reserver = ReservationSlots(max_backfills)
        self._remote_reserver = ReservationSlots(max_backfills)
        self._past_members: Dict[Tuple[int, int], Set[int]] = {}
        # (oid, version) pairs observed partial-above-newest-complete in a
        # COMPLETE listing, per PG: confirmed again next pass => revert
        # (pool, pg) -> {(oid, version): first_seen_monotonic} for versions
        # newer than the newest complete one (unfound-revert grace clock)
        self._partial_newer: Dict[Tuple[int, int], Dict[Tuple[str, int], float]] = {}
        # (pool, pg) -> last self-scheduled deep-scrub time (monotonic);
        # the scrub scheduler picks the oldest-due PG each tick
        self._last_scrub: Dict[Tuple[int, int], float] = {}
        self._last_scrub_scan = 0.0
        self._scrub_task: Optional[asyncio.Task] = None
        # scrub-found inconsistency per PG this OSD leads: (pool, pg) ->
        # {"errors", "repaired", "stamp"} for the most recent scrub pass
        # that found mismatches.  Rides the MPing health field as
        # OSD_SCRUB_ERRORS / PG_INCONSISTENT; CLEARED when a later
        # scrub/repair pass of the PG verifies zero mismatches (repair
        # confirmed — the raise/clear lifecycle `ceph pg repair` drives).
        self._scrub_errors: Dict[Tuple[int, int], Dict[str, float]] = {}
        # (epoch, {pool_id: distinct primaries}) memo for the cross-OSD
        # QoS normalization divisor (qos.primary_spread): O(pg_num)
        # CRUSH work, recomputed only when the map moves
        self._spread_memo: Tuple[int, Dict[int, int]] = (-1, {})
        # active MOSDBackoff blocks this primary holds on clients:
        # (pool, pg) -> {"id": block id, "conns": {id(conn): conn}} —
        # released (unblock sent to every registered conn) when the PG's
        # peering pass reaches Active, or when we stop being primary
        self._backoffs_sent: Dict[Tuple[int, int], Dict] = {}
        # the process-wide stripe-batching queue (None = batching off):
        # every EC encode/decode this daemon issues is submitted here so
        # CONCURRENT ops coalesce into one device dispatch (SURVEY.md
        # §7.5; the reference's per-stripe ECUtil::encode loop inverted
        # at process scope)
        self._ec_queue = (shared_batching_queue()
                          if self.conf.get("osd_ec_batching", True) else None)
        if self._ec_queue is not None:
            # device-dispatch watchdog knobs (BatchingQueue circuit
            # breaker): a configured timeout/injected delay applies to
            # the PROCESS queue — last writer wins, matching the queue's
            # process-shared nature
            t = float(self.conf.get("osd_ec_dispatch_timeout", 0) or 0)
            if t:
                self._ec_queue.dispatch_timeout = t
            d = float(self.conf.get(
                "osd_debug_inject_dispatch_delay", 0) or 0)
            if d:
                self._ec_queue.inject_dispatch_delay = d
        # bit-planar HBM residency (VERDICT r03 #1): full-object EC
        # writes leave their shard rows planar-resident on the device, so
        # later decodes, repair re-encodes, and recovery packs are
        # matmul-only (or pack-only) instead of re-unpacking — the
        # pack/unpack boundary is paid once per resident lifetime
        self._planar = (
            shared_planar_store(
                int(self.conf.get("osd_ec_planar_bytes", 0) or 0),
                page_bytes=int(
                    self.conf.get("osd_tier_page_bytes", 64 << 10) or 0),
                paged=bool(self.conf.get("osd_tier_pagestore", True)),
                # None = auto (device arm iff a real backend is live);
                # an explicit false config pins the host arm
                device=(None if self.conf.get("osd_tier_device_slab",
                                              True) else False),
                prewarm=bool(self.conf.get("osd_tier_slab_prewarm", True)))
            if self.conf.get("osd_ec_planar_residency", True) else None)
        # cache-tier policy state (ceph_tpu/rados/tiering.py): per-PG
        # bloom hit-set archives, the promotion rate throttle, and the
        # best-effort tier agent that makes HBM residency a POLICY —
        # hot objects are promoted into the planar store, cold residents
        # evicted coldest-temperature-first.  Hit recording runs even
        # without a device (temperatures are cheap and feed `tier
        # status`); promotion/eviction engage only when _planar exists.
        self._hit_sets: Dict[Tuple[int, int], HitSetArchive] = {}
        # per-PG epoch of the last ACCEPTED archive push (fencing:
        # cross-sender delivery has no wire ordering, see
        # _handle_pg_hit_set)
        self._hit_set_epochs: Dict[Tuple[int, int], int] = {}
        self._promote_throttle = PromoteThrottle(
            float(self.conf.get("osd_tier_promote_max_objects_sec", 32)
                  or 0),
            float(self.conf.get("osd_tier_promote_max_bytes_sec", 64 << 20)
                  or 0))
        self.tier_perf = self.ctx.perf.add(build_tier_perf())
        self._tier_agent_busy = False
        self._last_tier_scan = 0.0
        # promotions in flight, keyed by planar key: N hot reads racing
        # before the first install must fund ONE encode, not N
        self._promoting: Set[Tuple[int, int, str]] = set()
        # fast-ack raw destage single-flight: a key being flushed by
        # one plane (agent / fence / recovery replay) must not be
        # re-encoded concurrently by another
        self._raw_flush_inflight: Set[Tuple[int, int, str]] = set()
        # EC data-plane observability: ONE `perf dump` on this daemon
        # carries the whole pipeline breakdown — the messenger's `wire`
        # set (framing vs socket io), the shared queue's `ec_tpu` set
        # (per-lane submits/bytes, queue-wait/dispatch latencies, flush
        # causes), the gf2 `gf2_sched` schedule-cache set, the tpu
        # plugin's `ec_plugin` seam set (device dispatches vs CPU
        # fallbacks — the non-queue path), and the planar store's
        # `planar_store` residency set.  The queue/store/sched/plugin
        # sets are process-shared (as the resources are); every
        # colocated OSD dumps the same numbers.
        self.ctx.perf.add(self.messenger.perf)
        from ceph_tpu.ops.gf2 import SCHED_PERF

        self.ctx.perf.add(SCHED_PERF)
        try:
            from ceph_tpu.ec.plugins.tpu import PLUGIN_PERF

            self.ctx.perf.add(PLUGIN_PERF)
        except ImportError:  # plugin tier absent: nothing to count
            pass
        if self._ec_queue is not None:
            self.ctx.perf.add(self._ec_queue.perf)
            if self._ec_queue.tracer is None:
                # dispatch spans with no submitter parent (repair/bench
                # traffic) root in this daemon's trace ring
                self._ec_queue.tracer = self.ctx.tracer
        if self._planar is not None:
            self.ctx.perf.add(self._planar.perf)

    # -- lifecycle -----------------------------------------------------------

    async def start(self) -> int:
        self.messenger.dispatcher = self._dispatch
        self.messenger.group_dispatcher = self._dispatch_group
        self.addr = await self.messenger.bind()
        boot = MOsdBoot(osd_id=self.osd_id, addr=self.addr)
        # a no-quorum window answers boot with osd_id=-1: retry, don't run
        # as a ghost daemon the mon will never recognize
        for attempt in range(8):
            reply = await self._mon_rpc(boot, MBootReply)
            if reply.osd_id >= 0:
                break
            self.mons.rotate()
            await asyncio.sleep(0.25 * (attempt + 1))
        else:
            raise RuntimeError("mon refused boot (no quorum?)")
        self.osd_id = reply.osd_id
        self.messenger.name = f"osd.{self.osd_id}"
        # centralized config distributed at boot (ConfigMonitor role);
        # merged BEFORE the boot-time peering kick below so cluster-wide
        # settings (osd_auto_repair, repair delays) govern it
        cluster_conf = getattr(reply, "cluster_conf", None)
        if cluster_conf:
            if hasattr(self.conf, "set"):
                # per-key: one bad replicated value must not brick boot
                for k, v in cluster_conf.items():
                    try:
                        self.conf.set(k, v, source="mon")
                    except ValueError:
                        pass
            else:
                for k, v in cluster_conf.items():
                    self.conf.setdefault(k, v)
        if self.conf.get("auth_cephx", False):
            await self._refresh_auth()
            self.messenger.keyring_refresh = self._refresh_auth
        # through _on_map, NOT direct assignment: a freshly added OSD can
        # already be primary of remapped PGs (crush reshuffles on boot),
        # and those PGs need their peering kicked NOW — waiting for the
        # next epoch that happens to touch them leaves them driverless
        # while the old holders keep failing
        self._on_map(reply.osdmap)
        interval = self.conf.get("osd_heartbeat_interval", 0.3)
        loop = asyncio.get_running_loop()
        # the driver loops run under the daemon crash guard: an
        # unexpected exception becomes a crash report + clog entry +
        # clean shutdown, not a silently dead task
        self._ping_task = loop.create_task(
            self._guarded(self._ping_loop, interval))
        self._hb_task = loop.create_task(
            self._guarded(self._heartbeat_loop, interval))
        self.op_queue.start()
        self.ctx.name = f"osd.{self.osd_id}"
        self.ctx.log.name = f"osd.{self.osd_id}"
        self.ctx.tracer.service = f"osd.{self.osd_id}"
        self.clog.name = f"osd.{self.osd_id}"
        self.clog.start()
        if self._crash_dir:
            # replay reports spooled while the mon was unreachable
            # (cephadm crash-dir flow); acked entries leave the spool
            await replay_crash_spool(self._crash_dir, self._send_crash)
        # mon-distributed config landed after the Context was built:
        # re-apply the op-tracker thresholds it governs
        self.ctx.op_tracker.slow_threshold = float(
            self.conf.get("osd_op_complaint_time", 2.0) or 2.0)
        if self._ec_queue is not None:
            # in-process execute() works without the unix socket, so the
            # timeline command registers whether or not asok_dir is set
            self._ec_queue.register_asok(self.ctx.asok)
        # in-process execute() works without the unix socket (the asok
        # command registers whether or not asok_dir is set, like the EC
        # batch timeline above)
        self.ctx.asok.register(
            "dump_hit_sets", lambda a: self._dump_hit_sets(),
            "per-PG hit-set archives (intervals, fill, estimated fpp)")
        self.ctx.asok.register(
            "tier status", lambda a: self.tier_status(),
            "cache-tier residency/promotion/eviction status")
        self.ctx.asok.register(
            "dump_op_queue", lambda a: self.dump_op_queue(),
            "per-class/per-client queue depths and dmClock tags")
        self.ctx.asok.register(
            "dump_reactors", lambda a: self.messenger.dump_reactors(),
            "wire plane: reactor worker shards, per-peer lane state, "
            "colocated rings")
        self.ctx.asok.register(
            "inject_crash", lambda a: self.inject_crash(),
            "raise a fatal exception in the next ping tick "
            "(crash-telemetry exercise)")
        # single-PG scrub/repair (reference `ceph pg scrub/repair
        # <pgid>`): reached via the MCommand tell path aimed at the
        # PG's primary — the hooks are async; execute_async awaits them
        self.ctx.asok.register(
            "pg scrub",
            lambda a: self._pg_admin_scrub(a.get("pgid", ""),
                                           repair=False),
            "deep-scrub one PG this OSD leads (pgid=<pool>.<hex>)")
        self.ctx.asok.register(
            "pg repair",
            lambda a: self._pg_admin_scrub(a.get("pgid", ""),
                                           repair=True),
            "scrub + repair + verify one PG this OSD leads "
            "(pgid=<pool>.<hex>)")
        asok_dir = self.conf.get("admin_socket_dir")
        if asok_dir:
            self.ctx.asok.register(
                "status", lambda a: self.status(), "osd status")
            await self.ctx.asok.start(f"{asok_dir}/osd.{self.osd_id}.asok")
        return self.osd_id

    def status(self) -> dict:
        return {
            "osd_id": self.osd_id,
            "epoch": self.osdmap.epoch if self.osdmap else 0,
            "op_queue_depth": self.op_queue.depth(),
            "hb_peers": sorted(self._hb_last),
        }

    def dump_op_queue(self) -> dict:
        """asok ``dump_op_queue``: the sharded queue's per-class /
        per-client depths and current dmClock tags, plus the admission
        tracker's per-client over-limit excess (the shed-ranking view)."""
        out = self.op_queue.dump()
        out["admission"] = self.qos.dump()
        return out

    # -- daemon crash guard (the ceph-crash agent role) ----------------------

    async def _guarded(self, fn, *args) -> None:
        """Top-level exception hook around a serve loop: capture the
        dump_recent ring + backtrace + identity into a crash report,
        deliver it to the mon (spool to crash_dir when unreachable),
        shout on the cluster log, and stop the daemon — a dying OSD must
        leave a trace an operator (and `non_regression --crash`) can
        query."""
        try:
            await fn(*args)
        except asyncio.CancelledError:
            raise
        except BaseException as e:
            await self._on_fatal(e)

    async def _on_fatal(self, exc: BaseException) -> None:
        entity = f"osd.{self.osd_id}"
        self.ctx.log.error("osd", f"fatal: {exc!r}")
        report = build_crash_report(exc, entity, version=self.ctx.version,
                                    log=self.ctx.log)
        self.clog.error(f"{entity} crashed: {exc!r} "
                        f"(crash id {report.crash_id})")
        delivered = await self._send_crash(report)
        if not delivered and self._crash_dir:
            try:
                spool_crash(self._crash_dir, report)
            except OSError:
                pass
        try:
            await self.clog.flush_now()
        except Exception:
            pass
        # the daemon dies (we may be running inside a task stop()
        # cancels, so the shutdown detaches)
        if not self._stopped:
            self._fatal_task = asyncio.get_running_loop().create_task(
                self.stop())

    async def _send_crash(self, report) -> bool:
        """Deliver one crash report to the mon; True only on a durable
        ack (the spool-replay contract)."""
        try:
            ack = await self._mon_rpc(report, MCrashReportAck)
            return bool(getattr(ack, "ok", False))
        except Exception:
            return False

    def inject_crash(self) -> dict:
        """Dev/CI hook (asok ``inject_crash`` / osd_debug_inject_crash):
        the next ping tick raises, exercising the whole crash plane."""
        self._inject_crash = True
        return {"injected": True, "osd": self.osd_id}

    async def stop(self) -> None:
        self._stopped = True
        await self.clog.stop()
        for t in (self._ping_task, self._hb_task, self._repair_task,
                  self._meta_repl_task, self._scrub_task):
            if t:
                t.cancel()
        for m in self._pg_machines.values():
            if m.task is not None:
                m.task.cancel()
        await self.op_queue.stop()
        await self.ctx.shutdown()
        await self.messenger.shutdown()
        if self._planar is not None:
            # the shared store is process-global but keys are namespaced
            # per OSD: a stopped daemon's residents — dirty fast-ack
            # copies included — are process memory that a real dead OSD
            # loses, so drop them (kill_osd honesty: a revived id must
            # re-earn its pages, and surviving replicas' copies are the
            # ONLY cache-tier copies of its acked writebacks)
            snap = getattr(self._planar, "entries_snapshot", None)
            if snap is not None:
                for key, _nb in snap():
                    if isinstance(key, tuple) and key \
                            and key[0] == self.osd_id:
                        self._planar.drop(key, force=True)
        close = getattr(self.store, "close", None)
        if close is not None:
            close()

    @property
    def mon_addr(self):
        return self.mons.current

    async def _refresh_auth(self) -> None:
        """cephx-lite daemon setup: fetch the rotating service secrets
        (ticket validation) and our own service ticket (OSD->OSD dials)
        from the mon.  Called at boot and periodically so rotations
        propagate (reference RotatingKeyRing refresh)."""
        try:
            rot = await self._mon_rpc(MAuthRotating(), MAuthRotatingReply)
            if getattr(rot, "denied", False):
                raise PermissionError(
                    "mon refused rotating keys (connection not "
                    "daemon-authenticated)")
            if self.messenger.keyring is None:
                self.messenger.keyring = TicketKeyring()
            self.messenger.keyring.load(rot.keys)
            tkt = await self._mon_rpc(
                MAuthTicket(entity=f"osd.{self.osd_id}", entity_type="osd"),
                MAuthTicketReply)
            if getattr(tkt, "denied", False):
                raise PermissionError(
                    "mon refused osd ticket (connection not "
                    "daemon-authenticated)")
            self.messenger.ticket = bytes.fromhex(tkt.ticket)
            self.messenger.session_key = bytes.fromhex(tkt.session_key)
        except TRANSPORT_ERRORS as e:
            self.ctx.log.error("osd", f"auth refresh failed: {e}")
            if isinstance(e, PermissionError) and \
                    self.messenger.ticket is not None:
                # an expired/refused ticket wedges every dial (a presented
                # ticket MUST verify — no silent fallback): drop it so the
                # next refresh re-proves the bootstrap secret instead
                self.messenger.ticket = None
                self.messenger.session_key = None

    def _health_checks(self) -> Dict[str, Dict]:
        """Daemon-observed health checks riding the liveness ping (the
        reference's OSD -> mon health report path): SLOW_OPS from the op
        tracker's complaint aging, BREAKER_OPEN from the device-dispatch
        circuit breaker, TIER_OVER_TARGET from planar residency vs the
        agent's budget.  Empty dict = healthy; the mon clears a check
        when the next report omits it."""
        checks: Dict[str, Dict] = {}
        slow = self.ctx.op_tracker.slow_op_summary()
        if slow["count"]:
            checks["SLOW_OPS"] = {
                "severity": "warning",
                "summary": f"{slow['count']} slow ops, oldest "
                           f"{slow['oldest_age']:.1f}s "
                           f"(complaint time {slow['complaint_time']:g}s)",
                "count": slow["count"],
                "oldest_age": slow["oldest_age"],
                "detail": [f"{o['description']} age {o['age']:.1f}s "
                           f"last event {o['last_event']}"
                           for o in slow["ops"]],
            }
        if self._ec_queue is not None:
            lanes = self._ec_queue.open_lanes()
            if lanes:
                checks["BREAKER_OPEN"] = {
                    "severity": "warning",
                    "summary": f"{len(lanes)} device-dispatch lanes open "
                               f"(CPU fallback): {sorted(lanes)}",
                    "lanes": sorted(lanes),
                }
        if self._planar is not None:
            target = self._tier_effective_target()
            resident = self._planar.resident_bytes
            if target and resident > target:
                checks["TIER_OVER_TARGET"] = {
                    "severity": "warning",
                    "summary": f"tier resident {resident} bytes over "
                               f"target {target}",
                    "resident_bytes": resident,
                    "target_bytes": target,
                }
        if self._scrub_errors:
            # scrub-found inconsistency (reference OSD_SCRUB_ERRORS +
            # PG_INCONSISTENT off scrub stats): raised while any PG this
            # OSD leads had mismatches on its last scrub; cleared when a
            # later scrub/repair pass verifies the PG clean (the next
            # ping omits the check and the mon drops it)
            keys = sorted(self._scrub_errors)  # numeric (pool, pg) order
            pgs = [f"{k[0]}.{k[1]:x}" for k in keys]
            n_err = int(sum(rec.get("errors", 0)
                            for rec in self._scrub_errors.values()))
            checks["OSD_SCRUB_ERRORS"] = {
                "severity": "error",
                "summary": f"{n_err} scrub errors",
                "count": n_err,
            }
            checks["PG_INCONSISTENT"] = {
                "severity": "error",
                "summary": f"{len(pgs)} pg(s) inconsistent "
                           f"(scrub found shard mismatches)",
                "count": len(pgs),
                "pgs": pgs,
                "detail": [
                    f"pg {pgid} inconsistent: "
                    f"{int(rec.get('errors', 0))} mismatched shard(s), "
                    f"{int(rec.get('repaired', 0))} repaired; run "
                    f"`ceph pg repair {pgid}` (or wait for the next "
                    f"scrub) to verify and clear"
                    for pgid, rec in zip(pgs, (
                        self._scrub_errors[k] for k in keys))],
            }
        toofull = sorted(
            f"{k[0]}.{k[1]:x}" for k, m in self._pg_machines.items()
            if getattr(m, "backfill_toofull", False))
        if toofull:
            # the `backfill_toofull` PG state (reference PG_BACKFILL_FULL
            # health check): reservation refused by a BACKFILLFULL
            # target; the PG parks and retries until space frees
            checks["PG_BACKFILL_FULL"] = {
                "severity": "warning",
                "summary": f"{len(toofull)} pg(s) backfill_toofull "
                           f"(reservation refused by a backfillfull "
                           f"target)",
                "count": len(toofull),
                "pgs": toofull,
                "detail": [f"pg {p} backfill parked: target past its "
                           f"backfillfull ratio; retrying" for p in
                           toofull],
            }
        return checks

    # -- capacity / fullness plane -------------------------------------------

    def _inject_full_ratio(self) -> Optional[float]:
        """Dev knob: force this OSD's REPORTED utilization so CI can
        drive the whole fullness ladder without writing gigabytes.
        Sources (first match wins): conf ``osd_debug_inject_full``, the
        daemon Context's config layer (asok / `ceph tell ... config
        set` mutate THAT one live — a dict-conf'd vstart daemon keeps a
        separate Config there), then the ``CEPH_TPU_INJECT_FULL`` env.
        Value: ``RATIO`` (applies to this OSD) or
        ``ID:RATIO[,ID:RATIO...]`` (in-process clusters share one
        conf/env, so the ladder needs per-OSD aim)."""
        ctx_conf = getattr(self.ctx, "conf", None)
        for raw in (self.conf.get("osd_debug_inject_full", ""),
                    ctx_conf.get("osd_debug_inject_full", "")
                    if ctx_conf is not None
                    and ctx_conf is not self.conf else "",
                    os.environ.get("CEPH_TPU_INJECT_FULL", "")):
            if not raw:
                continue
            for part in str(raw).split(","):
                part = part.strip()
                if not part:
                    continue
                sid, sep, r = part.partition(":")
                try:
                    if not sep:
                        return float(part)
                    if int(sid) == self.osd_id:
                        return float(r)
                except (TypeError, ValueError):
                    continue
        return None

    def _statfs(self) -> Dict[str, int]:
        """Effective store utilization: every store implements the
        uniform statfs shape now (total == 0 = no configured capacity),
        with the fullness-injection knob applied on top."""
        st = dict(self.store.statfs())
        missing = {"total", "used", "avail", "num_objects"} - set(st)
        assert not missing, \
            f"{type(self.store).__name__}.statfs() missing {missing}"
        inj = self._inject_full_ratio()
        if inj is not None and inj >= 0:
            total = int(st.get("total") or 0) or (1 << 30)
            st["total"] = total
            st["used"] = int(total * inj)
            st["avail"] = max(0, total - st["used"])
            st["injected"] = True
        return st

    def _failsafe_full(self, extra_bytes: int = 0) -> bool:
        """Would accepting ``extra_bytes`` more cross the failsafe
        ceiling (osd_failsafe_full_ratio of capacity)?  The last-resort
        guard protecting the store itself; injection-aware so CI can
        exercise it."""
        # hot path (every shard write): the common no-ceiling,
        # no-injection case must not pay a statfs sweep
        if not int(getattr(self.store, "capacity_bytes", 0) or 0) \
                and self._inject_full_ratio() is None:
            return False
        st = self._statfs()
        total = int(st.get("total") or 0)
        if total <= 0:
            return False
        ratio = float(self.conf.get("osd_failsafe_full_ratio", 0.97)
                      or 0.97)
        return int(st.get("used") or 0) + extra_bytes > int(total * ratio)

    def _my_full_state(self) -> str:
        """This OSD's fullness state: the mon-derived map state, or the
        LOCAL effective ratio vs the map thresholds when that is more
        severe (the local view leads the mon by up to a ping)."""
        if self.osdmap is None:
            return ""
        state = self.osdmap.full_state(self.osd_id)
        st = self._statfs()
        total = int(st.get("total") or 0)
        if total > 0:
            local = self.osdmap.state_for_ratio(
                int(st.get("used") or 0) / total)
            if FULL_SEVERITY[local] > FULL_SEVERITY[state]:
                state = local
        return state

    def _full_block_reply(self, op: MOSDOp) -> Optional[MOSDOpReply]:
        """Typed-ENOSPC write gate (reference PrimaryLogPG check_full +
        the osdmap full handling): a mutation targeting a PG whose
        acting set contains a FULL OSD — or arriving at a failsafe-full
        primary — fails FAST with ENOSPC (definitive at the client; no
        eternal resend loop).  Reads are untouched.  DELETES are
        explicitly exempt (op delete, snap-trim, and delete-only
        multis): deleting is the only way out of full, so the delete
        path threads through every gate."""
        if self.osdmap is None or op.op not in ("write", "multi", "call"):
            return None
        if op.op == "multi" and (is_delete_only_multi(op)
                                 or is_read_only_multi(op)):
            # delete-only compounds drain; read-only compounds observe —
            # neither adds bytes, neither is gated
            return None
        pool = self.osdmap.pools.get(op.pool_id)
        if pool is None or not op.oid:
            return None
        pg = self.osdmap.object_to_pg(pool, op.oid)
        acting = self.osdmap.pg_to_acting(pool, pg)
        full = [a for a in acting if a != CRUSH_ITEM_NONE
                and self.osdmap.full_state(a) == "full"]
        if full:
            self.perf.inc("full_rejects")
            return MOSDOpReply(
                ok=False, code=-errno.ENOSPC,
                error=f"ENOSPC: pg {op.pool_id}.{pg:x} acting set has "
                      f"full osd(s) {full}; delete data or raise the "
                      f"full ratio")
        if self._failsafe_full(len(op.data) if op.data else 0):
            self.perf.inc("full_rejects")
            return MOSDOpReply(
                ok=False, code=-errno.ENOSPC,
                error=f"ENOSPC: osd.{self.osd_id} past failsafe ratio")
        return None

    async def _ping_loop(self, interval: float) -> None:
        ticks = 0
        while not self._stopped:
            if self._inject_crash:
                # dev/CI crash injection: a REAL unexpected exception in
                # the daemon's driver loop, caught only by the guard
                self._inject_crash = False
                raise RuntimeError(
                    "injected crash (osd_debug_inject_crash)")
            try:
                await self.messenger.send(
                    self.mons.current,
                    MPing(osd_id=self.osd_id,
                          epoch=self.osdmap.epoch if self.osdmap else 0,
                          addr=self.addr or ("", 0),
                          health=self._health_checks(),
                          # statfs piggybacks the liveness ping (v4):
                          # the mon's fullness derivation runs on it
                          statfs=self._statfs(),
                          # v5: unflushed-dirt roster for the mon's
                          # safe-to-destroy / ok-to-stop predicates
                          cache_dirty=self._cache_dirty_summary()),
                )
            except TRANSPORT_ERRORS:
                self.mons.rotate()  # that mon looks dead
            ticks += 1
            self._maybe_schedule_scrubs()
            self._maybe_schedule_tier_agent()
            if self._ec_queue is not None:
                # mirror the shared queue's stats into this daemon's
                # counters (perf dump / prometheus visibility); submits
                # vs dispatches is the coalescing ratio
                self.perf.set("ec_batch_ops", self._ec_queue.submits)
                self.perf.set("ec_batch_dispatches", self._ec_queue.dispatches)
                self.perf.set("ec_batch_bytes", self._ec_queue.bytes_dispatched)
            if ticks % 3 == 0:
                await self._report_to_mgr()
            if self.conf.get("auth_cephx", False):
                ttl = float(self.conf.get("auth_ticket_ttl", 3600.0) or 3600.0)
                period = max(1, int(ttl / 4 / max(interval, 0.01)))
                if ticks % period == 0:
                    await self._refresh_auth()
            await asyncio.sleep(interval)

    async def _report_to_mgr(self) -> None:
        """Push perf/status to the mgr (MMgrReport flow) when one is
        configured (mgr_addr rides the centralized config)."""
        raw = self.conf.get("mgr_addr", "")
        if not raw:
            return
        try:
            host, port = str(raw).rsplit(":", 1)
            from ceph_tpu.mgr.daemon import MMgrReport

            await asyncio.wait_for(
                self.messenger.send(
                    (host, int(port)),
                    MMgrReport(name=f"osd.{self.osd_id}",
                               perf=self.ctx.perf.dump(),
                               status=self.status(), stamp=time.time()),
                    peer_type="mgr"),
                timeout=2.0)  # a stalled mgr must not starve mon pings
        except TRANSPORT_ERRORS:
            pass

    async def _heartbeat_loop(self, interval: float) -> None:
        """OSD<->OSD liveness (maybe_update_heartbeat_peers + heartbeat,
        OSD.cc:5278,5837): ping every up peer; a peer silent past the grace
        is reported to the mon as MOSDFailure."""
        grace = float(self.conf.get("osd_heartbeat_grace", 2.0) or 2.0)
        while not self._stopped:
            await asyncio.sleep(interval)
            if self.osdmap is None:
                continue
            now = time.monotonic()
            peers = [o for o in self.osdmap.osds.values()
                     if o.up and o.osd_id != self.osd_id]
            for o in peers:
                try:
                    await self.messenger.send(
                        o.addr, MOSDPing(op="ping", from_osd=self.osd_id,
                                         stamp=now,
                                         epoch=self.osdmap.epoch))
                except ConnectionRefusedError:
                    # nothing is LISTENING at the peer's address: the
                    # process is gone, not slow — report immediately
                    # instead of burning the grace window (the reference
                    # reports connection faults ahead of ping timeouts).
                    # A restarting OSD re-boots and re-registers, so a
                    # false positive costs one re-peer, not data.
                    if now - self._hb_reported.get(o.osd_id, -1e9) > 1.0:
                        self._hb_reported[o.osd_id] = now
                        self.perf.inc("heartbeat_failures")
                        try:
                            await self.messenger.send(
                                self.mons.current,
                                MOSDFailure(target_osd=o.osd_id,
                                            from_osd=self.osd_id,
                                            failed_for=grace))
                        except TRANSPORT_ERRORS:
                            pass
                except TRANSPORT_ERRORS:
                    pass
                last = self._hb_last.setdefault(o.osd_id, now)
                last_report = self._hb_reported.get(o.osd_id, -1e9)
                if now - last > grace and now - last_report > grace:
                    # re-report each grace interval while the peer stays
                    # silent: the mon ages out stale reporter evidence, so
                    # one-shot reports could never meet a multi-reporter
                    # threshold (reference re-sends MOSDFailure too)
                    self._hb_reported[o.osd_id] = now
                    self.perf.inc("heartbeat_failures")
                    try:
                        await self.messenger.send(
                            self.mons.current,
                            MOSDFailure(target_osd=o.osd_id,
                                        from_osd=self.osd_id,
                                        failed_for=now - last))
                    except TRANSPORT_ERRORS:
                        pass
            # prune state for peers no longer up in the map
            live = {o.osd_id for o in peers}
            for dead in list(self._hb_last):
                if dead not in live:
                    self._hb_last.pop(dead, None)
                    self._hb_reported.pop(dead, None)

    async def _mon_rpc(self, msg, reply_type):
        """Send to a mon and wait for the typed reply; rotate through the
        monmap on timeout (peons forward writes to the leader).  Pending
        futures key on a per-RPC tid echoed by the mon, so two concurrent
        RPCs expecting the same reply type cannot clobber each other;
        type-name keying remains only for untagged messages."""
        if hasattr(msg, "tid"):
            if not msg.tid:
                msg.tid = uuid.uuid4().hex
            key = f"monrpc-{msg.tid}"
        else:
            key = f"monrpc-{reply_type.__name__}"
        last: Exception = TimeoutError("no mon reachable")
        try:
            for _ in range(len(self.mons)):
                fut: asyncio.Future = asyncio.get_running_loop().create_future()
                self._pending[key] = fut
                try:
                    await self.messenger.send(self.mons.current, msg)
                    return await asyncio.wait_for(fut, timeout=10)
                except (ConnectionError, OSError, asyncio.TimeoutError) as e:
                    last = e
                    self.mons.rotate()
        finally:
            self._pending.pop(key, None)
        raise last

    # -- codecs --------------------------------------------------------------

    def _codec(self, pool: PoolInfo):
        codec = self._codecs.get(pool.pool_id)
        if codec is None:
            profile = dict(pool.profile)
            codec = registry.factory(
                profile.get("plugin", "jerasure"), profile.get("directory", ""), profile
            )
            self._codecs[pool.pool_id] = codec
        return codec

    def _sinfo(self, pool: PoolInfo) -> StripeInfo:
        """Per-pool stripe geometry (the reference's sinfo, ECUtil.h:27):
        stripe_unit rides the pool profile (or osd_ec_stripe_unit), rounded
        up to the codec's per-chunk alignment so every stripe's chunks land
        on codec block boundaries."""
        si = self._sinfos.get(pool.pool_id)
        if si is None:
            codec = self._codec(pool)
            k = codec.get_data_chunk_count()
            if pool.stripe_width:
                su = max(1, pool.stripe_width // k)
            else:
                su = int(pool.profile.get(
                    "stripe_unit",
                    self.conf.get("osd_ec_stripe_unit", 4096)) or 4096)
            cs = codec.get_chunk_size(k * max(1, su))
            si = StripeInfo(k, cs * k)
            self._sinfos[pool.pool_id] = si
        return si

    # -- dispatch ------------------------------------------------------------

    def _resolve_monrpc(self, msg) -> None:
        fut = None
        tid = getattr(msg, "tid", "")
        if tid:
            fut = self._pending.pop(f"monrpc-{tid}", None)
        if fut is None:
            fut = self._pending.pop(f"monrpc-{type(msg).__name__}", None)
        if fut and not fut.done():
            fut.set_result(msg)

    async def _dispatch_group(self, conn, msgs) -> None:
        """Whole-group handoff from the messenger rx batch (frames that
        were already buffered on the transport).  Partitioning preserves
        per-connection order — only CONSECUTIVE runs of one type batch:
        sub-write runs apply together and coalesce their replies into
        one flush window; everything else (including MOSDOps, whose
        sharded-op-queue enqueue already returns at queue time, so a
        batch of writes reaches the BatchingQueue's coalescing window
        together) dispatches singly in arrival order."""
        i = 0
        n = len(msgs)
        while i < n:
            if isinstance(msgs[i], MECSubWrite):
                j = i
                while j < n and isinstance(msgs[j], MECSubWrite):
                    j += 1
                try:
                    await self._handle_sub_write_group(msgs[i:j])
                except (asyncio.CancelledError, GeneratorExit):
                    raise
                except Exception:
                    import traceback

                    traceback.print_exc()
                i = j
                continue
            try:
                await self._dispatch(conn, msgs[i])
            except (asyncio.CancelledError, GeneratorExit):
                raise
            except Exception:
                import traceback

                traceback.print_exc()
            i += 1

    async def _dispatch(self, conn, msg) -> None:
        if isinstance(msg, MMapReply):
            if msg.osdmap is not None:
                self._on_map(msg.osdmap)
            elif msg.incrementals and self.osdmap is not None:
                # apply the delta chain to a copy; on a broken chain fall
                # back to a full-map fetch (reference subscriber behavior)
                m = pickle.loads(pickle.dumps(self.osdmap, protocol=5))
                if all(m.apply_incremental(inc) for inc in msg.incrementals):
                    self._on_map(m)
                else:
                    asyncio.get_running_loop().create_task(self._fetch_full_map())
            self._resolve_monrpc(msg)
        elif isinstance(msg, MBootReply):
            self._resolve_monrpc(msg)
        elif isinstance(msg, (MAuthRotatingReply, MAuthTicketReply)):
            self._resolve_monrpc(msg)
        elif isinstance(msg, MOSDPing):
            if msg.op == "ping":
                try:
                    await conn.send(MOSDPing(op="reply", from_osd=self.osd_id,
                                             stamp=msg.stamp))
                except (ConnectionError, OSError):
                    pass
            else:
                self._hb_last[msg.from_osd] = time.monotonic()
                self._hb_reported.pop(msg.from_osd, None)
        elif isinstance(msg, MOSDOp):
            # a wire blob may have landed as an uninitialized-buffer VIEW
            # (MOSDOp.BLOB_VIEW_OK): only the write path is audited for
            # buffer semantics — every other op's handlers (object
            # classes, multi vectors) get real bytes
            if msg.op != "write" \
                    and not isinstance(msg.data, (bytes, bytearray)):
                msg.data = as_bytes(msg.data)
            # op tracking starts at ARRIVAL (not at dequeue) so the
            # queued_for_pg -> reached_pg gap measures real queue wait;
            # when the client propagated a trace context, our op span
            # JOINS it as a child — the cross-daemon stitch point
            tracked = self._track_client_op(msg)
            # client ops ride the sharded op queue: PG-pinned shard keeps
            # per-PG order; scheduler arbitrates client vs recovery
            # classes; a full queue blocks HERE so the messenger stops
            # reading and backpressure reaches the sender
            pg_key = self._pg_key_of(msg)
            if msg.op in ("notify", "deep-scrub", "repair"):
                # notify gathers watcher acks for seconds and touches no
                # PG state: it runs as its OWN task so neither the shard
                # worker nor this serve loop blocks (a watcher callback
                # may issue ops through both).  deep-scrub/repair are
                # multi-second fan-out sweeps whose per-object work now
                # waits its dmClock turn (CLASS_SCRUB/CLASS_RECOVERY)
                # through _background_throttle — run them OUTSIDE the
                # queue so a sweep never holds a shard slot hostage
                # while its own throttle items wait behind it
                t = asyncio.get_running_loop().create_task(
                    self._handle_client_op(conn, msg))
                self.messenger._tasks.add(t)
                t.add_done_callback(self.messenger._tasks.discard)
                return
            op_class = {"repair": CLASS_RECOVERY,
                        "deep-scrub": CLASS_BEST_EFFORT}.get(
                msg.op, CLASS_CLIENT)
            # per-client QoS: resolve the sender's profile from the
            # pool's osdmap-distributed opts and observe the ARRIVAL in
            # the admission tracker (the offered-rate view the
            # saturation shed ranks over — shed arrivals count too, with
            # the tracker's arrears cap bounding the memory); the same
            # profile seeds the op's per-client dmClock state in the
            # scheduler shard
            client = getattr(msg, "client", "")
            qos_params: Optional[QosParams] = None
            # byte-COST of this op in dmClock tag units (qos.qos_op_cost
            # — 1 + bytes/osd_qos_cost_per_io): both the admission
            # tracker and the per-client scheduler tags advance by it,
            # so a bandwidth hog issuing few large ops cannot escape a
            # limit declared in ops/sec
            qcost = qos_op_cost(len(msg.data) if msg.data else 0,
                                self.conf)
            if client and op_class == CLASS_CLIENT:
                pool = self.osdmap.pools.get(msg.pool_id) \
                    if self.osdmap else None
                qos_params = pool_qos(pool, client, self.conf) \
                    if pool is not None else None
                if qos_params is not None:
                    # cross-OSD normalization: the declared profile is
                    # the tenant's CLUSTER-WIDE entitlement; this OSD
                    # enforces its 1/spread share so N independent
                    # primaries sum to the nominal rate, not N x it
                    if self.conf.get("osd_qos_normalize_spread", True):
                        qos_params = qos_params.normalized(
                            self._primary_spread(pool))
                    self.qos.observe(client, qos_params, cost=qcost)
            # arrival-side saturation shed: a saturated OSD drops-and-
            # blocks HERE, before the op consumes a queue slot — the
            # post-dequeue point would drop a whole admitted burst in
            # lockstep instead of letting the first qmax ops through
            if await self._maybe_shed_queue(conn, msg):
                tracked.mark_event("backoff")
                if tracked.trace is not None:
                    tracked.trace.tag("backoff", True)
                    tracked.trace.finish()
                tracked.finish()
                return
            try:
                await self.op_queue.enqueue(
                    pg_key, lambda: self._handle_client_op(conn, msg),
                    op_class, cost=max(1, len(msg.data) // 4096),
                    client=client if qos_params is not None else "",
                    qos=qos_params, qos_cost=qcost,
                )
            except BaseException:
                # cancelled (or failed) while parked on a full queue:
                # the handler will never run, so the tracked op must not
                # sit in the in-flight map forever raising SLOW_OPS —
                # and its span must still record (spans only land in the
                # ring on finish)
                if tracked.done_at is None:
                    tracked.mark_event("enqueue_aborted")
                    if tracked.trace is not None:
                        tracked.trace.tag("aborted", True)
                        tracked.trace.finish()
                    tracked.finish()
                raise
        elif isinstance(msg, MECSubWrite):
            await self._handle_sub_write(msg)
        elif isinstance(msg, MCacheDirty):
            await self._handle_cache_dirty(msg)
        elif isinstance(msg, MECSubRead):
            await self._handle_sub_read(msg)
        elif isinstance(msg, MECSubDelete):
            await self._handle_sub_delete(msg)
        elif isinstance(msg, MListShards):
            await self._handle_list_shards(msg)
        elif isinstance(msg, MFetchShards):
            await self._handle_fetch_shards(msg)
        elif isinstance(msg, MPushShard):
            self._apply_push(msg)
        elif isinstance(msg, MPGInfoReq):
            await self._handle_pg_info(msg)
        elif isinstance(msg, MPGLogReq):
            await self._handle_pg_log_req(msg)
        elif isinstance(msg, MScrubShard):
            await self._handle_scrub_shard(msg)
        elif isinstance(msg, MBackfillReserve):
            await self._handle_backfill_reserve(msg)
        elif isinstance(msg, MECSubRollback):
            self._handle_sub_rollback(msg)
        elif isinstance(msg, MNotifyAck):
            q = self._collectors.get(msg.notify_id)
            if q is not None:
                q.put_nowait(msg)
        elif isinstance(msg, MSetXattrs):
            key = (msg.pool_id, msg.oid, msg.shard)
            try:
                for name, value in msg.xattrs.items():
                    self.store.setattr(key, name, value)
                for name in msg.removals:
                    self.store.rmattr(key, name)
            except NotImplementedError:
                pass
        elif isinstance(msg, MSetOmap):
            key = (msg.pool_id, msg.oid, msg.shard)
            try:
                if msg.clear:
                    self.store.omap_rm(key, list(self.store.omap_get(key)))
                if msg.entries:
                    self.store.omap_set(key, msg.entries)
                if msg.removals:
                    self.store.omap_rm(key, msg.removals)
            except NotImplementedError:
                pass
        elif isinstance(msg, MLogAck):
            self.clog.handle_ack(msg)
        elif isinstance(msg, MCommand):
            # `ceph tell osd.N <cmd>` (reference MCommand): run the
            # admin-socket command in-process — config set/get (runtime
            # debug levels), perf dump, dump_ops_in_flight, ... — and
            # reply on the same connection.  With auth configured, only
            # authenticated peers may drive it.
            if self.conf.get("auth_cephx", False) and \
                    getattr(conn, "auth_kind", "none") == "none":
                reply = MCommandReply(tid=msg.tid, ok=False,
                                      error="EPERM: unauthenticated tell")
            else:
                try:
                    result = await self.ctx.asok.execute_async(
                        msg.prefix, **(msg.args or {}))
                    reply = MCommandReply(tid=msg.tid, ok=True,
                                          result=result)
                except Exception as e:
                    reply = MCommandReply(
                        tid=msg.tid, ok=False,
                        error=f"{type(e).__name__}: {e}")
            try:
                await conn.send(reply)
            except (ConnectionError, OSError):
                pass
        elif isinstance(msg, MCrashReportAck):
            self._resolve_monrpc(msg)
        elif isinstance(msg, MOSDPGHitSet):
            self._handle_pg_hit_set(msg)
        elif isinstance(msg, MPGLogReply) and not msg.tid:
            # unsolicited authoritative log push from the primary: merge
            # (with divergent-entry rollback) so our head catches up
            entries = []
            for blob in msg.entries:
                e = LogEntry.decode(blob)
                e.version = tuple(e.version)
                e.prior_version = tuple(e.prior_version)
                entries.append(e)
            if entries:
                await self._merge_log_entries(msg.pool_id, msg.pg, entries)
        elif isinstance(
            msg, (MECSubWriteReply, MECSubReadReply, MListShardsReply,
                  MFetchShardsReply, MPGInfoReply, MPGLogReply,
                  MScrubShardReply, MBackfillReserveReply, MCacheDirtyAck)
        ):
            q = self._collectors.get(msg.tid)
            if q is not None:
                q.put_nowait(msg)

    async def _fetch_full_map(self) -> None:
        try:
            await self._mon_rpc(MGetMap(min_epoch=0), MMapReply)
        except TRANSPORT_ERRORS:
            pass

    def _on_map(self, osdmap: OSDMap) -> None:
        old = self.osdmap
        if old is not None and osdmap.epoch <= old.epoch:
            return
        # push per-pool store options (pg_pool_t::opts role) so the
        # ObjectStore applies compression policy at its blob boundary
        spo = getattr(self.store, "set_pool_opts", None)
        if spo is not None:
            for pool in osdmap.pools.values():
                spo(pool.pool_id, getattr(pool, "opts", {}) or {})
        if old is None:
            # FIRST map after boot: pools deleted while this OSD was
            # down never produce an old→new transition here, so sweep
            # the persistent store for pools absent from the map
            # (reference: PG deletion resumes on activation)
            try:
                for pid in self.store.list_pools():
                    if pid not in osdmap.pools:
                        self._purge_pool(pid)
            except NotImplementedError:
                pass
        changed_pgs: List[Tuple[PoolInfo, int]] = []
        if old is not None and self._mapping_inputs_changed(old, osdmap):
            # remember the outgoing interval's acting set for PGs whose
            # mapping changed (past_intervals role): it is the set a
            # pg_temp request must name during backfill, and its members
            # accumulate in _past_members (the scope set for deletes,
            # shard hunts and backfill until the PG is clean again).  The
            # pool DELETION (reference PG deletion after `osd pool rm`):
            # a pool present in the old map and gone from the new one is
            # authoritatively deleted cluster-wide — purge every local
            # object/shard of it, its PG logs, and its cache entries
            for gone_id in [p for p in old.pools if p not in osdmap.pools]:
                self._purge_pool(gone_id)
            # dual-CRUSH scan only runs when a mapping INPUT changed (osd
            # states, weights, pools, pg_temp, crush) — config-only
            # epochs skip it.
            for pool in osdmap.pools.values():
                old_pool = old.pools.get(pool.pool_id)
                if old_pool is None:
                    # the pool APPEARED between our old and new maps.  If
                    # it appeared in the very epoch it was created, it is
                    # brand new (no writes can predate us).  If our map
                    # JUMPED past its creation (created_epoch < new
                    # epoch, or an unknown pre-field 0), its PGs may
                    # carry history our logs never saw: kick peering and
                    # mark the interval "unknown prior" (empty prior
                    # acting) so the mutation backoff gate holds writes
                    # until the authoritative log is merged.
                    created = getattr(pool, "created_epoch", 0)
                    if 0 < created and created > old.epoch \
                            and created == osdmap.epoch:
                        continue  # appeared the epoch it was created
                    for pg in range(pool.pg_num):
                        changed_pgs.append((pool, pg))
                        self._prior_acting.setdefault(
                            (pool.pool_id, pg), [])
                    continue
                if old_pool.pg_num != pool.pg_num:
                    # PG split/merge: every object REHASHES, so any OSD
                    # that held any of the pool's PGs may hold objects of
                    # any NEW pg — seed every new pg's interval history
                    # with the union of the old mapping's members, or
                    # backfill/hunt scope would never visit the old
                    # holders and the data would sit stranded
                    old_members = set()
                    for opg in range(old_pool.pg_num):
                        old_members.update(
                            a for a in old.pg_to_acting(old_pool, opg)
                            if a != CRUSH_ITEM_NONE)
                    for npg in range(pool.pg_num):
                        self._past_members.setdefault(
                            (pool.pool_id, npg), set()).update(old_members)
                for pg in range(max(pool.pg_num, old_pool.pg_num)):
                    key = (pool.pool_id, pg)
                    oa = (old.pg_to_acting(old_pool, pg)
                          if pg < old_pool.pg_num else [])
                    na = (osdmap.pg_to_acting(pool, pg)
                          if pg < pool.pg_num else [])
                    if oa == na:
                        continue
                    if pg < pool.pg_num:  # a shrunk-away pg needs no kick
                        changed_pgs.append((pool, pg))
                    self._past_members.setdefault(key, set()).update(
                        a for a in oa if a != CRUSH_ITEM_NONE)
                    if key in old.pg_temp and key not in osdmap.pg_temp:
                        # the override was CLEARED: backfill to the crush
                        # set completed, so the outgoing acting (the
                        # override itself) is obsolete history — recording
                        # it would let a later transient degradation
                        # reinstall a long-stale interval as pg_temp
                        self._prior_acting.pop(key, None)
                    else:
                        self._prior_acting[key] = oa
            # prune intervals of deleted pools (bounded memory)
            for d in (self._prior_acting, self._past_members,
                      self._pg_machines, self._partial_newer,
                      self._hit_sets, self._hit_set_epochs,
                      self._scrub_errors):
                for key in [k for k in d if k[0] not in osdmap.pools]:
                    d.pop(key, None)
        elif old is None:
            # first map: every PG we lead needs an initial peering pass
            changed_pgs = [(pool, pg) for pool in osdmap.pools.values()
                           for pg in range(pool.pg_num)]
            # a pool that predates this map (or an unknown pre-field 0)
            # may carry history our logs never saw — a freshly-booted
            # primary must merge the authoritative log before serving
            # mutations (empty prior = "unknown prior interval", the
            # backoff gate's failover condition)
            for pool, pg in changed_pgs:
                created = getattr(pool, "created_epoch", 0)
                if not created or created < osdmap.epoch:
                    self._prior_acting.setdefault((pool.pool_id, pg), [])
        self.osdmap = osdmap
        # writeback demote fence: any dirty resident whose PG we no
        # longer lead flushes NOW — the next primary's sub-reads hit our
        # backing store, and "writeback is never the only copy of acked
        # data" means a demoted primary may not keep deferred local
        # applies parked in HBM pages
        self._tier_flush_demoted()
        # fast-ack replay sweep: raw dirty copies whose recorded primary
        # is no longer this PG's primary either flush HERE (we inherited
        # primaryship — complete the dead primary's deferred destage) or
        # get pushed to the new primary (we hold a replica copy it needs)
        self._tier_raw_replay_sweep()
        # primaryship may have moved: cached decodes can silently go stale
        # across an interval we didn't serve (ExtentCache is per-interval)
        self._extent_cache.clear()
        # invalidate only codecs whose pool profile actually changed —
        # plugin=tpu codecs carry jit caches worth keeping across epochs
        for pool_id in list(self._codecs):
            new_pool = osdmap.pools.get(pool_id)
            old_pool = old.pools.get(pool_id) if old else None
            if new_pool is None or old_pool is None or new_pool.profile != old_pool.profile:
                self._codecs.pop(pool_id, None)
                self._sinfos.pop(pool_id, None)
        # revoke remote backfill-reservation grants whose requesting
        # primary is no longer this PG's primary (or is down): its release
        # message will never come, and without revocation a few primary
        # deaths would permanently exhaust the slots (reference: remote
        # reservations are cancelled on interval change / peer reset)
        def _grant_still_valid(key, grantee, _t):
            if grantee is None:
                return True  # local grant, owned by a task on this OSD
            pool = osdmap.pools.get(key[0])
            if pool is None:
                return False
            info = osdmap.osds.get(grantee)
            if info is None or not info.up:
                return False
            acting = osdmap.pg_to_acting(pool, key[1])
            return self._primary(pool, key[1], acting) == grantee

        self._remote_reserver.revoke_stale(_grant_still_valid)
        # release client backoffs for PGs we no longer lead: the new
        # primary has no state for our blocks, and the client's own
        # primary-change check drops them too — belt and braces
        for key in list(self._backoffs_sent):
            pool = osdmap.pools.get(key[0])
            if pool is None or key[1] >= pool.pg_num or self._primary(
                    pool, key[1],
                    osdmap.pg_to_acting(pool, key[1])) != self.osd_id:
                self._release_backoffs(key)
        # drop scrub-error records for PGs we no longer lead: only the
        # primary scrubs, so a record held past primaryship loss (or a
        # pool deletion) would raise PG_INCONSISTENT forever with no
        # pass left to clear it — the new primary's scrub owns the state
        for key in list(self._scrub_errors):
            pool = osdmap.pools.get(key[0])
            if pool is None or key[1] >= pool.pg_num or self._primary(
                    pool, key[1],
                    osdmap.pg_to_acting(pool, key[1])) != self.osd_id:
                self._scrub_errors.pop(key, None)
        # event-driven recovery (reference AdvMap/ActMap): kick the peering
        # statechart for exactly the PGs whose mapping changed — repair
        # traffic for one failed OSD touches only that OSD's PGs
        if self.conf.get("osd_auto_repair", True):
            for pool, pg in changed_pgs:
                acting = osdmap.pg_to_acting(pool, pg)
                if self._primary(pool, pg, acting) == self.osd_id:
                    self._kick_peering(pool, pg, acting)

    @staticmethod
    def _mapping_inputs_changed(old: OSDMap, new: OSDMap) -> bool:
        """True when something that can move a PG mapping changed between
        two maps: OSD up/in/weight states, pools, pg_temp, or crush."""
        if old.pg_temp != new.pg_temp or old.pools != new.pools:
            return True
        if old.pg_upmap != new.pg_upmap:
            return True
        if old.primary_affinity != new.primary_affinity:
            return True
        # same crush-change heuristic the incremental diff uses
        if (old.crush.devices() != new.crush.devices()
                or old.crush.rules.keys() != new.crush.rules.keys()):
            return True
        if old.osds.keys() != new.osds.keys():
            return True
        return any(
            (o.up, o.in_cluster, o.weight, osd_crush_weight(o))
            != (new.osds[i].up, new.osds[i].in_cluster,
                new.osds[i].weight, osd_crush_weight(new.osds[i]))
            for i, o in old.osds.items()
        )

    def _machine(self, pool_id: int, pg: int) -> PGMachine:
        key = (pool_id, pg)
        m = self._pg_machines.get(key)
        if m is None:
            m = self._pg_machines[key] = PGMachine(pool_id, pg)
        return m

    def _kick_peering(self, pool: PoolInfo, pg: int,
                      acting: List[int]) -> None:
        """Open a new interval on the PG's statechart and (re)start its
        peering task.  A task already running for an older interval keeps
        running but aborts at its next is_stale check."""
        m = self._machine(pool.pool_id, pg)
        if not m.new_interval(self.osdmap.epoch, acting):
            return
        if m.task is not None and not m.task.done():
            # the running pass belongs to a dead interval and may be
            # blocked in a multi-second gather against a zombie peer —
            # cancel it NOW; waiting for its next staleness check would
            # delay recovery past the next failure
            m.task.cancel()
        m.task = asyncio.get_running_loop().create_task(
            self._run_peering(pool.pool_id, pg))

    def _kick_recovery(self, pool: PoolInfo, pg: int) -> None:
        """Restart the PG's peering task WITHOUT an interval change — used
        when a write completes degraded (a member missed its sub-write):
        the pass re-peers, computes the peer's missing set from the log,
        and re-pushes promptly (the reference's write-time missing-set
        update)."""
        if not self.conf.get("osd_auto_repair", True):
            return
        m = self._machine(pool.pool_id, pg)
        if m.task is None or m.task.done():
            m.task = asyncio.get_running_loop().create_task(
                self._run_peering(pool.pool_id, pg))

    async def _run_peering(self, pool_id: int, pg: int) -> None:
        """Walk one PG through the peering statechart:

            GetInfo -> GetLog -> GetMissing -> Active
              -> Recovering (missing-set-scoped pushes)      [local slot]
              -> WaitLocal/RemoteBackfillReserved
              -> Backfilling (per-PG scoped copy sweep)      [both slots]
              -> Clean

        (reference PeeringState.cc transitions; recovery runs off peering
        events, not timers).  The loop re-enters GetInfo whenever the
        interval advances underneath it."""
        m = self._machine(pool_id, pg)
        if any(a == CRUSH_ITEM_NONE for a in m.acting):
            # degraded: every member of the acting set is load-bearing for
            # redundancy — recover immediately, don't coalesce
            await asyncio.sleep(0.05)
        else:
            await asyncio.sleep(self.conf.get("osd_repair_delay", 0.5))
        delay = self.conf.get("osd_recovery_retry", 1.0)
        while True:  # until Clean / deposed / stopped; backoff on retries
            epoch = m.interval_epoch
            pool = self.osdmap.pools.get(pool_id)
            if pool is None or self._stopped:
                self._release_backoffs((pool_id, pg))
                return
            acting = self.osdmap.pg_to_acting(pool, pg)
            if self._primary(pool, pg, acting) != self.osd_id:
                self._release_backoffs((pool_id, pg))
                return  # not ours this interval
            try:
                done, _pushed = await self._peer_and_recover_pg(
                    m, pool, pg, acting)
            except (ConnectionError, OSError, asyncio.TimeoutError):
                done = False
            except ErasureCodeError as e:
                self.perf.inc("recovery_errors")
                self.ctx.log.error(
                    "osd", f"peering pg {pool_id}.{pg} codec error: {e}")
                self._release_backoffs((pool_id, pg))
                return
            except Exception as e:
                self.perf.inc("recovery_errors")
                self.ctx.log.error(
                    "osd",
                    f"peering pg {pool_id}.{pg}: {type(e).__name__}: {e}")
                done = False
            # the pass merged the authoritative log (Active or beyond):
            # clients parked on this PG may resend now — their reqids
            # dedupe against the merged log
            if m.state not in (GET_INFO, GET_LOG, GET_MISSING):
                self._release_backoffs((pool_id, pg))
            if done and not m.is_stale(epoch):
                return
            if m.is_stale(epoch):
                delay = self.conf.get("osd_recovery_retry", 1.0)
                continue  # interval advanced: re-peer immediately
            if m.reserve_blocked:
                if getattr(m, "backfill_toofull", False):
                    # a BACKFILLFULL target refused: space frees on the
                    # delete/agent cadence, not the slot cadence — park
                    # longer (liveness: the retry keeps running until
                    # the target drops below its ratio)
                    retry = float(self.conf.get(
                        "osd_backfill_toofull_retry", 1.0) or 1.0)
                    await asyncio.sleep(retry * (0.75 + 0.5
                                                 * random.random()))
                    continue
                # a reservation was refused, not a verification failure:
                # slots free in O(one backfill) — retry quickly, with
                # jitter so colliding primaries don't re-collide forever
                await asyncio.sleep(0.15 + 0.2 * random.random())
                continue
            await asyncio.sleep(delay)
            delay = min(delay * 1.6, 15.0)

    async def _peer_and_recover_pg(self, m: PGMachine, pool: PoolInfo,
                                   pg: int, acting: List[int],
                                   force_backfill: bool = False,
                                   reset_interval: bool = False,
                                   ) -> Tuple[bool, int]:
        """One full statechart pass for one PG.  Returns (clean, pushed):
        clean=True when the PG reached Clean (or needed nothing) this
        interval.  ``force_backfill`` runs the copy sweep even when the
        logs agree — the admin repair path uses it to catch silently-lost
        shards the logs cannot see.  ``reset_interval`` applies
        new_interval under the machine lock (admin repair must not mutate
        statechart state while the event-driven task is mid-pass)."""
        async with m.lock:
            if reset_interval:
                m.new_interval(self.osdmap.epoch, acting)
            return await self._peer_and_recover_pg_locked(
                m, pool, pg, acting, force_backfill)

    async def _peer_and_recover_pg_locked(
        self, m: PGMachine, pool: PoolInfo, pg: int,
        acting: List[int], force_backfill: bool = False,
    ) -> Tuple[bool, int]:
        epoch = m.interval_epoch
        key = (pool.pool_id, pg)
        log = self._pglog(pool.pool_id, pg)
        pushed = 0
        if self.ctx.log.wants("osd", 10):
            # guarded: peering passes are frequent under thrash, and the
            # whole point of debug_osd 10 is turning THIS on at runtime
            self.ctx.dout("osd", 10,
                          f"peering pg {pool.pool_id}.{pg:x} pass start: "
                          f"epoch {epoch} acting {acting} "
                          f"log head {log.head}")
        # -- GetInfo: every acting peer's last_update ------------------------
        m.transition(GET_INFO)
        infos, backfill = await self._peer_pg(pool, pg, acting)
        if m.is_stale(epoch):
            return False, pushed
        m.peer_info = dict(infos)
        # an acting member that did not answer GetInfo (lost frame, boot
        # race) is INVISIBLE, not absent: we cannot know what it lacks, so
        # the pass can neither skip it nor declare Clean — route it through
        # backfill (whose holdings listing retries it) and verify later
        live_acting = {a for a in acting if a != CRUSH_ITEM_NONE}
        if not live_acting <= set(infos):
            backfill = True
        # -- GetLog: adopt from peers AHEAD of us ----------------------------
        m.transition(GET_LOG)
        pulled = await self._pull_log_from_ahead(pool, pg, infos, log)
        backfill |= pulled
        if m.is_stale(epoch):
            return False, pushed
        # -- GetMissing: per-peer missing sets from the log ------------------
        m.transition(GET_MISSING)
        m.missing = {}
        for osd, last in infos.items():
            if osd == self.osd_id or last >= log.head:
                continue
            miss = log.calc_missing(last)
            if miss is None:
                backfill = True  # log window can't bridge: needs backfill
            elif miss:
                m.missing[osd] = miss
        # -- Active ----------------------------------------------------------
        m.transition(ACTIVE)
        if m.missing:
            m.transition(RECOVERING)
            got_slot = await self._local_reserver.acquire(
                key, priority=1, timeout=10.0)
            try:
                if m.is_stale(epoch):
                    return False, pushed
                pushed += await self._push_missing(pool, pg, acting, m.missing,
                                                   log)
            finally:
                if got_slot:
                    self._local_reserver.release(key)
            # an interval change mid-push may have reset the statechart
            # to GetInfo under us (new_interval runs lock-free from
            # _kick_peering; only m.task is cancelled, and THIS pass may
            # be the repair/admin one) — never transition out of a dead
            # interval
            if m.is_stale(epoch):
                return False, pushed
            m.transition(ACTIVE)
        if m.is_stale(epoch):
            return False, pushed
        # an active override means the crush up-set still needs filling —
        # the override primary (us) drives that backfill even though its
        # own acting peers are all caught up
        backfill |= bool(self.osdmap.pg_temp.get(key)) or force_backfill
        # the mapping changed since this PG was last clean: a surviving
        # member may have MOVED POSITION (it holds shard i, now serves
        # shard j) — its log is current, so log recovery skips it, but its
        # data is wrong for its seat.  Only the backfill sweep compares
        # data-at-position; run it until a verified-clean pass pops the
        # interval record.  _past_members forces the sweep for the same
        # reason even after _prior_acting was popped (pg_temp clearing
        # pops it): a LEAVER of the interval (an out/reweighted-away
        # member) may still hold strays, and only the sweep's listing
        # sees and purges them — without this, the pass after a pg_temp
        # clear would skip straight to Clean and strand the leaver's
        # shards forever.
        backfill |= key in self._prior_acting
        backfill |= key in self._past_members
        covered = True
        if backfill:
            await self._maybe_request_pg_temp(pool, pg, acting)
            if m.is_stale(epoch):
                # installing the override changed the mapping: the next
                # round (as override primary, possibly another OSD) drives
                # the backfill
                return False, pushed
            ran, bf_pushed, covered = await self._reserved_backfill(
                m, pool, pg)
            pushed += bf_pushed
            if not ran or m.is_stale(epoch):
                return False, pushed
        # -- Clean -----------------------------------------------------------
        # Clean requires a VERIFIED no-op pass: pushes are fire-and-forget,
        # so a pass that pushed anything (or saw an unanswered peer, or
        # found uncovered up-set positions) only made progress — the retry
        # loop re-peers and Clean is declared when a full pass finds
        # nothing left to do.  Declaring Clean optimistically would drop
        # the interval history (_past_members) while data is still in
        # flight, and the next failure could land before it ever arrived.
        if pushed or not covered:
            return False, pushed
        if self.osdmap.pg_temp.get(key):
            await self._clear_done_pg_temps(pool, pushed, None)
            if self.osdmap.pg_temp.get(key):
                return False, pushed  # override still serving: not clean
        if m.is_stale(epoch):
            return False, pushed  # interval moved while we verified
        m.transition(CLEAN)
        self._past_members.pop(key, None)
        self._prior_acting.pop(key, None)
        return True, pushed

    async def _pull_log_from_ahead(self, pool: PoolInfo, pg: int,
                                   infos: Dict[int, Tuple[int, int]],
                                   log: PGLog) -> bool:
        """GetLog role: pull entries from the furthest-ahead peer and adopt
        them (with divergent-entry rollback).  Returns True when objects
        were adopted (their shards need resync = backfill)."""
        ahead = [(osd, v) for osd, v in infos.items() if v > log.head]
        adopted = False
        for osd, _v in sorted(ahead, key=lambda t: t[1], reverse=True)[:1]:
            tid = uuid.uuid4().hex
            q = self._collector(tid)
            try:
                await self.messenger.send(
                    self.osdmap.addr_of(osd),
                    MPGLogReq(pool_id=pool.pool_id, pg=pg, since=log.head,
                              tid=tid, reply_to=self.addr))
            except TRANSPORT_ERRORS:
                continue
            for r in await self._gather(tid, q, 1, timeout=0.8):
                if r.backfill:
                    adopted = True
                    continue
                entries = []
                for blob in r.entries:
                    e = LogEntry.decode(blob)
                    e.version = tuple(e.version)
                    e.prior_version = tuple(e.prior_version)
                    entries.append(e)
                merged = await self._merge_log_entries(pool.pool_id, pg,
                                                       entries)
                if merged:
                    adopted = True
        return adopted

    async def _push_missing(self, pool: PoolInfo, pg: int,
                            acting: List[int],
                            missing: Dict[int, Dict[str, LogEntry]],
                            log: PGLog) -> int:
        """Recovering role: push exactly the objects each lagging peer's
        log says it lacks (missing-set-scoped, reference PGLog missing),
        then advance the peer's log."""
        pushed = 0
        for osd, miss in missing.items():
            shard_of_peer = None
            for shard, a in enumerate(acting):
                if a == osd:
                    shard_of_peer = shard
                    break
            for oid, entry in miss.items():
                # log-driven recovery is classed work too: each push
                # waits its CLASS_RECOVERY dmClock turn
                await self._background_throttle(
                    CLASS_RECOVERY, (pool.pool_id << 20) | pg)
                if entry.op == "delete":
                    try:
                        await self.messenger.send(
                            self.osdmap.addr_of(osd),
                            MECSubDelete(pool_id=pool.pool_id, pg=pg, oid=oid,
                                         shard=-1, tid="", reply_to=self.addr))
                        pushed += 1
                    except TRANSPORT_ERRORS:
                        pass
                    continue
                if shard_of_peer is None:
                    continue
                read = await self._do_read(
                    MOSDOp(op="read", pool_id=pool.pool_id, oid=oid))
                if not read.ok:
                    continue
                encoded = await self._encode_for(
                    pool, as_bytes(read.data), oid=oid, version=read.version)
                push = MPushShard(
                    pool_id=pool.pool_id, pg=pg, oid=oid, shard=shard_of_peer,
                    chunk=bytes(encoded[shard_of_peer]), version=read.version,
                    object_size=len(read.data),
                    hinfo=self._hinfo_for(pool, encoded))
                try:
                    await self.messenger.send(self.osdmap.addr_of(osd), push)
                    pushed += 1
                    self._note_backfill_push(len(push.chunk),
                                             rebalance=False)
                except TRANSPORT_ERRORS:
                    pass
            # the peer now holds the objects: advance its log so the next
            # GetInfo round sees it caught up (and its dup set learns the
            # replayed reqids)
            last = self._machine(pool.pool_id, pg).peer_info.get(osd)
            delta = log.entries_after(last) if last is not None else None
            if delta:
                await self._push_log_to_peer(pool.pool_id, pg, osd, delta)
        return pushed

    async def _reserved_backfill(self, m: PGMachine, pool: PoolInfo,
                                 pg: int) -> Tuple[bool, int, bool]:
        """Backfill under reservations: take a local slot, then a remote
        slot on every backfill target, run the per-PG scoped copy sweep,
        release everything.  Returns (ran, shards_pushed, fully_covered)."""
        key = (pool.pool_id, pg)
        epoch = m.interval_epoch
        m.reserve_blocked = False
        # a degraded PG (holes in the acting set) recovers redundancy, not
        # placement: it outranks plain rebalancing in the slot queues
        # (reference recovery-vs-backfill priority)
        degraded = any(a == CRUSH_ITEM_NONE
                       for a in self.osdmap.pg_to_acting(pool, pg))
        m.transition(WAIT_LOCAL_RESERVE)
        if not await self._local_reserver.acquire(
                key, priority=2 if degraded else 0, timeout=15.0):
            # the acquire waited: an interval change may have reset the
            # statechart to GetInfo lock-free underneath this pass —
            # transitions out of a dead interval are illegal
            if not m.is_stale(epoch):
                m.transition(ACTIVE)
            m.reserve_blocked = True
            return False, 0, False
        targets: List[int] = []
        granted: List[int] = []
        try:
            if m.is_stale(epoch):
                return False, 0, False
            m.transition(WAIT_REMOTE_RESERVE)
            targets = sorted({
                osd for osd in self._raw_up(pool, pg)
                if osd != CRUSH_ITEM_NONE and osd != self.osd_id
            })
            m.backfill_targets = targets
            # DEGRADED PGs skip remote reservations entirely: restoring
            # redundancy is the one thing reservations must never delay
            # (the reference throttles backfill, not degraded recovery —
            # partial-grant livelock here would leave objects one failure
            # from loss while primaries politely retry)
            if not degraded:
                toofull = False
                for osd in targets:
                    ok, reason = await self._remote_reserve(
                        pool.pool_id, pg, osd)
                    if ok:
                        granted.append(osd)
                    elif reason == "toofull":
                        toofull = True
                if m.is_stale(epoch):
                    return False, 0, False
                if len(granted) < len(targets):
                    # partial grant: back off rather than hog slots.
                    # A toofull refusal parks the PG as
                    # backfill_toofull (surfaced in health detail);
                    # the retry loop re-requests with backoff and the
                    # reservation succeeds once the target frees space.
                    m.transition(ACTIVE)
                    m.reserve_blocked = True
                    m.backfill_toofull = toofull
                    return False, 0, False
            m.backfill_toofull = False
            m.transition(BACKFILLING)
            # renew remote leases while the sweep runs: grant times refresh
            # on re-request, so only holders that actually died (and can't
            # renew) age past osd_backfill_reserve_lease and get expired
            lease = self._reserve_lease()

            async def _renew_leases() -> None:
                while True:
                    await asyncio.sleep(max(lease / 3.0, 0.5))
                    for osd in granted:
                        await self._remote_reserve(pool.pool_id, pg, osd)

            renewer = (asyncio.get_running_loop().create_task(_renew_leases())
                       if granted else None)
            try:
                pushed, _holdings, covered = await self._backfill_pg(pool, pg)
            finally:
                if renewer is not None:
                    renewer.cancel()
            if m.is_stale(epoch):
                return False, pushed, False
            m.transition(ACTIVE)
            return True, pushed, covered
        finally:
            # local slot first and synchronously: this block can run under
            # task cancellation, and the slot must never leak.
            # _remote_release swallows its own transport errors.
            self._local_reserver.release(key)
            for osd in granted:
                await self._remote_release(pool.pool_id, pg, osd)

    async def _remote_reserve(self, pool_id: int, pg: int,
                              osd: int) -> Tuple[bool, str]:
        """Request one backfill slot on ``osd``: (granted, refusal
        reason).  reason == "toofull" marks a BACKFILLFULL target (the
        caller parks the PG rather than hammering the slot queue)."""
        tid = uuid.uuid4().hex
        q = self._collector(tid)
        try:
            await self.messenger.send(
                self.osdmap.addr_of(osd),
                MBackfillReserve(op="request", pool_id=pool_id, pg=pg,
                                 from_osd=self.osd_id, tid=tid,
                                 reply_to=self.addr))
        except TRANSPORT_ERRORS:
            self._collectors.pop(tid, None)
            return False, ""
        for r in await self._gather(tid, q, 1, timeout=0.8):
            return bool(r.ok), str(getattr(r, "reason", "") or "")
        return False, ""

    async def _remote_release(self, pool_id: int, pg: int, osd: int) -> None:
        try:
            await self.messenger.send(
                self.osdmap.addr_of(osd),
                MBackfillReserve(op="release", pool_id=pool_id, pg=pg,
                                 from_osd=self.osd_id))
        except TRANSPORT_ERRORS:
            pass

    def _handle_sub_rollback(self, msg: MECSubRollback) -> None:
        """Revert one shard to its rollback slot (primary-confirmed the
        newer version is unrecoverable cluster-wide).  With no PREV copy,
        drop the orphaned shard — it can never decode and its version
        guard would hold the seat hostage against restore pushes."""
        key = (msg.pool_id, msg.oid, msg.shard)
        cur = self._store_read(key)
        if cur is None or cur[1].version != msg.bad_version:
            return  # already moved on
        prev_key = (msg.pool_id, msg.oid, msg.shard + PREV_SLOT)
        prev = self._store_read(prev_key)
        txn = Transaction()
        if prev is not None:
            txn.write(key, prev[0], prev[1])
            txn.delete(prev_key)
        else:
            txn.delete(key)
        self._cache_drop(msg.pool_id, msg.oid)
        self.store.queue_transaction(txn)
        self.perf.inc("unfound_reverted")

    async def _handle_backfill_reserve(self, msg: MBackfillReserve) -> None:
        key = (msg.pool_id, msg.pg)
        if msg.op == "release":
            self._remote_reserver.release(key)
            return
        if FULL_SEVERITY[self._my_full_state()] >= \
                FULL_SEVERITY["backfillfull"]:
            # BACKFILLFULL (or worse): refuse the reservation — backfill
            # onto an OSD that cannot hold the data would burn the wire
            # and then fail at the failsafe (reference
            # PeeringState::Active react RemoteBackfillReserved
            # TOO_FULL).  The primary parks the PG backfill_toofull and
            # retries with backoff; renewals for ALREADY-granted slots
            # refuse too, so a sweep racing the threshold stops at the
            # next lease renewal.
            self.perf.inc("backfill_toofull_refusals")
            self.ctx.dout("osd", 2,
                          f"backfill reserve pg {msg.pool_id}.{msg.pg:x} "
                          f"refused: {self._my_full_state()}")
            try:
                await self.messenger.send(
                    tuple(msg.reply_to),
                    MBackfillReserveReply(tid=msg.tid, osd_id=self.osd_id,
                                          ok=False, reason="toofull"))
            except TRANSPORT_ERRORS:
                pass
            return
        was_held = key in self._remote_reserver.held
        if not was_held and len(self._remote_reserver.held) >= \
                self._remote_reserver.slots:
            # all slots taken: expire leases whose grant outlived the
            # reservation lease (a primary that died without releasing —
            # its release message is not retried) so one crashed peer
            # cannot wedge backfill onto this OSD forever
            lease = self._reserve_lease()
            now = time.monotonic()
            self._remote_reserver.revoke_stale(
                lambda _k, g, t: g is None or now - t < lease)
        ok = self._remote_reserver.try_acquire(key, grantee=msg.from_osd)
        try:
            await self.messenger.send(
                tuple(msg.reply_to),
                MBackfillReserveReply(tid=msg.tid, osd_id=self.osd_id, ok=ok))
        except TRANSPORT_ERRORS:
            # only roll back a slot THIS request took: a duplicate request
            # for an already-held key must not free the real holder's slot
            if ok and not was_held:
                self._remote_reserver.release(key)

    def dump_peering(self) -> List[Dict[str, object]]:
        """Admin-socket hook: every PG statechart + reservation state."""
        out = [m.dump() for m in self._pg_machines.values()]
        out.append({"local_reserver": self._local_reserver.dump(),
                    "remote_reserver": self._remote_reserver.dump()})
        return out

    # -- sub-op RPC plumbing -------------------------------------------------

    def _collector(self, tid: str) -> asyncio.Queue:
        q: asyncio.Queue = asyncio.Queue()
        self._collectors[tid] = q
        return q

    async def _gather(self, tid: str, q: asyncio.Queue, expected: int, timeout: float = 5.0):
        out = []
        try:
            for _ in range(expected):
                out.append(await asyncio.wait_for(q.get(), timeout=timeout))
        except asyncio.TimeoutError:
            pass
        finally:
            self._collectors.pop(tid, None)
        return out

    # -- client ops (primary) ------------------------------------------------

    def _store_read(self, key):
        """store.read with EIO absorbed to a missing-shard result: a bad
        local shard must degrade, never crash, the op (EIO handling the
        reference tests via bluestore read-error injection)."""
        try:
            return self.store.read(key)
        except IOError:
            return None

    # -- PG log --------------------------------------------------------------

    @staticmethod
    def _pgmeta_key(pool_id: int, pg: int) -> Tuple[int, str, int]:
        return (pool_id, f"{PGMETA_PREFIX}{pg}", -1)

    def _pglog(self, pool_id: int, pg: int) -> PGLog:
        log = self._pglogs.get((pool_id, pg))
        if log is None:
            omap = {}
            try:
                omap = self.store.omap_get(self._pgmeta_key(pool_id, pg))
            except (IOError, OSError):
                pass  # unreadable pgmeta: start a fresh log (redo covers)
            maxe = int(self.conf.get("osd_min_pg_log_entries", 500) or 500)
            log = PGLog.load(omap, max_entries=maxe) if omap \
                else PGLog(max_entries=maxe)
            self._pglogs[(pool_id, pg)] = log
        return log

    def _log_in_txn(self, txn: Transaction, pool_id: int, pg: int,
                    entry: LogEntry) -> None:
        """Append to the in-memory log and persist the entry in the SAME
        transaction as the data (reference log_operation +
        queue_transactions coupling)."""
        log = self._pglog(pool_id, pg)
        if entry.version <= log.head:
            return  # replayed/duplicate entry
        trimmed = log.append(entry)
        key = self._pgmeta_key(pool_id, pg)
        txn.omap_set(key, log.omap_entries(entry))
        if trimmed:
            txn.omap_rm(key, trimmed)

    def _list_pool_objects(self, pool_id: int):
        """list_objects minus PG metadata objects and rollback slots."""
        for oid, shard in self.store.list_objects(pool_id):
            if not oid.startswith(PGMETA_PREFIX) and shard < PREV_SLOT:
                yield oid, shard

    # -- extent cache (primary-side RMW pinning) ------------------------------

    def _cache_put(self, pool_id: int, oid: str, version: int,
                   data: bytes) -> None:
        self._extent_cache.put_full((pool_id, oid), version, data)

    def _cache_get(self, pool_id: int, oid: str) -> Optional[Tuple[int, bytes]]:
        return self._extent_cache.get_full((pool_id, oid))

    def _cache_drop(self, pool_id: int, oid: str) -> None:
        self._extent_cache.drop((pool_id, oid))
        if self._planar is not None:
            # force past the dirty guard: every _cache_drop site is a
            # delete, a pool purge, or failed-write cleanup — the data
            # the dirty pages were protecting is itself going away (or
            # was never acked), so flush-before-evict does not apply
            self._planar.drop(self._planar_key(pool_id, oid), force=True)

    def _planar_key(self, pool_id: int, oid: str):
        # namespaced per OSD: in-process clusters share one store/budget
        return (self.osd_id, pool_id, oid)

    def _paged_store(self):
        """The shared resident store WHEN it is the paged flavor (dirty
        tracking / page table / writeback live only there); None under
        the monolithic r10 store or no store at all."""
        s = self._planar
        return s if (s is not None and hasattr(s, "dirty_items")) else None

    def _purge_pool(self, pool_id: int) -> None:
        """Delete every locally stored object of a pool removed from the
        map (reference PG deletion): data shards, rollback slots, PG
        logs, and cache residents all go."""
        txn = Transaction()
        seen = set()
        try:
            for oid, shard in self.store.list_objects(pool_id):
                txn.delete((pool_id, oid, shard))
                seen.add(oid)
        except NotImplementedError:
            return
        if txn.deletes:
            self.store.queue_transaction(txn)
        for oid in seen:
            self._cache_drop(pool_id, snap_head(oid))
        for key in [k for k in self._pglogs if k[0] == pool_id]:
            del self._pglogs[key]
        for d in (self._past_members, self._prior_acting, self._hit_sets,
                  self._hit_set_epochs):
            for k in [k for k in d if k[0] == pool_id]:
                d.pop(k, None)
        self.tier_perf.set("hit_sets", len(self._hit_sets))
        self.perf.inc("pools_purged")

    def _mark_failed_write(self, reqid: str) -> None:
        if reqid:
            self._failed_writes.add(reqid)
            while len(self._failed_writes) > 1024:
                self._failed_writes.pop()

    def _pg_key_of(self, op: MOSDOp) -> int:
        if self.osdmap is None:
            return 0
        pool = self.osdmap.pools.get(op.pool_id)
        if pool is None:
            return op.pool_id
        return (op.pool_id << 20) | self.osdmap.object_to_pg(pool, op.oid)

    def _primary_spread(self, pool: PoolInfo) -> int:
        """Distinct primaries across ``pool``'s PGs under the current
        map (qos.primary_spread), memoized per epoch — the cross-OSD
        QoS normalization divisor resolved on every client op."""
        epoch = self.osdmap.epoch if self.osdmap else 0
        memo_epoch, by_pool = self._spread_memo
        if memo_epoch != epoch:
            by_pool = {}
            self._spread_memo = (epoch, by_pool)
        spread = by_pool.get(pool.pool_id)
        if spread is None:
            spread = by_pool[pool.pool_id] = primary_spread(
                self.osdmap, pool)
        return spread

    async def _background_throttle(self, op_class: str, pg_key: int,
                                   cost: int = 1) -> None:
        """One unit of background work (a scrub'd object, a backfill
        push) waits its dmClock turn in the sharded op queue under its
        background class (reference: recovery/scrub ops ride the op
        queue with osd_mclock_profile service classes).  The waiter
        carries NO order_key — background sweeps need scheduling
        arbitration against client ops, not the per-PG ordering chain
        (chaining onto a PG's client tail from inside a long-running
        sweep could deadlock the sweep against its own queue slot).
        Under mClock the class's (r, w, l, burst) profile shapes when
        the slot is granted; an idle OSD grants immediately through the
        work-conserving fallback.  WPQ arbitrates by class priority.
        No-op when osd_background_qos is off or the OSD is stopping."""
        if self._stopped or not self.conf.get("osd_background_qos", True):
            return
        fut = asyncio.get_running_loop().create_future()

        async def _granted() -> None:
            if not fut.done():
                fut.set_result(None)

        await self.op_queue.enqueue(pg_key, _granted, op_class=op_class,
                                    cost=max(1, cost), ordered=False)
        await fut

    def _track_client_op(self, op: MOSDOp):
        """TrackedOp + trace span for one arriving client op.  The span
        joins the client's propagated trace context when one rode the
        wire (ms_trace_propagation), else roots a fresh trace; the
        TrackedOp carries it so the asok timeline and the stitched span
        tree name the same op.  Attached as a private attribute — resends
        overwrite it, and the attribute never rides a wire encode (fixed
        layouts enumerate FIXED_FIELDS; the only pickled MOSDOp variant,
        `multi`, is deep-copied by the local fastpath before delivery)."""
        prev = getattr(op, "_tracked", None)
        if prev is not None and prev.done_at is None:
            # a resend/duplicate delivery of the SAME op object (local
            # fastpath hands by reference) displaces the prior record:
            # finish it (and its span — spans only record on finish) so
            # neither can dangle forever
            if prev.trace is not None:
                prev.trace.finish()
            prev.finish()
        t_tid = getattr(op, "trace_id", "")
        if t_tid:
            span = self.ctx.tracer.join(f"osd_op {op.op}", t_tid,
                                        getattr(op, "span_id", "") or None)
        else:
            span = self.ctx.tracer.new_trace(f"osd_op {op.op}")
        span.tag("osd", self.osd_id)
        if op.reqid:
            span.tag("reqid", op.reqid)
        tracked = self.ctx.op_tracker.create(
            f"osd_op({op.op} {op.pool_id}:{op.oid})", reqid=op.reqid,
            trace=span)
        # tenant-class tag: phase samples also land in per-class rings
        # ("cls:<name>|<phase>") so the macro bench can reduce
        # per-tenant-class p50/p99/p999 from the same optracker path.
        # "|" is the ring-key separator and the client name is
        # wire-controlled: sanitize so a crafted name cannot mislabel
        # the per-class reduction
        tracked.qos_tag = tenant_class(
            getattr(op, "client", "")).replace("|", "_")
        if op.op == "notify":
            # a notify legitimately parks for its whole watcher-ack
            # gather window — aging it would raise SLOW_OPS on every
            # notify with one sluggish watcher
            tracked.complaint_ok = False
        tracked.mark_event("queued_for_pg")
        op._tracked = tracked
        return tracked

    async def _handle_client_op(self, conn, op: MOSDOp) -> None:
        tracked = getattr(op, "_tracked", None)
        if tracked is None or tracked.done_at is not None:
            tracked = self._track_client_op(op)
        t0 = time.monotonic()
        self.perf.inc("op")
        if op.op == "write":
            self.perf.inc("op_w")
        elif op.op == "read":
            self.perf.inc("op_r")
        try:
            await self._handle_client_op_inner(conn, op, tracked)
        finally:
            self.perf.tinc("op_lat", time.monotonic() - t0)
            if tracked.trace is not None:
                tracked.trace.finish()
            tracked.mark_event("done")
            tracked.finish()

    # ops the backoff gate may drop-and-block (client data plane; admin
    # fan-outs like repair/deep-scrub/pgls answer normally)
    _BACKOFF_OPS = frozenset(("write", "read", "delete", "multi", "stat",
                              "call"))
    # mutations gated by the peering-window check (reads can serve from
    # any interval; mutations must not race the authoritative log merge).
    # "call" belongs here: class-call results dedupe through the
    # primary-LOCAL _call_results cache, so a failover resend racing the
    # prior primary is exactly the non-idempotent double-execute window.
    _BACKOFF_MUTATIONS = frozenset(("write", "delete", "multi", "call"))

    async def _maybe_shed_queue(self, conn, op: MOSDOp) -> bool:
        """Arrival-side saturation shed (the "queue" backoff reason):
        when admitted-but-unfinished ops exceed osd_backoff_queue_depth
        (0 disables; under per-PG chaining an overload lives in RUNNING
        chains, not the scheduler queue, so raw depth() would never see
        it), the arriving op is dropped and its client blocked for a
        short timed window via MOSDBackoff.  The shed is QoS-DIRECTED
        when client identities are in play: if any client's OFFERED rate
        is past its limit (qos.QosTracker), only over-limit clients' ops
        are shed — the flooder parks while the reserved tenant keeps
        being admitted; with nobody over limit the legacy
        shed-the-arrival behavior applies.  Returns True when the op was
        dropped."""
        if self.osdmap is None or op.op not in self._BACKOFF_OPS:
            return False
        if op.op == "delete" or (op.op == "multi"
                                 and is_delete_only_multi(op)):
            # deletes thread through every gate (pausewr, the full
            # check, AND this shed): under capacity pressure they are
            # the only way out, and a saturated-because-full OSD
            # shedding its deletes would deadlock the drain
            return False
        qmax = int(self.conf.get("osd_backoff_queue_depth", 0) or 0)
        if not qmax or self.op_queue.inflight_ops <= qmax:
            return False
        pool = self.osdmap.pools.get(op.pool_id)
        if pool is None or not op.oid:
            return False
        shed, qos_directed = self.qos.should_shed(
            getattr(op, "client", ""),
            float(self.conf.get("osd_qos_shed_grace", 0.25) or 0.0))
        if not shed:
            # an over-limit client exists and it is not this one: admit
            # (the flooder eats the shed at its own next arrival)
            return False
        if qos_directed:
            self.sched_perf.inc("qos_shed")
        pg = self.osdmap.object_to_pg(pool, op.oid)
        self.ctx.dout(
            "osd", 2,
            f"qos shed {'directed' if qos_directed else 'legacy'}: "
            f"client={getattr(op, 'client', '')!r} op={op.op} "
            f"pg={op.pool_id}.{pg:x} inflight={self.op_queue.inflight_ops}")
        await self._send_queue_block(conn, (op.pool_id, pg), op)
        return True

    async def _send_queue_block(self, conn, key: Tuple[int, int],
                                op: MOSDOp) -> None:
        """Send the timed MOSDBackoff block for a queue-saturation shed
        (expiry-released: the client resends after osd_backoff_secs)."""
        self.perf.inc("backoffs_sent")
        tracked = getattr(op, "_tracked", None)
        b_tid = b_sid = ""
        if self._trace_on and tracked is not None \
                and tracked.trace is not None:
            b_tid, b_sid = tracked.trace.context()
        msg = MOSDBackoff(
            op="block", pool_id=key[0], pg=key[1], id=uuid.uuid4().hex,
            epoch=self.osdmap.epoch,
            duration=float(self.conf.get("osd_backoff_secs", 0.5) or 0.5),
            trace_id=b_tid, span_id=b_sid)
        try:
            await conn.send(msg)
        except TRANSPORT_ERRORS:
            pass  # op dropped either way; client times out + resends

    def _op_backoff_reason(self, op: MOSDOp) -> Optional[Tuple[Tuple[int, int], str]]:
        """((pool, pg), reason) when this op must be BLOCKED via
        MOSDBackoff instead of served (reference PrimaryLogPG
        maybe_handle_backoff / the waiting_for_peered queue):

        - "peering": a mutation while the PG's peering pass has not yet
          merged the authoritative log AND the window is actually unsafe
          — the interval moved primaryship onto us (a resend racing the
          prior primary's in-flight sub-writes could double-execute its
          reqid) or the PG is below min_size (the write would only burn
          EAGAIN retries).  Healthy same-primary intervals (pool create,
          rebalance without failover) serve ops as before.

        (The "queue" saturation shed moved to the ARRIVAL side —
        _maybe_shed_queue — so a saturated OSD drops before the op
        consumes a queue slot.)
        """
        if self.osdmap is None or op.op not in self._BACKOFF_OPS:
            return None
        pool = self.osdmap.pools.get(op.pool_id)
        if pool is None or not op.oid:
            return None
        pg = self.osdmap.object_to_pg(pool, op.oid)
        key = (op.pool_id, pg)
        if op.op not in self._BACKOFF_MUTATIONS:
            return None
        m = self._pg_machines.get(key)
        if m is None or m.task is None or m.task.done() \
                or m.state not in (GET_INFO, GET_LOG, GET_MISSING):
            return None
        acting = self.osdmap.pg_to_acting(pool, pg)
        live = [a for a in acting if a != CRUSH_ITEM_NONE]
        prior = self._prior_acting.get(key)
        failover = prior is not None and self.osdmap.primary_of(
            prior, seed=(op.pool_id << 20) | pg) != self.osd_id
        if len(live) < pool.min_size or failover:
            return key, "peering"
        return None

    async def _maybe_backoff(self, conn, op: MOSDOp) -> bool:
        """Send an MOSDBackoff block and DROP the op when the PG's
        peering window cannot serve it right now; returns True when the
        op was dropped.  The client parks everything for the PG until
        the unblock (the conn registers for release) or until
        ``duration`` expires (the liveness bound for a dying primary).
        Queue-saturation sheds live on the arrival side
        (_maybe_shed_queue)."""
        got = self._op_backoff_reason(op)
        if got is None:
            return False
        key, reason = got
        ent = self._backoffs_sent.get(key)
        bid = ent["id"] if ent is not None else uuid.uuid4().hex
        duration = float(self.conf.get("osd_backoff_max", 3.0) or 3.0)
        self.perf.inc("backoffs_sent")
        tracked = getattr(op, "_tracked", None)
        b_tid = b_sid = ""
        if self._trace_on and tracked is not None \
                and tracked.trace is not None:
            # the block rides the op's trace: the client sees WHY its op
            # parked inside the same stitched tree
            b_tid, b_sid = tracked.trace.context()
        msg = MOSDBackoff(op="block", pool_id=key[0], pg=key[1], id=bid,
                          epoch=self.osdmap.epoch, duration=duration,
                          trace_id=b_tid, span_id=b_sid)
        try:
            await conn.send(msg)
        except TRANSPORT_ERRORS:
            return True  # op dropped either way; client times out + resends
        if reason == "peering":
            ent = self._backoffs_sent.setdefault(
                key, {"id": bid, "conns": {}})
            ent["conns"][id(conn)] = conn
        return True

    def _release_backoffs(self, key: Tuple[int, int]) -> None:
        """Unblock every client parked on this PG (peering reached
        Active / primaryship moved off us).  Sends ride their own task —
        callers sit on the peering/map path and must not serialize on
        client sockets."""
        ent = self._backoffs_sent.pop(key, None)
        if ent is None or not ent["conns"]:
            return
        self.perf.inc("backoffs_released", len(ent["conns"]))
        msg = MOSDBackoff(op="unblock", pool_id=key[0], pg=key[1],
                          id=ent["id"],
                          epoch=self.osdmap.epoch if self.osdmap else 0)

        async def _send() -> None:
            for c in ent["conns"].values():
                try:
                    await c.send(msg)
                except TRANSPORT_ERRORS:
                    pass  # client's park duration is the liveness bound

        try:
            t = asyncio.get_running_loop().create_task(_send())
        except RuntimeError:
            return  # no loop (teardown): clients release on expiry
        self.messenger._tasks.add(t)
        t.add_done_callback(self.messenger._tasks.discard)

    async def _handle_client_op_inner(self, conn, op: MOSDOp,
                                      tracked) -> None:
        tracked.mark_event("reached_pg")
        try:
            if op.epoch > (self.osdmap.epoch if self.osdmap else 0):
                # epoch barrier (reference require_same_or_newer_map): the
                # client computed its target on a newer map than ours —
                # deciding primaryship on the stale one could execute an
                # op we no longer own.  Catch up first.
                await self._fetch_full_map()
            if await self._maybe_backoff(conn, op):
                tracked.mark_event("backoff")
                return  # dropped: the client parks and resends on release
            full_reply = self._full_block_reply(op)
            if full_reply is not None:
                # fullness gate: typed ENOSPC, definitive at the client
                # (reads and deletes never land here)
                tracked.mark_event("full_reject")
                reply = full_reply
            elif op.op == "write":
                reply = await self._do_write(op)
            elif op.op == "read":
                reply = await self._snap_routed(op, self._do_read)
                if reply.ok and op.snap_read == 0:
                    # tier policy hook: record the hit in the PG's
                    # hit-set archive and maybe promote (client reads
                    # only — internal reads via _do_read must not heat
                    # the working set)
                    self._tier_observe_read(op, reply)
                # byte-COST catch-up for reads: the op carried no
                # payload at arrival (cost observed as 1 IO), but the
                # served bytes are the bandwidth a read hog consumes —
                # charge the admission tracker the byte increment now
                # so a few-large-GETs tenant ranks by its true load
                # (the reference mClock costs reads by length too)
                if reply.ok and reply.data is not None \
                        and getattr(op, "client", ""):
                    nbytes = len(reply.data)
                    if nbytes:
                        pool = self.osdmap.pools.get(op.pool_id) \
                            if self.osdmap else None
                        if pool is not None:
                            params = pool_qos(pool, op.client, self.conf)
                            self.qos.observe(
                                op.client, params,
                                cost=qos_op_cost(nbytes, self.conf) - 1.0)
            elif op.op == "delete":
                reply = await self._do_delete(op)
            elif op.op == "snap-trim":
                reply = await self._do_snap_trim(op)
            elif op.op == "pgls":
                reply = await self._do_pgls(op)
            elif op.op == "list":
                reply = MOSDOpReply(ok=True, oids=[
                    o for o in self._list_heads(op.pool_id)
                    if _ns_match(o, op.nspace)])
            elif op.op == "repair":
                pool = self.osdmap.pools.get(op.pool_id)
                if pool is not None:
                    await self.repair_pool(pool)
                reply = MOSDOpReply(ok=True)
            elif op.op == "call":
                reply = await self._do_call(op)
            elif op.op == "multi":
                reply = await self._do_multi(op)
            elif op.op == "stat":
                reply = await self._snap_routed(op, self._do_stat)
            elif op.op == "watch":
                reply = await self._do_watch(op)
            elif op.op == "unwatch":
                reply = await self._do_watch(op, remove=True)
            elif op.op == "notify":
                reply = await self._do_notify(op)
            elif op.op == "deep-scrub":
                pool = self.osdmap.pools.get(op.pool_id)
                if pool is None:
                    reply = MOSDOpReply(ok=False, code=-errno.ENOENT,
                                        error="no such pool")
                else:
                    summary = await self.deep_scrub_pool(pool)
                    reply = MOSDOpReply(ok=True, data=pickle.dumps(summary))
            elif op.op == "statfs":
                # per-OSD store utilization (reference
                # ObjectStore::statfs feeding `ceph osd df`): every
                # store implements the uniform {total, used, avail,
                # num_objects} shape now (total == 0 = no configured
                # capacity); _statfs asserts it and applies injection
                stats = self._statfs()
                stats["store"] = type(self.store).__name__
                reply = MOSDOpReply(ok=True,
                                    data=json.dumps(stats).encode())
            else:
                reply = MOSDOpReply(ok=False, code=-errno.EINVAL,
                                    error=f"bad op {op.op}")
        except ErasureCodeError as e:
            # the codec REJECTED the operation (unsatisfiable decode,
            # profile violation): deterministic, so definitive
            reply = MOSDOpReply(ok=False, code=-errno.EBADMSG,
                                error=f"ec error: {e}")
        except ENOSPCError as e:
            # the failsafe (OSD-level or the store's own last-resort
            # guard) refused BEFORE mutating anything: typed and
            # definitive — resending into a full store cannot succeed,
            # deleting is the cure
            self.perf.inc("full_rejects")
            reply = MOSDOpReply(ok=False, code=-errno.ENOSPC,
                                error=f"ENOSPC: {e.strerror}")
        except Exception as e:
            # unexpected: conservatively retryable (transient state races
            # dominate here; a true logic bug surfaces in the counter)
            self.perf.inc("op_unexpected_error")
            reply = MOSDOpReply(ok=False, code=-errno.EIO,
                                error=f"{type(e).__name__}: {e}")
        reply.reqid = op.reqid
        # our epoch rides every reply: on retryable errors the client
        # fences its re-target on at least this epoch
        reply.map_epoch = self.osdmap.epoch if self.osdmap else 0
        tracked.mark_event("commit_sent")
        try:
            await conn.send(reply)
        except ConnectionError:
            pass

    def _acting(self, pool: PoolInfo, oid: str) -> Tuple[int, List[int]]:
        pg = self.osdmap.object_to_pg(pool, oid)
        return pg, self.osdmap.pg_to_acting(pool, pg)

    def _primary(self, pool: PoolInfo, pg: int, acting: List[int]):
        return self.osdmap.primary_of(acting, seed=(pool.pool_id << 20) | pg)

    # -- snapshots (reference SnapMapper.h:43, PrimaryLogPG::make_writeable,
    #    librados selfmanaged snap ops IoCtxImpl.cc) --------------------------

    SNAPSET_XATTR = "snapset_key"

    def _load_snapset(self, pool_id: int, oid: str) -> Dict:
        """The object's SnapSet (per-object clone list, reference
        SnapSet in osd_types.h): {"seq", "born", "whiteout",
        "clones": [[clone_id, [snaps...]], ...]}."""
        try:
            raw = self.store.getattr((pool_id, oid, 0), self.SNAPSET_XATTR)
        except (IOError, OSError):
            raw = None
        if not raw:
            return {"seq": 0, "born": 0, "whiteout": False, "clones": []}
        try:
            return json.loads(raw)
        except (ValueError, KeyError, TypeError):
            return {"seq": 0, "born": 0, "whiteout": False, "clones": []}

    async def _save_snapset(self, pool: PoolInfo, pg: int,
                            acting: List[int], oid: str, ss: Dict) -> None:
        """Persist the SnapSet on the head's canonical shard and replicate
        to the acting members (same pattern as cls xattrs: a failover
        primary must resolve snap reads without the old primary)."""
        blob = json.dumps(ss).encode()
        self.store.setattr((pool.pool_id, oid, 0), self.SNAPSET_XATTR, blob)
        for osd in acting:
            if osd in (CRUSH_ITEM_NONE, self.osd_id):
                continue
            try:
                await self.messenger.send(
                    self.osdmap.addr_of(osd),
                    MSetXattrs(pool_id=pool.pool_id, oid=oid, shard=0,
                               xattrs={self.SNAPSET_XATTR: blob}))
            except TRANSPORT_ERRORS:
                pass  # recovery pushes carry xattrs; scrub repairs drift

    def _live_snaps(self, pool: PoolInfo, snaps: List[int]) -> List[int]:
        # IntervalSet membership: O(log runs) per id, no materialization
        return [s for s in snaps if s not in pool.removed_snaps]

    async def _make_writeable(self, op: MOSDOp, pool: PoolInfo, pg: int,
                              acting: List[int]) -> Optional[MOSDOpReply]:
        """COW before the first write past a new snap (the reference's
        make_writeable): clone the current head into a clone object
        (placed in the SAME PG — object_to_pg hashes the head name) and
        record it in the SnapSet.  Clone writes ride the normal write
        pipeline, so they are erasure-coded, logged, and recoverable like
        any object.

        Returns an error reply the parent write must surface (and NOT
        proceed past) when snapshot preservation could not be guaranteed;
        None means the write may go ahead.  The born/absent branches fire
        only on VERIFIED absence (typed -ENOENT / whiteout) — a transient
        head-read failure (-EAGAIN degraded, -EIO) on an existing object
        must not skip the COW clone, or the pre-snap bytes are destroyed.
        """
        if is_snap_clone(op.oid) or op.snapc_seq <= 0:
            return None
        snapc = self._live_snaps(pool, op.snapc_snaps)
        ss = self._load_snapset(op.pool_id, op.oid)
        newer = [s for s in snapc if s > ss["seq"]]
        if newer:
            head = await self._do_read(
                MOSDOp(op="read", pool_id=op.pool_id, oid=op.oid))
            if head.ok and not ss.get("whiteout"):
                clone_id = max(newer)
                wr = await self._do_write(MOSDOp(
                    op="write", pool_id=op.pool_id,
                    oid=snap_clone_oid(op.oid, clone_id),
                    data=as_bytes(head.data),
                    reqid=uuid.uuid4().hex))
                if not wr.ok:
                    # the clone did not durably land (below min_size, …):
                    # overwriting the head now would lose the pre-snap
                    # bytes.  Fail the parent write retryably instead.
                    return MOSDOpReply(
                        ok=False, code=-errno.EAGAIN,
                        error=f"snap clone write failed: {wr.error}",
                        backoff=float(
                            self.conf.get("osd_backoff_secs", 0.5) or 0))
                ss["clones"].append([clone_id, sorted(newer)])
            elif head.ok or head.code == -errno.ENOENT:
                # verified absence: whiteout head, or every possible
                # holder answered ENOENT (_absent_reply discipline)
                if not head.ok and ss["seq"] == 0 and not ss["clones"]:
                    # object is being CREATED under this context: snaps at
                    # or before snapc_seq predate it (existence-at-snap)
                    ss["born"] = op.snapc_seq
                else:
                    # the object was ABSENT (whiteout, or vanished) while
                    # these snaps were taken: record that, or recreating
                    # the head would make reads at those snaps serve
                    # FUTURE data
                    absent = ss.setdefault("absent", [])
                    absent.extend(s for s in newer if s not in absent)
            else:
                # transient head-read failure (-EAGAIN, -EIO): existence
                # is UNKNOWN — neither clone nor record absence.  The
                # parent write must back off rather than mutate the head.
                return MOSDOpReply(
                    ok=False, code=-errno.EAGAIN,
                    error=f"snap COW head read failed: {head.error}",
                    backoff=float(
                        self.conf.get("osd_backoff_secs", 0.5) or 0))
        if op.snapc_seq > ss["seq"]:
            ss["seq"] = op.snapc_seq
            ss["whiteout"] = False
            await self._save_snapset(pool, pg, acting, op.oid, ss)
        elif ss.get("whiteout"):
            ss["whiteout"] = False
            await self._save_snapset(pool, pg, acting, op.oid, ss)
        return None

    def _resolve_snap_read(self, pool: PoolInfo, oid: str,
                           snap: int) -> Optional[str]:
        """Which object serves a read at `snap`: the covering clone, the
        (unchanged-since) head, or None for ENOENT (removed snap, or the
        object did not exist at that snap)."""
        if snap in pool.removed_snaps:
            return None
        ss = self._load_snapset(pool.pool_id, oid)
        if 0 < snap <= ss.get("born", 0):
            return None  # created after the snapshot
        if snap in ss.get("absent", ()):
            return None  # object was deleted while this snap was taken
        removed = pool.removed_snaps
        for clone_id, snaps in sorted(ss["clones"]):
            live = [s for s in snaps if s not in removed]
            if live and clone_id >= snap:
                # first clone at-or-past the snap holds the bytes as they
                # were WHEN that snap was live (reference clone coverage)
                return snap_clone_oid(oid, clone_id)
        if ss.get("whiteout"):
            return None  # deleted after the last clone: gone at this snap
        return oid  # unchanged since the snap: the head serves

    async def _snap_routed(self, op: MOSDOp, handler) -> MOSDOpReply:
        """Route a read/stat through snap resolution when snap_read is
        set; a whiteout head answers ENOENT even for head reads."""
        pool = self.osdmap.pools.get(op.pool_id)
        if pool is None:
            return MOSDOpReply(ok=False, code=-errno.ENOENT,
                               error="no such pool")
        snap = getattr(op, "snap_read", 0)
        if snap > 0 and not is_snap_clone(op.oid):
            target = self._resolve_snap_read(pool, op.oid, snap)
            if target is None:
                return MOSDOpReply(ok=False, code=-errno.ENOENT,
                                   error="object not found (at snap)")
            if target != op.oid:
                routed = MOSDOp(op=op.op, pool_id=op.pool_id, oid=target,
                                reqid=op.reqid)
                return await handler(routed)
        elif not is_snap_clone(op.oid):
            ss = self._load_snapset(op.pool_id, op.oid)
            if ss.get("whiteout"):
                return MOSDOpReply(ok=False, code=-errno.ENOENT,
                                   error="object not found")
        return await handler(op)

    async def _do_pgls(self, op: MOSDOp) -> MOSDOpReply:
        """Paginated listing of ONE PG's objects (reference do_pgnls,
        PrimaryLogPG.cc): the primary answers from its local shards —
        after backfill it holds a shard of every object in the PG — so
        admin listings fan out to per-PG primaries and page, instead of
        broadcasting to every OSD.  Returns up to max_entries heads past
        `cursor`, plus the resume cursor ("" when exhausted)."""
        pool = self.osdmap.pools.get(op.pool_id)
        if pool is None:
            return MOSDOpReply(ok=False, code=-errno.ENOENT,
                               error="no such pool")
        pg = op.pg
        acting = self.osdmap.pg_to_acting(pool, pg)
        if self._primary(pool, pg, acting) != self.osd_id:
            return MOSDOpReply(ok=False, code=-errno.ESTALE,
                               error="not primary")
        limit = op.max_entries or 512
        heads = sorted({
            snap_head(oid)
            for oid, _ in self._list_pool_objects(op.pool_id)
            if self.osdmap.object_to_pg(pool, oid) == pg
        })
        out: List[str] = []
        for oid in heads:
            if op.cursor and oid <= op.cursor:
                continue
            if is_snap_clone(oid):
                continue
            if not _ns_match(oid, op.nspace):
                continue
            if self._load_snapset(op.pool_id, oid).get("whiteout"):
                continue
            out.append(oid)
            if len(out) >= limit:
                break
        exhausted = not out or out[-1] == (heads[-1] if heads else "")
        return MOSDOpReply(ok=True, oids=out,
                           cursor="" if exhausted else out[-1])

    def _list_heads(self, pool_id: int) -> List[str]:
        """User-visible listing: heads only — no clones, no whiteouts."""
        out = []
        for oid in sorted({oid for oid, _ in
                           self._list_pool_objects(pool_id)}):
            if is_snap_clone(oid):
                continue
            if self._load_snapset(pool_id, oid).get("whiteout"):
                continue
            out.append(oid)
        return out

    async def _do_snap_trim(self, op: MOSDOp) -> MOSDOpReply:
        """Remove one snap pool-wide for the PGs this OSD leads
        (reference snap trimmer + SnapMapper reverse index; here the
        per-PG object walk is the scoped listing already used by
        backfill).  Idempotent — safe to re-run."""
        pool = self.osdmap.pools.get(op.pool_id)
        if pool is None:
            return MOSDOpReply(ok=False, code=-errno.ENOENT,
                               error="no such pool")
        snapid = op.snap_id
        trimmed = 0
        heads = {snap_head(oid)
                 for oid, _ in self._list_pool_objects(op.pool_id)}
        for oid in sorted(heads):
            pg, acting = self._acting(pool, oid)
            if self._primary(pool, pg, acting) != self.osd_id:
                continue
            ss = self._load_snapset(op.pool_id, oid)
            if (not ss["clones"] and not ss.get("whiteout")
                    and snapid not in ss.get("absent", ())):
                continue
            changed = False
            if snapid in ss.get("absent", ()):
                ss["absent"] = [s for s in ss["absent"] if s != snapid]
                changed = True
            kept = []
            for clone_id, snaps in ss["clones"]:
                live = [s for s in snaps if s != snapid]
                if live != snaps:
                    changed = True
                if live:
                    kept.append([clone_id, live])
                else:
                    # no snap references the clone: delete it
                    await self._do_delete(MOSDOp(
                        op="delete", pool_id=op.pool_id,
                        oid=snap_clone_oid(oid, clone_id),
                        reqid=uuid.uuid4().hex))
                    trimmed += 1
                    changed = True
            ss["clones"] = kept
            if ss.get("whiteout") and not kept:
                # a deleted head whose last clone just went: fully gone.
                # Persist the emptied clone list FIRST so the delete path
                # (which re-reads the SnapSet) takes the real-delete
                # branch instead of re-whiteouting.
                await self._save_snapset(pool, pg, acting, oid, ss)
                await self._do_delete(MOSDOp(
                    op="delete", pool_id=op.pool_id, oid=oid,
                    reqid=uuid.uuid4().hex))
                trimmed += 1
                continue
            if changed:
                await self._save_snapset(pool, pg, acting, oid, ss)
        return MOSDOpReply(ok=True, data=str(trimmed).encode())

    async def _do_write(self, op: MOSDOp) -> MOSDOpReply:
        pool = self.osdmap.pools[op.pool_id]
        pg, acting = self._acting(pool, op.oid)
        if self._primary(pool, pg, acting) != self.osd_id:
            return MOSDOpReply(ok=False, code=-errno.ESTALE,
                               error="not primary")
        live = [a for a in acting if a != CRUSH_ITEM_NONE]
        if len(live) < pool.min_size:
            return MOSDOpReply(
                ok=False, code=-errno.EAGAIN,
                error=f"degraded below min_size ({len(live)}/{pool.min_size})",
                backoff=float(self.conf.get("osd_backoff_secs", 0.5) or 0),
            )
        log = self._pglog(op.pool_id, pg)
        if log.has_reqid(op.reqid) and op.reqid not in self._failed_writes:
            # client resend of an op we already applied (pg log dups role)
            return MOSDOpReply(ok=True)
        self._failed_writes.discard(op.reqid)
        if op.offset >= 0 and not op.data:
            return MOSDOpReply(ok=True)  # zero-length overwrite: no-op
        cow_err = await self._make_writeable(op, pool, pg, acting)
        if cow_err is not None:
            return cow_err
        if pool.pool_type != "ec":
            return await self._do_write_replicated(op, pool, pg, acting)
        codec = self._codec(pool)
        sinfo = self._sinfo(pool)
        n = codec.get_chunk_count()
        tracked = getattr(op, "_tracked", None)
        parent = tracked.trace if tracked is not None else None
        # the EC pipeline span is a CHILD of the op span (which itself
        # joined the client's trace): the whole write renders as one tree
        span = (parent.child("ec write") if parent is not None
                else self.ctx.tracer.new_trace("ec write"))
        span.event("start ec write")

        def mark(event: str) -> None:
            if tracked is not None:
                tracked.mark_event(event)
        # splice plan: chunk_off >= 0 means each shard splices `blobs[shard]`
        # into its stored blob at chunk_off (per-stripe RMW, the reference's
        # write plan ECTransaction.cc:37-95); -1 replaces the whole blob
        data = op.data
        chunk_off = -1
        shard_size = 0
        base_version = 0
        object_size = len(op.data)
        full_for_cache: Optional[bytes] = bytes(op.data)
        if op.offset >= 0:
            span.event("rmw read")
            mark("rmw_read")
            # writeback fence: a partial overwrite splices against the
            # STORED shard blobs, and a dirty resident means the stored
            # local shard is behind the acked bytes — flush it first so
            # the splice precondition (prior_version match) composes
            # with reality instead of degrading every RMW to a full
            # rewrite
            _ps = self._paged_store()
            if _ps is not None \
                    and _ps.is_dirty(self._planar_key(op.pool_id, op.oid)):
                if await self._tier_flush_any(
                        self._planar_key(op.pool_id, op.oid)):
                    self.tier_perf.inc("flush_rmw")
                else:
                    self.tier_perf.inc("flush_error")
            # partial overwrite: read ONLY the stripes the write touches
            # (try_state_to_reads, ECBackend.cc:1915); the extent cache
            # pins recently decoded objects so back-to-back partial writes
            # skip the read entirely
            s0, slen = sinfo.offset_len_to_stripe_bounds(
                op.offset, len(op.data))
            seg: Optional[bytes] = None
            cached = self._cache_get(op.pool_id, op.oid)
            if cached is not None:
                base_version, cached_data = cached
                base = bytearray(cached_data)
                if len(base) < op.offset:
                    base.extend(b"\x00" * (op.offset - len(base)))
                base[op.offset:op.offset + len(op.data)] = op.data
                full = bytes(base)
                object_size = len(full)
                seg = full[s0:s0 + slen]
                full_for_cache = full
            else:
                # extent-granular hit (reference ExtentCache pinning): a
                # prior RMW on an overlapping range left its decoded
                # stripes here — no shard reads at all
                ranged = self._extent_cache.get_range(
                    (op.pool_id, op.oid), s0, slen)
                got = None
                if ranged is not None and ranged[2] > 0                         and len(ranged[1]) == slen:
                    base_version, stripes, old_size = ranged
                    self.perf.inc("rmw_extent_hits")
                    got = (old_size, stripes, base_version)
                else:
                    got = await self._read_stripe_range(
                        op, pool, codec, sinfo, s0, slen)
                if got is not None:
                    old_size, stripes, base_version = got
                    seg_buf = bytearray(stripes)
                    lo = op.offset - s0
                    seg_buf[lo:lo + len(op.data)] = op.data
                    seg = bytes(seg_buf)
                    object_size = max(old_size, op.offset + len(op.data))
                    full_for_cache = None  # only the segment is in hand
                else:
                    # degraded / inconsistent / absent: whole-object path
                    read = await self._do_read(
                        MOSDOp(op="read", pool_id=op.pool_id, oid=op.oid))
                    base = bytearray(as_bytes(read.data)) \
                        if read.ok else bytearray()
                    if len(base) < op.offset:
                        base.extend(b"\x00" * (op.offset - len(base)))
                    base[op.offset:op.offset + len(op.data)] = op.data
                    data = bytes(base)
                    object_size = len(data)
                    full_for_cache = data
            if seg is not None:
                self.perf.inc("rmw_partial")
                data = seg
                chunk_off = sinfo.aligned_logical_offset_to_chunk_offset(s0)
                shard_size = sinfo.logical_to_next_chunk_offset(object_size)
        # encode BEFORE allocating the PG-log eversion: the batched encode
        # awaits the device queue, and the version->local-apply window
        # below must stay SYNCHRONOUS — a concurrent log merge (repair
        # task / unsolicited log reply) advancing the head across an await
        # would invalidate a version handed out earlier.
        planar = None
        # write heat + the install decision (the r10 OPEN tail): writes
        # record into the hit set like reads, and residency on write
        # rides the same recency/throttle gate as read promotion — a
        # refused install takes the cheaper non-resident encode lane
        install = self._tier_write_install(op, pool, pg, acting,
                                           len(data),
                                           full=chunk_off < 0)
        if install == "writeback" and chunk_off < 0:
            # replicated-writeback fast ack: commit the RAW object on
            # the cache quorum (our dirty pages + osd_cache_min_size-1
            # acting peers' adopted copies) and ack NOW — the k+m
            # encode and the sub-write fan-out move wholesale into the
            # flush path (_tier_flush_raw_key).  None = quorum short /
            # store refusal: fall through to the synchronous
            # write-through shape below, counted wb_quorum_short.
            fast = await self._tier_fast_ack_write(
                op, pool, pg, acting, data, object_size, span, mark)
            if fast is not None:
                span.finish()
                return fast
            install = "clean"
        mark("ec_encode_dispatched")
        if install is not None and self._planar is not None \
                and chunk_off < 0:
            # full-object write: leave the shard rows planar-resident so
            # later decodes / repair re-encodes skip the unpack boundary
            planar = await planar_encode_async(codec, sinfo, data,
                                               queue=self._ec_queue,
                                               span=span)
        if planar is not None:
            blobs = planar[0]
        else:
            blobs = await batched_encode_async(codec, sinfo, data,
                                               queue=self._ec_queue,
                                               span=span)
        span.event("encoded")
        mark("encoded")
        # one crc pass per shard, shared by the hinfo record and every
        # sub-write's chunk_crc (a fresh object's chained hinfo crc IS
        # the shard crc)
        shard_crcs = ([shard_crc(blobs[i])
                       for i in range(codec.get_chunk_count())]
                      if chunk_off < 0 else None)
        hinfo_blob = (self._hinfo_for(pool, blobs, crcs=shard_crcs)
                      if chunk_off < 0 else b"")
        # Allocate the eversion only after every await above; from here to
        # the local apply the path is synchronous, so the head cannot move
        # underneath us.
        entry = LogEntry(version=log.next_version(self.osdmap.epoch),
                         op="write", oid=op.oid, prior_version=log.head,
                         reqid=op.reqid)
        version = pack_eversion(entry.version)
        entry.object_version = version
        entry_blob = entry.encode()
        tid = uuid.uuid4().hex
        local_ok = 0
        wb_shards: set = set()
        if chunk_off < 0 and planar is None and self._planar is not None:
            # gated / ineligible / empty full write: it supersedes any
            # existing resident, and the resident must die NOW, dirty
            # included — the write-through applies below land the newer
            # version, and a surviving writeback record would later
            # replay its OLD deferred shard bytes over them (the flush
            # validates against the resident's own meta; same
            # synchronous window as the applies, so the agent cannot
            # interleave)
            self._planar.drop(self._planar_key(op.pool_id, op.oid),
                              force=True)
        if install == "writeback" and planar is not None:
            # writeback: the local shard applies defer into dirty pages
            # (log entry commits NOW, flush replays the applies later);
            # still synchronous — no await between the eversion above
            # and here, so the head cannot move underneath the install
            locals_ = [s for s, o_ in enumerate(acting)
                       if o_ == self.osd_id]
            if locals_:
                wb_shards = self._tier_writeback_install(
                    op, pool, pg, planar, version, object_size, entry,
                    locals_, shard_crcs, hinfo_blob, data)
                if wb_shards:
                    span.event(f"writeback install ({len(wb_shards)} "
                               f"local applies deferred)")
        remote: List[Tuple[int, int]] = []  # (shard, osd)
        for shard, osd in enumerate(acting):
            if osd == CRUSH_ITEM_NONE:
                continue
            if osd == self.osd_id:
                if shard in wb_shards:
                    # deferred to flush: the dirty page IS this shard's
                    # copy until then (counted acked — same durability
                    # as the store apply, both are process-local)
                    local_ok += 1
                    continue
                # the local shard gets a sub-write span of its own, so
                # the stitched trace shows ALL k+m shard applies (the
                # remote peers record theirs in their own rings)
                with span.child(f"ec_sub_write s{shard}") as lsp:
                    lsp.tag("osd", self.osd_id).tag("local", True)
                    # memoryview, not bytes(): ownership of the fresh
                    # encode-output row passes to the store (Owned
                    # marking in _apply_shard_write) — no per-shard copy
                    if self._apply_shard_write(
                        op.pool_id, op.oid, shard,
                        memoryview(np.ascontiguousarray(blobs[shard])),
                        version,
                        object_size, pg=pg, entry=entry,
                        chunk_off=chunk_off,
                        shard_size=shard_size, hinfo=hinfo_blob,
                        prior_version=base_version,
                        chunk_crc=(shard_crcs[shard]
                                   if shard_crcs is not None else None),
                    ):
                        local_ok += 1
            else:
                remote.append((shard, osd))
        q = self._collector(tid)
        sends = []
        # trace propagation on the fan-out: each peer joins a child
        # ec_sub_write span under OUR ec-write span (feature-gated)
        w_tid, w_sid = (span.context() if self._trace_on else ("", ""))
        for shard, osd in remote:
            # memoryview: the shard row rides the messenger's blob lane
            # without a bytes() copy; crc reuses the per-shard pass above
            chunk = memoryview(np.ascontiguousarray(blobs[shard]))
            crc = (shard_crcs[shard] if shard_crcs is not None
                   else shard_crc(chunk))
            msg = MECSubWrite(
                pool_id=op.pool_id, pg=pg, oid=op.oid, shard=shard, chunk=chunk,
                version=version, object_size=object_size,
                chunk_crc=crc, tid=tid, reply_to=self.addr,
                log_entry=entry_blob, chunk_off=chunk_off,
                shard_size=shard_size, hinfo=hinfo_blob,
                prior_version=base_version,
                from_osd=self.osd_id, epoch=self.osdmap.epoch,
                trace_id=w_tid, span_id=w_sid,
            )
            sends.append(self.messenger.send(self.osdmap.addr_of(osd), msg))
        # CONCURRENT stripe fan-out: all k+m sub-writes enqueue and their
        # per-connection flushes interleave on the loop, instead of each
        # send serializing on the previous one's socket drain; a failed
        # send counts as a missing ack, not a 5s stall
        sent = 0
        for got in await asyncio.gather(*sends, return_exceptions=True):
            if got is None:
                sent += 1
            elif not isinstance(got, TRANSPORT_ERRORS):
                raise got  # framing bug etc: crash loudly (the _serve rule)
        span.event(f"sub writes sent ({sent})")
        mark("sub_writes_sent")
        mark("waiting_for_subops")
        replies = await self._gather(tid, q, sent)
        span.event("commit gathered")
        mark("commit_gathered")
        span.finish()
        acks = local_ok + sum(1 for r in replies if r.ok)  # self + remote
        if acks < pool.min_size:
            # the entry is logged but the write failed: a same-reqid resend
            # must re-execute rather than be deduped into false success
            self._mark_failed_write(op.reqid)
            self._cache_drop(op.pool_id, op.oid)
            return MOSDOpReply(
                ok=False, code=-errno.EBUSY,
                error=f"write acked by {acks} < min_size {pool.min_size}"
            )
        if acks < len(live):
            # acked but DEGRADED: a member missed its sub-write (lost
            # frame, refused splice).  The reference marks it missing and
            # recovers promptly; waiting for the next interval change
            # would leave the object one failure from loss
            self._kick_recovery(pool, pg)
        if planar is not None and not wb_shards:
            # install the residency only once the write is DURABLE (and
            # under the version it landed as): a failed write must not
            # leave resident rows that reads would serve.  (A writeback
            # install already landed — dirty, pre-fan-out — because its
            # pages ARE the deferred local applies.)
            pkey = self._planar_key(op.pool_id, op.oid)
            k_ = codec.get_data_chunk_count()
            if self._install_resident(pkey, planar, version,
                                      object_size, k_):
                # seed the exit-boundary memo with the just-written
                # bytes: the first resident-hit read serves host bytes
                # instead of paying a device pack (memo_put contract)
                if isinstance(data, bytes) and len(data) == object_size:
                    self._planar.memo_put(pkey, version, data)
        if full_for_cache is not None:
            self._cache_put(op.pool_id, op.oid, version, full_for_cache)
        elif chunk_off >= 0:
            # segment RMW: pin the freshly-written stripes at the NEW
            # version; carry_from upgrades the entry in place (nothing
            # outside this extent changed — our write made the version)
            self._extent_cache.put_extent(
                (op.pool_id, op.oid), version,
                sinfo.aligned_chunk_offset_to_logical_offset(chunk_off),
                data, size_hint=object_size, carry_from=base_version)
        else:
            self._cache_drop(op.pool_id, op.oid)
        return MOSDOpReply(ok=True)

    async def _read_stripe_range(self, op: MOSDOp, pool: PoolInfo, codec,
                                 sinfo: StripeInfo, s0: int,
                                 slen: int) -> Optional[Tuple[int, bytes, int]]:
        """Stripe-scoped RMW read: fetch only the affected chunk ranges of
        a decodable shard set (extent sub-reads) and decode just those
        stripes.  Returns (object_size, segment_bytes, base_version) — the
        segment covers logical [s0, s0+slen) zero-padded past EOF — or None
        when a consistent single-version cut isn't cheaply available
        (degraded, mid-write drift, absent object) and the caller must take
        the full reconstructing read."""
        pg, acting = self._acting(pool, op.oid)
        k = codec.get_data_chunk_count()
        chunk_off = sinfo.aligned_logical_offset_to_chunk_offset(s0)
        clen = slen // k
        available = {shard: osd for shard, osd in enumerate(acting)
                     if osd != CRUSH_ITEM_NONE}
        mapping = codec.get_chunk_mapping()
        want = {mapping[i] if mapping else i for i in range(k)}
        try:
            plan = codec.minimum_to_decode(want, set(available))
        except ErasureCodeError:
            return None
        # a cut older than the log's committed head is a stale survivor;
        # when the log holds NO entry for this oid (trimmed, or written in
        # a prior interval) the log cannot corroborate — stat-probe the
        # shards OUTSIDE the plan in the same fan-out and refuse the cut
        # if any of them holds a newer version (a consistent k-subset of
        # stale survivors would otherwise pass and an acked write's bytes
        # would be spliced away)
        log = self._pglog(op.pool_id, pg)
        latest_logged = max(
            (e.object_version for e in log.entries if e.oid == op.oid),
            default=0)
        probe = ([s for s in available if s not in plan]
                 if latest_logged == 0 else [])
        tid = uuid.uuid4().hex
        pieces: Dict[int, bytes] = {}
        versions: Dict[int, int] = {}
        probe_versions: Dict[int, int] = {}
        sizes: Dict[int, int] = {}
        remote = []
        for shard in list(plan) + probe:
            osd = available[shard]
            stat_only = shard not in plan
            if osd == self.osd_id:
                got = self._store_read((op.pool_id, op.oid, shard))
                if got is not None:
                    blob, meta = got
                    if stat_only:
                        probe_versions[shard] = meta.version
                    else:
                        pieces[shard] = bytes(blob[chunk_off:chunk_off + clen])
                        versions[shard] = meta.version
                        sizes[shard] = meta.object_size
            else:
                remote.append((shard, osd, stat_only))
        q = self._collector(tid)
        sent = 0
        for shard, osd, stat_only in remote:
            try:
                await self.messenger.send(
                    self.osdmap.addr_of(osd),
                    MECSubRead(pool_id=op.pool_id, pg=pg, oid=op.oid,
                               shard=shard, tid=tid, reply_to=self.addr,
                               extents=[(0, 0)] if stat_only
                               else [(chunk_off, clen)]))
                sent += 1
            except TRANSPORT_ERRORS:
                pass
        plan_set = set(plan)
        for r in await self._gather(tid, q, sent):
            if r.ok and r.shard in plan_set:
                # extents replies ride as a BufferList of views (local
                # fastpath hands it over by reference): materialize here
                pieces[r.shard] = as_bytes(r.chunk)
                versions[r.shard] = r.version
                sizes[r.shard] = r.object_size
            elif r.ok:
                probe_versions[r.shard] = r.version
        if len(pieces) < k or len(set(versions.values())) != 1:
            return None
        cut_version = max(versions.values())
        if cut_version < latest_logged:
            return None
        if any(v > cut_version for v in probe_versions.values()):
            return None  # someone holds newer: the cut is a stale survivor
        arrays = {}
        for shard, piece in pieces.items():
            if len(piece) < clen:  # stripes past EOF read back as zeros
                piece = piece + b"\x00" * (clen - len(piece))
            self.perf.inc("rmw_read_bytes", len(piece))
            arrays[shard] = np.frombuffer(piece, dtype=np.uint8)
        seg = await decode_object_async(codec, sinfo, arrays, slen,
                                        queue=self._ec_queue)
        return sizes[next(iter(sizes))], seg, max(versions.values())

    async def _do_read(self, op: MOSDOp,
                       exclude_shards: frozenset = frozenset()) -> MOSDOpReply:
        """Reconstructing read.  `exclude_shards` drops shards KNOWN bad
        (scrub found a crc mismatch) from every source, so a repair read
        cannot launder corruption back into the object."""
        pool = self.osdmap.pools[op.pool_id]
        if pool.pool_type != "ec":
            return await self._do_read_replicated(op, pool, exclude_shards)
        codec = self._codec(pool)
        pg, acting = self._acting(pool, op.oid)
        k = codec.get_data_chunk_count()
        if (self._planar is not None and not exclude_shards
                and self._primary(pool, pg, acting) == self.osd_id):
            # planar fast path — a TRUE zero-shard-read: the primary's PG
            # log is the authoritative per-object version source, so when
            # the HBM resident matches the log's newest entry for this
            # oid, the data rows pack straight out — no sub-reads, no
            # decode.  Any mismatch (trimmed window, rewound log, stale
            # resident, delete) falls through to the quorum path.
            # exclude_shards (scrub repair) always takes the quorum path:
            # repair must observe the STORED shards, not our cache.
            ent = self._pglog(op.pool_id, pg).latest_entry(op.oid)
            if ent is not None and ent.op == "write":
                # meta-only probe (no gather): the paged store would pay
                # a page-table gather for a get_planar here, and the
                # memo inside planar_object_bytes serves the common case
                meta = self._planar.resident_meta(
                    self._planar_key(op.pool_id, op.oid))
                if meta is not None:
                    if (meta and len(meta) >= 3
                            and meta[0] == ent.object_version):
                        data = planar_object_bytes(
                            self._planar,
                            self._planar_key(op.pool_id, op.oid),
                            ent.object_version, k,
                            self._sinfo(pool).chunk_size, meta[2])
                        if data is None:
                            # raw fast-ack resident (w=0, whole-object
                            # bytes, no planar rows): the memo inside
                            # planar_object_bytes missed — gather the
                            # object straight off the page table
                            rr = getattr(self._planar, "read_raw", None)
                            data = rr(self._planar_key(
                                op.pool_id, op.oid)) if rr else None
                        if data is not None:
                            self.perf.inc("planar_read_hits")
                            self.tier_perf.inc("resident_hit")
                            self.tier_perf.inc("resident_hit_bytes",
                                               len(data))
                            t = getattr(op, "_tracked", None)
                            if t is not None:
                                t.mark_event("resident_hit")
                            return MOSDOpReply(ok=True, data=data,
                                               version=ent.object_version)
        available = {
            shard: osd for shard, osd in enumerate(acting)
            if osd != CRUSH_ITEM_NONE and shard not in exclude_shards
        }
        # ask the codec which shards suffice (subchunk-aware plan); the
        # wanted shards are the codec's DATA positions, which mapped codecs
        # (lrc) place at chunk_index(i), not at 0..k-1
        mapping = codec.get_chunk_mapping()
        want = {mapping[i] if mapping else i for i in range(k)}
        try:
            plan = codec.minimum_to_decode(want, set(available))
        except ErasureCodeError:
            # fewer than k live ACTING members (e.g. a pg_temp override
            # whose members died): the data may still exist on past
            # holders — fall through to the shard hunt instead of failing
            plan = []
        tid = uuid.uuid4().hex
        chunks: Dict[int, bytes] = {}
        versions: Dict[int, int] = {}
        sizes: Dict[int, int] = {}
        remote = []
        for shard in plan:
            osd = available[shard]
            if osd == self.osd_id:
                got = self._store_read((op.pool_id, op.oid, shard))
                if got is not None:
                    chunks[shard] = got[0]
                    versions[shard] = got[1].version
                    sizes[shard] = got[1].object_size
            else:
                remote.append((shard, osd))
        q = self._collector(tid)
        tracked = getattr(op, "_tracked", None)
        if tracked is not None:
            tracked.mark_event("sub_reads_sent")
        sent = 0
        for shard, osd in remote:
            msg = MECSubRead(
                pool_id=op.pool_id, pg=pg, oid=op.oid, shard=shard, tid=tid,
                reply_to=self.addr,
            )
            try:
                await self.messenger.send(self.osdmap.addr_of(osd), msg)
                sent += 1
            except TRANSPORT_ERRORS:
                pass
        for r in await self._gather(tid, q, sent):
            if r.ok:
                chunks[r.shard] = r.chunk
                versions[r.shard] = r.version
                sizes[r.shard] = r.object_size
        # consistent-version cut: only shards at ONE version may mix in a
        # decode.  Prefer the newest version that is COMPLETE (>= k
        # shards): a failed overwrite can leave a partial newer version
        # that must not poison reads of the intact older one (the
        # reference's last_complete / rollback semantics).
        newest = max(versions.values()) if versions else -1
        complete = {s: c for s, c in chunks.items() if versions[s] == newest}
        if len(complete) < k:
            # shard hunt: shards carry their id, so a degraded read
            # survives placement drift between failure and recovery
            # (send_all_remaining_reads + missing-set role).  Scoped to
            # the PG's possible holders first; if that cannot assemble k
            # shards (purge/bookkeeping messages can be lost under churn)
            # retry once as a cluster-wide broadcast before failing.
            viable: List[int] = []
            by_version: Dict[int, Dict[int, Tuple[bytes, int]]] = {}
            hunt_complete = False
            for broadcast in (False, True):
                hunted, hunt_complete = await self._fetch_all_shards(
                    op.pool_id, op.oid, broadcast=broadcast)
                by_version = {}
                for s_, c_ in chunks.items():
                    by_version.setdefault(versions[s_], {})[s_] = (c_, sizes[s_])
                for shard, chunk, version, osize in hunted:
                    if shard in exclude_shards:
                        continue
                    by_version.setdefault(version, {}).setdefault(
                        shard, (chunk, osize))
                viable = [v for v, m in by_version.items() if len(m) >= k]
                if viable:
                    break
            if not by_version:
                return self._absent_reply(hunt_complete, "shards")
            if not viable:
                return MOSDOpReply(ok=False, code=-errno.EAGAIN,
                                   error="cannot reconstruct: shards missing")
            newest = max(viable)
            chunks = {s_: cm[0] for s_, cm in by_version[newest].items()}
            sizes = {s_: cm[1] for s_, cm in by_version[newest].items()}
            versions = {s_: newest for s_ in chunks}
        else:
            chunks = complete
        object_size = sizes[max(sizes, key=lambda s: versions.get(s, 0))]
        if self._planar is not None:
            # planar residency: the resident rows at this exact version
            # ARE the object — pack the data rows once, skip the decode
            got_planar = planar_object_bytes(
                self._planar, self._planar_key(op.pool_id, op.oid),
                newest, k, self._sinfo(pool).chunk_size, object_size)
            if got_planar is not None:
                # decode skipped (shard reads already happened): counts
                # as a resident hit for the tier — the resident absorbed
                # the decode dispatch even though the log could not
                # corroborate the zero-shard-read path above
                self.tier_perf.inc("resident_hit")
                self.tier_perf.inc("resident_hit_bytes", len(got_planar))
                self._cache_put(op.pool_id, op.oid, newest, got_planar)
                return MOSDOpReply(ok=True, data=got_planar, version=newest)
        arrays = {s: np.frombuffer(c, dtype=np.uint8) for s, c in chunks.items()}
        if tracked is not None:
            tracked.mark_event("decode_dispatched")
        # scatter=True: the healthy-read fast path hands back a
        # BufferList of stripe VIEWS over the sub-read reply buffers —
        # the reply writev's them as one blob, no gather copy on the
        # primary.  Consumers that need contiguous bytes (RMW base,
        # recovery re-encode, the local-fastpath client) materialize at
        # their own boundary (messenger.as_bytes).
        data = await decode_object_async(codec, self._sinfo(pool), arrays,
                                         object_size, queue=self._ec_queue,
                                         scatter=True)
        if tracked is not None:
            tracked.mark_event("decoded")
        if not isinstance(data, BufferList):
            # a scatter result is views over this read's rx buffers; the
            # RMW cache wants a stable contiguous copy — caching it would
            # re-pay exactly the gather the scatter path avoids
            self._cache_put(op.pool_id, op.oid, newest, data)
        return MOSDOpReply(ok=True, data=data, version=newest)

    class _AllShards:
        """Replicated 'encoding': every position gets the full object."""

        def __init__(self, data: bytes):
            self.data = data

        def __getitem__(self, shard: int) -> bytes:
            return self.data

    async def _encode_for(self, pool: PoolInfo, data: bytes,
                          oid: Optional[str] = None, version: int = -1):
        if pool.pool_type == "ec":
            if self._planar is not None and oid is not None:
                # residency: the resident planar rows at this version ARE
                # the encoded object — one pack, zero matmuls
                rows = planar_rows(
                    self._planar, self._planar_key(pool.pool_id, oid),
                    version)
                if rows is not None:
                    return rows
            return await batched_encode_async(
                self._codec(pool), self._sinfo(pool), data,
                queue=self._ec_queue)
        return OSD._AllShards(data)

    def _cls_xattrs(self, pool_id: int, oid: str) -> Dict[str, bytes]:
        """Object-class xattrs to ride a recovery push — minus the
        hinfo_key record, which is per-shard state the push recomputes."""
        attrs = dict(self.store.getattrs((pool_id, oid, 0)))
        attrs.pop(HashInfo.XATTR_KEY, None)
        return attrs

    def _hinfo_for(self, pool: PoolInfo, encoded,
                   crcs: Optional[List[int]] = None) -> bytes:
        """HashInfo blob for a freshly (re-)encoded object (rides recovery
        pushes so the hinfo_key xattr survives, ECUtil.h:101).  A fresh
        object's chained crc equals the plain shard crc, so callers that
        already computed per-shard crcs pass them instead of re-hashing
        every chunk."""
        if pool.pool_type != "ec":
            return b""
        n = self._codec(pool).get_chunk_count()
        if crcs is not None:
            sizes = len(encoded[0])
            h = HashInfo(n, total_chunk_size=sizes, crcs=list(crcs))
            return h.encode()
        h = HashInfo(n)
        h.append({i: bytes(encoded[i]) for i in range(n)})
        return h.encode()

    # -- ReplicatedBackend (reference src/osd/ReplicatedBackend.cc) ----------

    async def _do_write_replicated(self, op: MOSDOp, pool: PoolInfo,
                                   pg: int, acting: List[int]) -> MOSDOpReply:
        """Full copies to every acting position; same log/ack machinery as
        EC but without encode.  Dedupe/failed-write gating already happened
        in _do_write, the single entry point."""
        log = self._pglog(op.pool_id, pg)
        data = op.data
        if op.offset >= 0:
            cached = self._cache_get(op.pool_id, op.oid)
            if cached is not None:
                base = bytearray(cached[1])
            else:
                read = await self._do_read_replicated(
                    MOSDOp(op="read", pool_id=op.pool_id, oid=op.oid), pool)
                base = bytearray(read.data) if read.ok else bytearray()
            if len(base) < op.offset:
                base.extend(b"\x00" * (op.offset - len(base)))
            base[op.offset:op.offset + len(op.data)] = op.data
            data = bytes(base)
        entry = LogEntry(version=log.next_version(self.osdmap.epoch),
                         op="write", oid=op.oid, prior_version=log.head,
                         reqid=op.reqid)
        version = pack_eversion(entry.version)
        entry.object_version = version
        entry_blob = entry.encode()
        tid = uuid.uuid4().hex
        q = self._collector(tid)
        sent = 0
        for shard, osd in enumerate(acting):
            if osd == CRUSH_ITEM_NONE:
                continue
            if osd == self.osd_id:
                self._apply_shard_write(op.pool_id, op.oid, shard, data,
                                        version, len(data), pg=pg, entry=entry)
            else:
                try:
                    await self.messenger.send(
                        self.osdmap.addr_of(osd),
                        MECSubWrite(pool_id=op.pool_id, pg=pg, oid=op.oid,
                                    shard=shard, chunk=data, version=version,
                                    object_size=len(data),
                                    chunk_crc=shard_crc(data), tid=tid,
                                    reply_to=self.addr, log_entry=entry_blob,
                                    from_osd=self.osd_id,
                                    epoch=self.osdmap.epoch))
                    sent += 1
                except TRANSPORT_ERRORS:
                    pass
        replies = await self._gather(tid, q, sent)
        acks = 1 + sum(1 for r in replies if r.ok)
        if acks < pool.min_size:
            self._mark_failed_write(op.reqid)
            return MOSDOpReply(
                ok=False, code=-errno.EBUSY,
                error=f"write acked by {acks} < min_size {pool.min_size}")
        if acks < len([a for a in acting if a != CRUSH_ITEM_NONE]):
            self._kick_recovery(pool, pg)  # degraded write: recover now
        self._cache_put(op.pool_id, op.oid, version, data)
        return MOSDOpReply(ok=True)

    async def _do_read_replicated(self, op: MOSDOp, pool: PoolInfo,
                                  exclude_shards: frozenset = frozenset()
                                  ) -> MOSDOpReply:
        """Serve from the local copy, else ask acting peers; newest wins."""
        pg, acting = self._acting(pool, op.oid)
        best: Optional[Tuple[bytes, int, int]] = None  # data, version, size
        for shard, osd in enumerate(acting):
            if osd != self.osd_id or shard in exclude_shards:
                continue
            got = self._store_read((op.pool_id, op.oid, shard))
            if got is not None and (best is None or got[1].version > best[1]):
                best = (got[0], got[1].version, got[1].object_size)
        # a local copy older than what the PG log says was committed is a
        # stale survivor from a degraded write: hunt for the newer copy
        log = self._pglog(op.pool_id, pg)
        latest_logged = max(
            (e.object_version for e in log.entries if e.oid == op.oid),
            default=0,
        )
        if best is not None and best[1] < latest_logged:
            best = None
        hunt_complete = True
        if best is None:
            # a copy is a copy regardless of the position key it was stored
            # under in an earlier interval: hunt every up OSD for any shard
            # of the oid and take the newest (placement-drift tolerance)
            hunted, hunt_complete = await self._fetch_all_shards(
                op.pool_id, op.oid)
            for shard, chunk, version, osize in hunted:
                if shard in exclude_shards:
                    continue
                if best is None or version > best[1]:
                    best = (chunk, version, osize)
        if best is None:
            return self._absent_reply(hunt_complete, "copies")
        data, version, size = best
        self._cache_put(op.pool_id, op.oid, version, data[:size])
        return MOSDOpReply(ok=True, data=data[:size], version=version)

    # -- object classes (reference src/cls/, ClassHandler) -------------------

    async def _do_call(self, op: MOSDOp) -> MOSDOpReply:
        from ceph_tpu.services.cls import ClsContext
        from ceph_tpu.services.cls import registry as cls_registry

        pool = self.osdmap.pools[op.pool_id]
        if pool.pool_type == "ec":
            # reference parity: EC pools do not support class calls
            return MOSDOpReply(ok=False, code=-errno.EOPNOTSUPP,
                               error="EOPNOTSUPP: class calls on EC pools")
        pg, acting = self._acting(pool, op.oid)
        if self._primary(pool, pg, acting) != self.osd_id:
            return MOSDOpReply(ok=False, code=-errno.ESTALE,
                               error="not primary")
        # class methods are not idempotent (refcount.get): a resend whose
        # reply was lost must return the ORIGINAL result, not re-execute
        if op.reqid and op.reqid in self._call_results:
            return self._call_results[op.reqid]
        fn = cls_registry.get(op.cls, op.method)
        if fn is None:
            return MOSDOpReply(ok=False, code=-errno.ENOENT,
                               error=f"ENOENT: no class {op.cls}.{op.method}")
        # cls state lives under a CANONICAL shard key (0) so it survives
        # acting-position drift; data via the replicated read path (a
        # just-promoted primary may not hold a local copy)
        key = (op.pool_id, op.oid, 0)
        # the read-execute-write MUST be atomic per object — that is the
        # entire contract in-OSD classes exist for (reference
        # ClassHandler under the PG lock, src/osd/ClassHandler.cc).  The
        # sharded queue serializes per PG in steady state, but a map
        # race around pool creation can key two calls differently, so
        # the primary holds its own per-object critical section.
        async with self._object_critical_section(op.pool_id, op.oid):
            # resend racing the original: it queued on the lock; replay
            # the original's reply instead of re-executing
            if op.reqid and op.reqid in self._call_results:
                return self._call_results[op.reqid]
            reply = await self._do_call_locked(op, pool, pg, acting, fn,
                                               key)
        if reply.ok:
            self._cache_call_reply(op.reqid, reply)
        return reply

    @contextlib.asynccontextmanager
    async def _object_critical_section(self, pool_id: int, oid: str):
        """Refcounted per-object mutex shared by cls calls and compound
        (multi) ops — the two must be mutually atomic.  Eviction never
        orphans a lock another task still waits on."""
        from ceph_tpu.common.lockdep import make_async_mutex

        ent = self._cls_locks.setdefault(
            (pool_id, oid), [make_async_mutex("osd-cls-call"), 0])
        ent[1] += 1  # waiter refcount
        try:
            async with ent[0]:
                yield
        finally:
            ent[1] -= 1
            while len(self._cls_locks) > 512:
                k = next(iter(self._cls_locks))
                if self._cls_locks[k][1] > 0:
                    break  # oldest still referenced: trim next time
                del self._cls_locks[k]

    def _cache_call_reply(self, reqid: str, reply: MOSDOpReply) -> None:
        """Bounded replay cache for non-idempotent ops (cls calls,
        multis, notifies): a resend whose reply was lost replays the
        ORIGINAL result instead of re-executing."""
        if not reqid:
            return
        self._call_results[reqid] = reply
        while len(self._call_results) > 512:
            self._call_results.pop(next(iter(self._call_results)))

    async def _do_call_locked(self, op, pool, pg, acting, fn,
                              key) -> MOSDOpReply:
        from ceph_tpu.services.cls import ClsContext

        read = await self._do_read_replicated(
            MOSDOp(op="read", pool_id=op.pool_id, oid=op.oid), pool)
        hctx = ClsContext(read.data if read.ok else None,
                          dict(self.store.getattrs(key)))
        ret, out = fn(hctx, op.data)
        if hctx.data_dirty and ret >= 0:
            wr = await self._do_write_replicated(
                MOSDOp(op="write", pool_id=op.pool_id, oid=op.oid,
                       data=hctx.data, reqid=uuid.uuid4().hex),
                pool, pg, acting)
            if not wr.ok:
                return MOSDOpReply(ok=False, code=wr.code,
                                   error=wr.error)
        if hctx.xattrs_dirty and ret >= 0:
            # xattr apply stays INSIDE the critical section: the
            # advisory-lock class's read-check-set is only atomic if
            # the next call observes these bytes
            for name, value in hctx.xattrs.items():
                self.store.setattr(key, name, value)
            # replicate xattr state to the other acting members so a
            # failover primary still sees locks/refcounts (same
            # queue-on-failure discipline as the multi path — cls lock
            # state must not go silently stale either)
            for shard, osd in enumerate(acting):
                if osd in (CRUSH_ITEM_NONE, self.osd_id):
                    continue
                await self._send_meta_repl(
                    osd, MSetXattrs(pool_id=op.pool_id, oid=op.oid,
                                    shard=0, xattrs=dict(hctx.xattrs)))
        return MOSDOpReply(ok=True, data=pickle.dumps((ret, out)))

    # -- compound atomic ops (reference MOSDOp vector<OSDOp>,
    # PrimaryLogPG::do_osd_ops; client side ObjectWriteOperation /
    # neorados WriteOp) ------------------------------------------------------

    # sub-ops whose execution needs the object's prior data image; a multi
    # containing none of these serves existence/version/size from a cheap
    # metadata stat instead of a full (possibly decoding) head read
    _MULTI_NEEDS_DATA = frozenset({
        "read", "write", "append", "truncate", "zero", "call",
    })
    _MULTI_OMAP = frozenset({"omap_set", "omap_rm_keys", "omap_clear",
                             "omap_get_vals", "omap_get_keys"})
    # sub-ops allowed on EC pools (reference parity: EC pools support
    # neither omap nor class calls — doc/dev/osd_internals/erasure_coding)
    _MULTI_EC_OK = frozenset({
        "create", "assert_exists", "assert_version", "cmpxattr",
        "read", "stat", "getxattr", "getxattrs",
        "write", "write_full", "append", "truncate", "zero", "remove",
        "setxattr", "rmxattr",
    })

    async def _do_multi(self, op: MOSDOp) -> MOSDOpReply:
        """Execute op.ops — an ordered vector of (name, kwargs) sub-ops —
        atomically on one object.  All-or-nothing: sub-ops run against a
        STAGED image (data bytes + xattrs + omap) under the object's
        critical section; nothing touches the store or the wire until the
        whole vector has succeeded, so a failing assert/sub-op aborts with
        zero side effects.  Reads inside the vector observe earlier
        staged writes (reference do_osd_ops execution order)."""
        pool = self.osdmap.pools.get(op.pool_id)
        if pool is None:
            return MOSDOpReply(ok=False, code=-errno.ENOENT,
                               error="no such pool")
        pg, acting = self._acting(pool, op.oid)
        if self._primary(pool, pg, acting) != self.osd_id:
            return MOSDOpReply(ok=False, code=-errno.ESTALE,
                               error="not primary")
        # compound ops are not idempotent (append, cls calls): replay the
        # original reply on a resend, exactly as _do_call does
        if op.reqid and op.reqid in self._call_results:
            return self._call_results[op.reqid]
        if pool.pool_type == "ec":
            for i, (name, _kw) in enumerate(op.ops):
                if name not in self._MULTI_EC_OK:
                    return MOSDOpReply(
                        ok=False, code=-errno.EOPNOTSUPP,
                        error=f"EOPNOTSUPP: sub-op {i} ({name}) on EC pool")
        # the SAME per-object critical section cls calls use: a multi and
        # a cls call (or two multis) on one object serialize, so the
        # read-stage-commit below is atomic per object
        async with self._object_critical_section(op.pool_id, op.oid):
            # re-check the replay cache INSIDE the section: a resend
            # racing the original execution queues on the lock, then
            # finds the original's reply here instead of re-applying a
            # non-idempotent vector
            if op.reqid and op.reqid in self._call_results:
                return self._call_results[op.reqid]
            reply = await self._do_multi_locked(op, pool, pg, acting)
        if reply.ok:
            # only successes replay; a failed multi applied nothing, so a
            # resend may legitimately re-execute (and could then succeed)
            self._cache_call_reply(op.reqid, reply)
        return reply

    async def _do_multi_locked(self, op: MOSDOp, pool: PoolInfo,
                               pg: int, acting: List[int]) -> MOSDOpReply:
        from ceph_tpu.services.cls import ClsContext
        from ceph_tpu.services.cls import registry as cls_registry

        key0 = (op.pool_id, op.oid, 0)  # canonical metadata shard (cls role)
        # -- gather the current image --------------------------------------
        exists = False
        data = bytearray()
        data_loaded = False  # False: `size` is authoritative, not len(data)
        size = 0
        version = 0
        if any(name in self._MULTI_NEEDS_DATA for name, _ in op.ops):
            read = await self._do_read(
                MOSDOp(op="read", pool_id=op.pool_id, oid=op.oid))
            if read.ok:
                exists, data, version = (
                    True, bytearray(as_bytes(read.data)), read.version)
                data_loaded = True
            elif read.code != -errno.ENOENT:
                # transient failure reading the head: the multi must not
                # run against a guessed image — bubble the retryable error
                return MOSDOpReply(ok=False, code=read.code,
                                   error=read.error, backoff=read.backoff)
        else:
            # metadata-only vector: existence + version + size from the
            # stat path (shard metadata fan-out, no payload transfer)
            st = await self._do_stat(
                MOSDOp(op="stat", pool_id=op.pool_id, oid=op.oid))
            if st.ok:
                exists, version, size = True, st.version, int(st.data or b"0")
            elif st.code != -errno.ENOENT:
                return MOSDOpReply(ok=False, code=st.code,
                                   error=st.error, backoff=st.backoff)
        reserved = {self.SNAPSET_XATTR, HashInfo.XATTR_KEY}
        try:
            xattrs = {k: v for k, v in self.store.getattrs(key0).items()
                      if k not in reserved}
        except NotImplementedError:
            xattrs = {}
        for i, (name, kw) in enumerate(op.ops):
            if (name in ("setxattr", "rmxattr", "getxattr", "cmpxattr")
                    and kw.get("name") in reserved):
                return MOSDOpReply(
                    ok=False, code=-errno.EINVAL,
                    error=f"sub-op {i} ({name}): reserved xattr name",
                    data=pickle.dumps([]))
        omap: Dict[str, bytes] = {}
        if any(name in self._MULTI_OMAP for name, _ in op.ops):
            try:
                omap = dict(self.store.omap_get(key0))
            except NotImplementedError:
                omap = {}
        # -- staged execution ----------------------------------------------
        results: List[Tuple[int, object]] = []
        data_dirty = False
        removed = False
        xattr_sets: Dict[str, bytes] = {}
        xattr_rms: set = set()
        omap_cleared = False
        omap_sets: Dict[str, bytes] = {}
        omap_rms: set = set()

        def fail(i: int, name: str, code: int, why: str) -> MOSDOpReply:
            return MOSDOpReply(
                ok=False, code=code,
                error=f"sub-op {i} ({name}): {why}",
                data=pickle.dumps(results))

        for i, (name, kw) in enumerate(op.ops):
            rval = 0
            out: object = None
            if name == "create":
                if kw.get("exclusive") and exists:
                    return fail(i, name, -errno.EEXIST, "object exists")
                if not exists:
                    exists, data_dirty, removed = True, True, False
                    data_loaded = True  # fresh empty image IS the data
            elif name == "assert_exists":
                if not exists:
                    return fail(i, name, -errno.ENOENT, "object absent")
            elif name == "assert_version":
                want = int(kw.get("version", 0))
                if not exists or version != want:
                    return fail(i, name, -errno.ERANGE,
                                f"version {version} != asserted {want}")
            elif name == "cmpxattr":
                if not exists:
                    return fail(i, name, -errno.ENOENT, "object absent")
                if xattrs.get(kw["name"]) != kw.get("value"):
                    return fail(i, name, -errno.ECANCELED,
                                "xattr comparison failed")
            elif name == "read":
                if not exists:
                    return fail(i, name, -errno.ENOENT, "object absent")
                off = int(kw.get("offset", 0))
                length = kw.get("length")
                end = len(data) if length is None else off + int(length)
                out = bytes(data[off:end])
            elif name == "stat":
                if not exists:
                    return fail(i, name, -errno.ENOENT, "object absent")
                out = {"size": len(data) if data_loaded else size,
                       "version": version}
            elif name == "getxattr":
                if not exists:
                    return fail(i, name, -errno.ENOENT, "object absent")
                val = xattrs.get(kw["name"])
                if val is None:
                    return fail(i, name, -errno.ENODATA,
                                f"no xattr {kw['name']!r}")
                out = val
            elif name == "getxattrs":
                if not exists:
                    return fail(i, name, -errno.ENOENT, "object absent")
                out = dict(xattrs)
            elif name == "omap_get_vals":
                if not exists:
                    return fail(i, name, -errno.ENOENT, "object absent")
                out = dict(omap)
            elif name == "omap_get_keys":
                if not exists:
                    return fail(i, name, -errno.ENOENT, "object absent")
                out = sorted(omap)
            elif name == "write":
                off = int(kw.get("offset", 0))
                blob = kw["data"]
                if len(data) < off:
                    data.extend(b"\x00" * (off - len(data)))
                data[off:off + len(blob)] = blob
                exists, data_dirty, removed = True, True, False
            elif name == "write_full":
                data = bytearray(kw["data"])
                exists, data_dirty, removed = True, True, False
            elif name == "append":
                data.extend(kw["data"])
                exists, data_dirty, removed = True, True, False
            elif name == "truncate":
                size = int(kw.get("size", 0))
                if len(data) < size:
                    data.extend(b"\x00" * (size - len(data)))
                else:
                    del data[size:]
                exists, data_dirty, removed = True, True, False
            elif name == "zero":
                off, length = int(kw.get("offset", 0)), int(kw["length"])
                if len(data) < off + length:
                    data.extend(b"\x00" * (off + length - len(data)))
                data[off:off + length] = b"\x00" * length
                exists, data_dirty, removed = True, True, False
            elif name == "remove":
                if not exists:
                    return fail(i, name, -errno.ENOENT, "object absent")
                exists, removed, data_dirty = False, True, False
                data = bytearray()
                # a removed object has no metadata: later sub-ops must
                # not see it, earlier-staged sets must not be applied,
                # and commit purges the persisted user names
                xattr_rms.update(xattrs)
                xattrs.clear()
                xattr_sets.clear()
                omap.clear()
                omap_sets.clear()
                omap_rms.clear()
                omap_cleared = True
            elif name == "setxattr":
                if removed:  # write-class op after remove recreates
                    exists, data_dirty, removed = True, True, False
                    data_loaded = True
                xattrs[kw["name"]] = kw["value"]
                xattr_sets[kw["name"]] = kw["value"]
                xattr_rms.discard(kw["name"])
            elif name == "rmxattr":
                if kw["name"] not in xattrs:
                    return fail(i, name, -errno.ENODATA,
                                f"no xattr {kw['name']!r}")
                del xattrs[kw["name"]]
                xattr_sets.pop(kw["name"], None)
                xattr_rms.add(kw["name"])
            elif name == "omap_set":
                if removed:  # write-class op after remove recreates
                    exists, data_dirty, removed = True, True, False
                    data_loaded = True
                entries = dict(kw["entries"])
                omap.update(entries)
                omap_sets.update(entries)
                omap_rms.difference_update(entries)
            elif name == "omap_rm_keys":
                for k in kw["keys"]:
                    omap.pop(k, None)
                    omap_sets.pop(k, None)
                    omap_rms.add(k)
            elif name == "omap_clear":
                omap.clear()
                omap_sets.clear()
                omap_rms.clear()
                omap_cleared = True
            elif name == "call":
                fn = cls_registry.get(kw["cls"], kw["method"])
                if fn is None:
                    return fail(i, name, -errno.ENOENT,
                                f"no class {kw['cls']}.{kw['method']}")
                hctx = ClsContext(bytes(data) if exists else None,
                                  dict(xattrs))
                ret, cout = fn(hctx, kw.get("input", b""))
                if ret < 0:
                    return fail(i, name, ret,
                                f"class {kw['cls']}.{kw['method']} -> {ret}")
                if hctx.data_dirty:
                    data = bytearray(hctx.data or b"")
                    exists, data_dirty, removed = True, True, False
                if hctx.xattrs_dirty:
                    for k, v in hctx.xattrs.items():
                        if xattrs.get(k) != v:
                            xattr_sets[k] = v
                            xattr_rms.discard(k)
                    for k in list(xattrs):
                        if k not in hctx.xattrs:
                            xattr_sets.pop(k, None)
                            xattr_rms.add(k)
                    xattrs = dict(hctx.xattrs)
                rval, out = ret, cout
            else:
                return fail(i, name, -errno.EINVAL, "unknown sub-op")
            results.append((rval, out))
        # -- commit (all sub-ops passed) -----------------------------------
        meta_dirty = bool(xattr_sets or xattr_rms or omap_sets or omap_rms
                          or omap_cleared)
        if not exists and not removed and meta_dirty:
            # metadata mutation on a nonexistent object creates it
            # (reference: every write-class op, setxattr/omap included,
            # creates the object) — commit an empty data write so the
            # object has a PG-log identity, not just orphan metadata
            exists, data_dirty, data_loaded = True, True, True
        elif exists and not removed and meta_dirty and not data_dirty:
            # metadata mutation on an EXISTING object must still bump the
            # object version (reference: every op logs), or two
            # assert_version CAS writers racing on xattrs/omap would both
            # pass the same guard and silently lose one update
            if not data_loaded:
                read = await self._do_read(
                    MOSDOp(op="read", pool_id=op.pool_id, oid=op.oid))
                if read.ok:
                    data = bytearray(as_bytes(read.data))
                    data_loaded = True
                elif read.code != -errno.ENOENT:
                    return MOSDOpReply(ok=False, code=read.code,
                                       error=read.error,
                                       backoff=read.backoff)
            data_dirty = True
        if removed:
            dr = await self._do_delete(MOSDOp(
                op="delete", pool_id=op.pool_id, oid=op.oid,
                reqid=uuid.uuid4().hex, snapc_seq=op.snapc_seq,
                snapc_snaps=list(op.snapc_snaps)))
            if not dr.ok and dr.code != -errno.ENOENT:
                return MOSDOpReply(ok=False, code=dr.code, error=dr.error,
                                   backoff=dr.backoff)
        elif data_dirty:
            wr = await self._do_write(MOSDOp(
                op="write", pool_id=op.pool_id, oid=op.oid,
                data=bytes(data), reqid=uuid.uuid4().hex,
                snapc_seq=op.snapc_seq, snapc_snaps=list(op.snapc_snaps)))
            if not wr.ok:
                # data commit failed: xattr/omap staging is NOT applied —
                # the all-or-nothing contract holds even at commit time
                return MOSDOpReply(ok=False, code=wr.code, error=wr.error,
                                   backoff=wr.backoff)
        if xattr_sets or xattr_rms:
            for k, v in xattr_sets.items():
                self.store.setattr(key0, k, v)
            for k in xattr_rms:
                try:
                    self.store.rmattr(key0, k)
                except NotImplementedError:
                    pass
        if omap_cleared or omap_sets or omap_rms:
            try:
                if omap_cleared:
                    self.store.omap_rm(key0, list(self.store.omap_get(key0)))
                if omap_sets:
                    self.store.omap_set(key0, omap_sets)
                if omap_rms:
                    self.store.omap_rm(key0, sorted(omap_rms))
            except NotImplementedError:
                pass
        # replicate metadata mutations to the acting peers so a failover
        # primary serves the same xattrs/omap (cls durability discipline).
        # A failed send is queued for retry, never dropped: silently
        # losing one leaves the replica stale until the next deep scrub.
        if xattr_sets or xattr_rms or omap_cleared or omap_sets or omap_rms:
            msgs = []
            if xattr_sets or xattr_rms:
                msgs.append(MSetXattrs(pool_id=op.pool_id, oid=op.oid,
                                       shard=0, xattrs=dict(xattr_sets),
                                       removals=sorted(xattr_rms)))
            if omap_cleared or omap_sets or omap_rms:
                msgs.append(MSetOmap(pool_id=op.pool_id, oid=op.oid,
                                     shard=0, clear=omap_cleared,
                                     entries=dict(omap_sets),
                                     removals=sorted(omap_rms)))
            for shard, osd in enumerate(acting):
                if osd in (CRUSH_ITEM_NONE, self.osd_id):
                    continue
                for msg in msgs:
                    await self._send_meta_repl(osd, msg)
        return MOSDOpReply(ok=True, data=pickle.dumps(results),
                           version=version)

    async def _send_meta_repl(self, osd: int, msg) -> None:
        """Send one metadata-replication message (MSetXattrs/MSetOmap)
        to an acting peer, preserving per-peer FIFO order: while earlier
        messages to this peer sit in the retry queue, new ones must
        queue BEHIND them — a direct send racing ahead of a queued
        older mutation would let the pump later overwrite newer state
        with stale bytes."""
        if self._meta_repl_pending.get(osd):
            self._queue_meta_repl(osd, msg)
            return
        try:
            await self.messenger.send(self.osdmap.addr_of(osd), msg)
        except TRANSPORT_ERRORS:
            self._queue_meta_repl(osd, msg)

    def _queue_meta_repl(self, osd: int, msg) -> None:
        """Queue a failed MSetXattrs/MSetOmap for redelivery to `osd`
        (FIFO per peer — reordering a clear+set sequence corrupts the
        replica) and make sure the retry pump is running.  Bounded: on
        overflow the OLDEST entry is dropped with a cluster-visible
        error, so sustained unreachability degrades loudly, not
        silently."""
        q = self._meta_repl_pending.setdefault(osd, deque())
        q.append(msg)
        while len(q) > 4096:
            dropped = q.popleft()
            self.perf.inc("meta_repl_dropped")
            self.ctx.log.error(
                "osd", f"meta replication queue to osd.{osd} overflowed; "
                f"dropping {type(dropped).__name__} for "
                f"{dropped.pool_id}/{dropped.oid} (replica stale until "
                "next deep scrub)")
        if self._meta_repl_task is None or self._meta_repl_task.done():
            self._meta_repl_task = asyncio.get_running_loop().create_task(
                self._meta_repl_pump())

    async def _meta_repl_pump(self) -> None:
        """Drain the per-peer metadata-replication retry queues with
        backoff.  A peer marked OUT has its queue dropped — once out,
        the data is re-mapped and a rejoining OSD is rebuilt by
        peering/backfill, so redelivery is pointless (and entries in
        osdmap.osds are never deleted, so keying off presence would
        never fire).  A merely-down peer keeps its queue: it may return
        with its store intact, and redelivery is idempotent (absolute
        sets/removals)."""
        delay = 0.2
        while self._meta_repl_pending and not self._stopped:
            progressed = False
            for osd in list(self._meta_repl_pending):
                q = self._meta_repl_pending.get(osd)
                if not q:
                    self._meta_repl_pending.pop(osd, None)
                    continue
                info = self.osdmap.osds.get(osd)
                if info is None or not info.in_cluster:
                    self._meta_repl_pending.pop(osd, None)
                    continue
                if not info.up:
                    continue  # keep the queue; retry when it returns
                while q:
                    try:
                        await self.messenger.send(
                            self.osdmap.addr_of(osd), q[0])
                    except TRANSPORT_ERRORS:
                        break
                    q.popleft()
                    progressed = True
                if not q:
                    self._meta_repl_pending.pop(osd, None)
            if not self._meta_repl_pending:
                return
            delay = 0.2 if progressed else min(delay * 1.6, 5.0)
            await asyncio.sleep(delay)

    # -- watch/notify (reference src/osd/Watch.{h,cc}) -----------------------

    async def _do_watch(self, op: MOSDOp, remove: bool = False) -> MOSDOpReply:
        pool = self.osdmap.pools[op.pool_id]
        pg, acting = self._acting(pool, op.oid)
        if self._primary(pool, pg, acting) != self.osd_id:
            return MOSDOpReply(ok=False, code=-errno.ESTALE,
                               error="not primary")
        watcher = tuple(pickle.loads(op.data))
        key = (op.pool_id, op.oid)
        if remove:
            self._watchers.get(key, set()).discard(watcher)
        else:
            self._watchers.setdefault(key, set()).add(watcher)
        return MOSDOpReply(ok=True)

    async def _do_notify(self, op: MOSDOp) -> MOSDOpReply:
        """Deliver to every watcher, gather acks (notify2 semantics:
        the notifier's reply lists who acked).  Dedupes by reqid (a resend
        must not re-fire side-effecting callbacks) and gathers acks on a
        task of its own (see _dispatch) so the PG shard worker is never
        blocked — a watcher callback that itself issues ops to this shard
        would otherwise deadlock against the gather."""
        pool = self.osdmap.pools[op.pool_id]
        pg, acting = self._acting(pool, op.oid)
        if self._primary(pool, pg, acting) != self.osd_id:
            return MOSDOpReply(ok=False, code=-errno.ESTALE,
                               error="not primary")
        if op.reqid:
            if op.reqid in self._call_results:
                return self._call_results[op.reqid]
            inflight = self._notify_inflight.get(op.reqid)
            if inflight is not None:
                # resend while the first execution still gathers: share it
                return await asyncio.shield(inflight)
            self._notify_inflight[op.reqid] = \
                asyncio.get_running_loop().create_future()
        try:
            watchers = list(self._watchers.get((op.pool_id, op.oid), ()))
            notify_id = uuid.uuid4().hex
            q = self._collector(notify_id)
            sent = []
            for watcher in watchers:
                try:
                    await self.messenger.send(
                        watcher,
                        MWatchNotify(pool_id=op.pool_id, oid=op.oid,
                                     notify_id=notify_id, payload=op.data,
                                     reply_to=self.addr),
                        peer_type="client")
                    sent.append(watcher)
                except TRANSPORT_ERRORS:
                    # dead watcher: drop the registration (watch timeout role)
                    self._watchers.get((op.pool_id, op.oid), set()).discard(watcher)
            acked = []
            for r in await self._gather(notify_id, q, len(sent), timeout=2.0):
                acked.append(tuple(r.watcher))
            # a watcher that took the frame but never acked is hung or gone:
            # prune it so it can't tax every future notify (watch expiry
            # role); live clients re-register, as the reference's do on
            # watch errors
            for watcher in sent:
                if tuple(watcher) not in acked:
                    self._watchers.get((op.pool_id, op.oid), set()).discard(watcher)
            reply = MOSDOpReply(ok=True, data=pickle.dumps(acked))
        except Exception as e:
            # deliberately BROAD: the inflight future must resolve even on
            # an own-code failure, or every same-reqid resend would hang
            # on a forever-pending shield (counted, not silent)
            self.perf.inc("op_unexpected_error")
            reply = MOSDOpReply(ok=False, code=-errno.EIO,
                                error=f"{type(e).__name__}: {e}")
        if op.reqid:
            if reply.ok:
                # only successes are replayable results; a failed notify
                # resend should re-execute
                self._cache_call_reply(op.reqid, reply)
            fut = self._notify_inflight.pop(op.reqid, None)
            if fut is not None and not fut.done():
                fut.set_result(reply)
        return reply

    async def _do_stat(self, op: MOSDOp) -> MOSDOpReply:
        """Size/version from shard metadata — no payload transfer/decode
        (stat must not cost a full read)."""
        pool = self.osdmap.pools[op.pool_id]
        pg, acting = self._acting(pool, op.oid)
        best: Optional[Tuple[int, int]] = None  # (version, object_size)
        for shard, osd in enumerate(acting):
            if osd != self.osd_id:
                continue
            got = self._store_read((op.pool_id, op.oid, shard))
            if got is not None and (best is None or got[1].version > best[0]):
                best = (got[1].version, got[1].object_size)
        # a local copy older than the log's committed version is stale
        log = self._pglog(op.pool_id, pg)
        latest_logged = max(
            (e.object_version for e in log.entries if e.oid == op.oid),
            default=0,
        )
        if best is not None and best[0] < latest_logged:
            best = None
        if best is None:
            # sub-reads to every live acting peer (each transfers one
            # chunk, not k) carry the metadata we need; newest wins
            tid = uuid.uuid4().hex
            q = self._collector(tid)
            sent = 0
            for shard, osd in enumerate(acting):
                if osd in (CRUSH_ITEM_NONE, self.osd_id):
                    continue
                try:
                    await self.messenger.send(
                        self.osdmap.addr_of(osd),
                        MECSubRead(pool_id=op.pool_id, pg=pg, oid=op.oid,
                                   shard=shard, tid=tid, reply_to=self.addr))
                    sent += 1
                except TRANSPORT_ERRORS:
                    continue
            for r in await self._gather(tid, q, sent, timeout=2.0):
                if r.ok and (best is None or r.version > best[0]):
                    best = (r.version, r.object_size)
        hunt_complete = True
        if best is None:
            # placement drift: hunt any shard cluster-wide (metadata only)
            hunted, hunt_complete = await self._fetch_all_shards(
                op.pool_id, op.oid)
            for _s, _c, version, osize in hunted:
                if best is None or version > best[0]:
                    best = (version, osize)
        if best is None:
            return self._absent_reply(hunt_complete, "shards")
        return MOSDOpReply(ok=True, version=best[0],
                           data=str(best[1]).encode())

    async def _do_delete(self, op: MOSDOp) -> MOSDOpReply:
        """Delete every shard of the object on the PG's possible holders
        (acting + up-set + members of intervals since the PG was last
        clean) — stray shards left by placement drift would otherwise
        resurrect the object through the shard hunt.  The scope set, not a
        cluster broadcast: OSDs outside it can only hold copies from
        intervals that ended with a clean PG, and those were purged."""
        pool = self.osdmap.pools[op.pool_id]
        pg, acting = self._acting(pool, op.oid)
        log = self._pglog(op.pool_id, pg)
        if log.has_reqid(op.reqid):
            return MOSDOpReply(ok=True)  # resent delete: already applied
        # snapshot semantics (reference make_writeable on delete): a
        # delete under a snap context first clones the head, then leaves
        # a WHITEOUT carrying the SnapSet so snap reads keep resolving;
        # the head reads as ENOENT.  Without live clones, a delete is a
        # real delete.
        if not is_snap_clone(op.oid):
            cow_err = await self._make_writeable(op, pool, pg, acting)
            if cow_err is not None:
                return cow_err
            ss = self._load_snapset(op.pool_id, op.oid)
            if ss["clones"]:
                self._cache_drop(op.pool_id, op.oid)
                wr = await self._do_write(MOSDOp(
                    op="write", pool_id=op.pool_id, oid=op.oid, data=b"",
                    reqid=op.reqid or uuid.uuid4().hex))
                if not wr.ok:
                    return wr
                ss = self._load_snapset(op.pool_id, op.oid)
                ss["whiteout"] = True
                await self._save_snapset(pool, pg, acting, op.oid, ss)
                return MOSDOpReply(ok=True)
        tid = uuid.uuid4().hex
        self._cache_drop(op.pool_id, op.oid)
        entry = LogEntry(version=log.next_version(self.osdmap.epoch),
                         op="delete", oid=op.oid, prior_version=log.head,
                         reqid=op.reqid)
        entry_blob = entry.encode()
        # local: drop any shard we hold (rollback slots included); the
        # delete is a PG log event
        txn = Transaction()
        for oid, shard in list(self.store.list_objects(op.pool_id)):
            if oid == op.oid:
                txn.delete((op.pool_id, op.oid, shard))
        self._log_in_txn(txn, op.pool_id, pg, entry)
        self.store.queue_transaction(txn)
        acting_set = {a for a in acting if a != CRUSH_ITEM_NONE}
        peers = [o for o in self._scope_osds(pool, pg) if o != self.osd_id]
        q = self._collector(tid)
        sent = 0
        for osd in peers:
            try:
                # shard=-1: drop every shard of the oid (one message per
                # peer); acting members also log the delete so their PG
                # logs advance with the primary's
                await self.messenger.send(
                    self.osdmap.addr_of(osd),
                    MECSubDelete(pool_id=op.pool_id, pg=pg, oid=op.oid,
                                 shard=-1, tid=tid, reply_to=self.addr,
                                 log_entry=entry_blob
                                 if osd in acting_set else b""),
                )
                sent += 1
            except TRANSPORT_ERRORS:
                pass
        await self._gather(tid, q, sent)
        return MOSDOpReply(ok=True)

    # -- shard side ----------------------------------------------------------

    def _apply_shard_write(
        self, pool_id: int, oid: str, shard: int, chunk: bytes, version: int,
        object_size: int, pg: Optional[int] = None,
        entry: Optional[LogEntry] = None, chunk_off: int = -1,
        shard_size: int = 0, hinfo: bytes = b"", prior_version: int = 0,
        chunk_crc: Optional[int] = None,
    ) -> bool:
        # failsafe FIRST — before the rollback-slot read, the in-memory
        # PG-log append, and the store transaction: a refused write must
        # leave both the store AND the in-memory log byte-identical
        # (injection-aware, so CI exercises this without filling disks)
        if self._failsafe_full(len(chunk)):
            raise ENOSPCError(
                f"osd.{self.osd_id} failsafe full: refusing "
                f"{len(chunk)}-byte shard write")
        txn = Transaction()
        # retain the outgoing version in the rollback slot (same txn):
        # reads fall back to it when a newer write never completed
        old = self._store_read((pool_id, oid, shard))
        if old is not None and old[1].version != version:
            # the retained blob is already store-owned: re-mark, don't
            # re-copy
            txn.write((pool_id, oid, shard + PREV_SLOT),
                      old[0] if isinstance(old[0], bytes)
                      else StoreOwned(old[0]), old[1])
        appended = False
        if chunk_off >= 0:
            # splice precondition: the delta only composes with the exact
            # base the primary read.  A shard that missed an intermediate
            # write (or lost the object) must refuse — splicing into a
            # stale blob would stamp corrupt bytes as newest with a
            # self-consistent crc.  Refusal costs one ack; recovery
            # re-pushes the full blob.
            if old is None or old[1].version != prior_version:
                return False
            # splice the chunk range into the stored blob (per-stripe RMW);
            # zero-extension to shard_size covers gap stripes — zero chunks
            # ARE the parity of zero stripes for these linear codes
            base = bytearray(old[0])
            appended = chunk_off == len(base)
            want = max(shard_size, chunk_off + len(chunk), len(base))
            if len(base) < want:
                base.extend(b"\x00" * (want - len(base)))
            base[chunk_off:chunk_off + len(chunk)] = chunk
            blob = bytes(base)
            chunk_crc = None  # splice: the shipped crc covered the delta
        else:
            blob = chunk
        # one crc per shard per write: reuse the crc the primary already
        # computed (or the receiver already VERIFIED the frame against)
        # instead of a third pass over the same bytes
        crc = shard_crc(blob) if chunk_crc is None else chunk_crc
        txn.write(
            (pool_id, oid, shard),
            # a non-bytes full-write blob is an encode-output (or
            # fetched-shard) buffer whose ownership transfers to the
            # store here: mark it Owned so the RAM store keeps the view
            # instead of a 16 MiB defensive copy per shard (stored
            # buffers are never mutated in place — overwrites replace
            # entries)
            blob if isinstance(blob, bytes) else StoreOwned(blob),
            ShardMeta(version=version, object_size=object_size,
                      chunk_crc=crc),
        )
        if entry is not None and pg is not None:
            self._log_in_txn(txn, pool_id, pg, entry)
        self.store.queue_transaction(txn)
        self._update_hinfo(pool_id, oid, shard, blob, chunk, hinfo,
                           chunk_off, appended)
        return True

    def _update_hinfo(self, pool_id: int, oid: str, shard: int, blob: bytes,
                      chunk: bytes, hinfo: bytes, chunk_off: int,
                      appended: bool) -> None:
        """Maintain the hinfo_key xattr (cumulative shard crcs, reference
        ECUtil.h:101-160): full writes store the primary-computed record;
        splices refresh our OWN entry — by crc32 chaining when the splice
        is a pure append (no re-read of prior bytes), by recompute
        otherwise — and mark the record dirty (other entries went stale)."""
        pool = self.osdmap.pools.get(pool_id) if self.osdmap else None
        if pool is not None and pool.pool_type != "ec":
            return  # replicated pools carry no hinfo; skip the xattr I/O
        key = (pool_id, oid, shard)
        try:
            if chunk_off < 0:
                if hinfo:
                    self.store.setattr(key, HashInfo.XATTR_KEY, hinfo)
                else:
                    # full-blob write without a primary-computed record
                    # (e.g. a sub-chunk recovery push whose helper record
                    # was dirty): an existing record is now stale for this
                    # shard — refresh our own entry and mark it dirty so
                    # scrub trusts the self crc and skips the cross-shard
                    # comparison, instead of flagging fresh data as bad
                    raw0 = self.store.getattr(key, HashInfo.XATTR_KEY)
                    if raw0 is not None:
                        h0 = HashInfo.decode(raw0)
                        if shard < len(h0.crcs):
                            h0.crcs[shard] = shard_crc(blob)
                            h0.total_chunk_size = len(blob)
                            h0.dirty = True
                            self.store.setattr(key, HashInfo.XATTR_KEY,
                                               h0.encode())
                return
            raw = self.store.getattr(key, HashInfo.XATTR_KEY)
            if raw is None:
                return
            h = HashInfo.decode(raw)
            if shard >= len(h.crcs):
                return
            if appended and h.total_chunk_size == chunk_off:
                from ceph_tpu.utils.checksum import checksum

                h.crcs[shard] = checksum(chunk, h.crcs[shard]) & 0xFFFFFFFF
            else:
                h.crcs[shard] = shard_crc(blob)
            h.total_chunk_size = len(blob)
            h.dirty = True
            self.store.setattr(key, HashInfo.XATTR_KEY, h.encode())
        except NotImplementedError:
            pass  # store without xattr support

    async def _apply_sub_write(self, msg: MECSubWrite) -> MECSubWriteReply:
        """Validate + apply one sub-write; the reply is the CALLER's to
        send (the group path batches a whole run of them so the replies
        coalesce into one flush window on the primary's connection)."""
        # every sub-write is a first-class tracked op with a span that
        # joins the primary's propagated `ec write` context — this is
        # the peer leg of the client->primary->k+m stitched trace
        t_tid = getattr(msg, "trace_id", "")
        span = None
        if t_tid:
            span = self.ctx.tracer.join(
                f"ec_sub_write s{msg.shard}", t_tid,
                getattr(msg, "span_id", "") or None)
            span.tag("osd", self.osd_id)
        tracked = self.ctx.op_tracker.create(
            f"ec_sub_write({msg.pool_id}.{msg.pg} {msg.oid} s{msg.shard})",
            reqid=msg.tid, trace=span)
        ok = False
        try:
            ok = True
            sender = getattr(msg, "from_osd", -1)
            if sender >= 0 and self.osdmap is not None:
                # interval fence (reference same_interval_since): refuse a
                # sub-write from an OSD that is not this pg's primary in
                # OUR map — a deposed primary with in-flight sub-ops must
                # not complete a write concurrently with its successor.
                # Catch up first when the sender's map is newer than ours.
                if msg.epoch > self.osdmap.epoch:
                    await self._fetch_full_map()
                pool = self.osdmap.pools.get(msg.pool_id)
                if pool is not None:
                    acting = self.osdmap.pg_to_acting(pool, msg.pg)
                    if (self._primary(pool, msg.pg, acting)
                            not in (sender, None)):
                        ok = False
            if not ok:
                tracked.mark_event("refused_interval")
            elif msg.chunk_crc and not getattr(msg, "_wire_verified", False) \
                    and not crc_verify_any(msg.chunk, msg.chunk_crc):
                # _wire_verified: the frame layer already checked the blob
                # against chunk_crc (the sender reused it as the wire crc)
                # — a second pass over the same bytes proves nothing new
                ok = False  # corrupted in flight
                tracked.mark_event("refused_crc")
            else:
                entry = LogEntry.decode(msg.log_entry) \
                    if msg.log_entry else None
                if entry is not None:
                    entry.version = tuple(entry.version)
                    entry.prior_version = tuple(entry.prior_version)
                enospc = False
                try:
                    ok = self._apply_shard_write(
                        msg.pool_id, msg.oid, msg.shard, msg.chunk,
                        msg.version,
                        msg.object_size, pg=msg.pg, entry=entry,
                        chunk_off=msg.chunk_off,
                        shard_size=msg.shard_size,
                        hinfo=msg.hinfo, prior_version=msg.prior_version,
                        # just verified against the frame: reuse, don't
                        # re-crc
                        chunk_crc=msg.chunk_crc or None,
                    )
                except ENOSPCError:
                    # this shard's store is failsafe-full: refuse (one
                    # missing ack at the primary), never mutate
                    ok = False
                    enospc = True
                # another primary wrote this object: cached decode is
                # stale.  EXCEPTION: an adopted raw fast-ack copy at (or
                # past) this sub-write's version IS the cache-tier
                # durability of an ACKED write — this sub-write is that
                # write's own flush landing, and force-dropping the copy
                # here would reopen the acked-data-loss window the
                # replication closed (primary dies mid-flush).  The copy
                # is released only by the owner's post-flush clear.
                self._extent_cache.drop((msg.pool_id, msg.oid))
                _pkey = self._planar_key(msg.pool_id, msg.oid)
                _spare = False
                _ps = self._paged_store()
                if _ps is not None:
                    _snap = _ps.peek_dirty(_pkey)
                    if _snap is not None \
                            and isinstance(_snap[0], CacheDirtyRecord) \
                            and _snap[0].version >= msg.version:
                        _spare = True
                if not _spare and self._planar is not None:
                    self._planar.drop(_pkey, force=True)
                # ONE event per outcome: an ENOSPC refusal must not also
                # count as a splice/crc refusal in the op timeline
                tracked.mark_event("applied" if ok
                                   else "refused_enospc" if enospc
                                   else "refused_splice")
                if ok:
                    self.perf.inc("subop_w")
        finally:
            if span is not None:
                span.tag("ok", ok)
                span.finish()
            tracked.finish()
        return MECSubWriteReply(tid=msg.tid, shard=msg.shard, ok=ok,
                                trace_id=t_tid,
                                span_id=getattr(msg, "span_id", ""))

    async def _handle_sub_write(self, msg: MECSubWrite) -> None:
        reply = await self._apply_sub_write(msg)
        try:
            await self.messenger.send(tuple(msg.reply_to), reply)
        except TRANSPORT_ERRORS:
            pass

    async def _handle_sub_write_group(self, msgs: List[MECSubWrite]) -> None:
        """A consecutive run of sub-writes from one rx batch: apply all
        in arrival order FIRST, then send the replies — replies to the
        same primary land in the same outbox flush window (one writev +
        one piggybacked ack instead of a write+drain per sub-write)."""
        replies = []
        for msg in msgs:
            replies.append((tuple(msg.reply_to),
                            await self._apply_sub_write(msg)))

        async def _send_one(addr, reply):
            try:
                await self.messenger.send(addr, reply)
            except TRANSPORT_ERRORS:
                pass

        # concurrent enqueue (not sequential awaits): every reply joins
        # the connection outbox before the flusher runs, so one flush
        # window carries the whole run
        await asyncio.gather(*[_send_one(a, r) for a, r in replies])

    async def _handle_sub_read(self, msg: MECSubRead) -> None:
        self.perf.inc("subop_r")
        try:
            got = self.store.read((msg.pool_id, msg.oid, msg.shard))
        except IOError:
            # EIO / checksum failure on our shard: reply error so the
            # primary reconstructs from other shards (the behavior
            # qa/standalone/erasure-code/test-erasure-eio.sh exercises)
            got = None
        _ps = self._paged_store()
        if _ps is not None:
            _snap = _ps.peek_dirty(self._planar_key(msg.pool_id, msg.oid))
            if _snap is not None and isinstance(_snap[0], CacheDirtyRecord):
                got = await self._raw_subread_fence(msg, _snap[0], got)
        got = self._dirty_subread_fence(msg, got)
        if got is None:
            reply = MECSubReadReply(tid=msg.tid, shard=msg.shard, ok=False)
        else:
            chunk, meta = got
            stored_crc = 0
            if msg.extents:
                # fragmented read: only the requested blob ranges cross
                # the wire, as a BufferList of extent VIEWS — no join
                # copy (stripe-RMW + sub-chunk recovery, ECMsgTypes.h:105)
                payload = BufferList(
                    [memoryview(chunk)[o:o + l] for o, l in msg.extents])
            else:
                payload = chunk
                # whole-blob reply: the stored meta crc IS the crc of
                # these bytes — the messenger reuses it as the frame's
                # blob crc (BLOB_CRC_ATTR), skipping the checksum pass.
                # MemStore only: its contents were written by THIS
                # process, so the crc kind is the current resolver's; a
                # persistent store may hold crcs from another build/kind
                # (the crc_verify_any discipline), and shipping one as
                # the wire crc would fail every frame at the receiver
                if isinstance(self.store, MemStore):
                    stored_crc = meta.chunk_crc
            hraw = None
            if getattr(msg, "want_hinfo", False):
                try:
                    hraw = self.store.getattr(
                        (msg.pool_id, msg.oid, msg.shard), HashInfo.XATTR_KEY)
                except NotImplementedError:
                    pass
            reply = MECSubReadReply(
                tid=msg.tid, shard=msg.shard, ok=True, chunk=payload,
                version=meta.version, object_size=meta.object_size,
                hinfo=hraw or b"", chunk_crc=stored_crc,
            )
        try:
            await self.messenger.send(tuple(msg.reply_to), reply)
        except TRANSPORT_ERRORS:
            pass

    async def _handle_sub_delete(self, msg: MECSubDelete) -> None:
        txn = Transaction()
        if msg.shard < 0:  # whole-object delete (rollback slots included)
            for oid, shard in list(self.store.list_objects(msg.pool_id)):
                if oid == msg.oid:
                    txn.delete((msg.pool_id, msg.oid, shard))
        else:
            txn.delete((msg.pool_id, msg.oid, msg.shard))
        if msg.log_entry:
            entry = LogEntry.decode(msg.log_entry)
            entry.version = tuple(entry.version)
            entry.prior_version = tuple(entry.prior_version)
            self._log_in_txn(txn, msg.pool_id, msg.pg, entry)
        self._cache_drop(msg.pool_id, msg.oid)
        self.store.queue_transaction(txn)
        try:
            await self.messenger.send(
                tuple(msg.reply_to), MECSubWriteReply(tid=msg.tid, shard=msg.shard, ok=True)
            )
        except TRANSPORT_ERRORS:
            pass

    async def _fetch_all_shards(self, pool_id: int, oid: str,
                                broadcast: bool = False):
        """Shard hunt scoped to the object's PG: ask the PG's possible
        holders (acting + up + past-interval members) for any shard of oid
        they hold; include our own.  Not a cluster broadcast by default —
        OSDs outside the scope set were purged of strays when their
        interval closed; ``broadcast=True`` is the slow-path fallback for
        when that bookkeeping was itself disrupted (lost purges under
        socket failures).

        Returns (shards, complete): ``complete`` is True only when every
        possible holder was up, was reached, and answered — the bar for
        treating an empty result as VERIFIED absence (-ENOENT) rather than
        cannot-locate (-EAGAIN).  A gather timeout or an unreachable/down
        holder makes the hunt incomplete: the shards may exist there."""
        out = []
        complete = True
        for oid2, shard in self.store.list_objects(pool_id):
            if oid2 != oid:
                continue
            got = self._store_read((pool_id, oid, shard))
            if got is not None:
                out.append((shard % PREV_SLOT, got[0], got[1].version,
                            got[1].object_size))
        pool = self.osdmap.pools.get(pool_id)
        if pool is None:
            return out, False
        pg = self.osdmap.object_to_pg(pool, oid)
        # a down possible-holder may be carrying the shards through a
        # restart: its absence from the queried set forfeits "complete"
        if not self._scope_all_up(pool, pg):
            complete = False
        if broadcast:
            peers = [o.osd_id for o in self.osdmap.osds.values()
                     if o.up and o.osd_id != self.osd_id]
        else:
            peers = [o for o in self._scope_osds(pool, pg)
                     if o != self.osd_id]
        tid = uuid.uuid4().hex
        q = self._collector(tid)
        sent = 0
        for osd in peers:
            try:
                await self.messenger.send(
                    self.osdmap.addr_of(osd),
                    MFetchShards(pool_id=pool_id, oid=oid, tid=tid, reply_to=self.addr),
                )
                sent += 1
            except TRANSPORT_ERRORS:
                complete = False  # unreachable holder: unknown contents
        replies = await self._gather(tid, q, sent)
        if len(replies) < sent:
            complete = False  # gather timeout: someone never answered
        for r in replies:
            out.extend(tuple(s) for s in r.shards)
        return out, complete

    async def _handle_fetch_shards(self, msg: MFetchShards) -> None:
        shards = []
        for oid, shard in self.store.list_objects(msg.pool_id):
            if oid != msg.oid:
                continue
            got = self._store_read((msg.pool_id, msg.oid, shard))
            if got is not None:
                shards.append((shard % PREV_SLOT, got[0], got[1].version,
                               got[1].object_size))
        try:
            await self.messenger.send(
                tuple(msg.reply_to),
                MFetchShardsReply(tid=msg.tid, osd_id=self.osd_id, shards=shards),
            )
        except TRANSPORT_ERRORS:
            pass

    async def _handle_list_shards(self, msg: MListShards) -> None:
        entries = []
        want_pg = getattr(msg, "pg", -1)
        pool = self.osdmap.pools.get(msg.pool_id) if self.osdmap else None
        for oid, shard in self._list_pool_objects(msg.pool_id):
            if (want_pg >= 0 and pool is not None
                    and self.osdmap.object_to_pg(pool, oid) != want_pg):
                continue
            got = self._store_read((msg.pool_id, oid, shard))
            if got is not None:
                entries.append((oid, shard, got[1].version))
        try:
            await self.messenger.send(
                tuple(msg.reply_to),
                MListShardsReply(tid=msg.tid, osd_id=self.osd_id, entries=entries),
            )
        except TRANSPORT_ERRORS:
            pass

    def _apply_push(self, msg: MPushShard) -> None:
        # recovery pushes are first-class tracked ops too: a recovering
        # OSD's dump_ops_in_flight shows what it is applying
        tracked = self.ctx.op_tracker.create(
            f"recovery_push({msg.pool_id} {msg.oid} s{msg.shard})")
        try:
            # a push must never regress the object: the primary read and
            # re-encoded at some version, but a client write may have
            # landed here since — applying the stale push would bury the
            # newer acked bytes in the rollback slot where the next write
            # evicts them (the reference's recovery also refuses to move
            # backward)
            cur = self._store_read((msg.pool_id, msg.oid, msg.shard))
            if cur is not None and cur[1].version > msg.version:
                tracked.mark_event("refused_stale")
                return
            self.perf.inc("recovery_push")
            self._cache_drop(msg.pool_id, msg.oid)
            try:
                self._apply_shard_write(
                    msg.pool_id, msg.oid, msg.shard, msg.chunk,
                    msg.version, msg.object_size, hinfo=msg.hinfo,
                )
            except ENOSPCError:
                # failsafe-full: even recovery stops at the last-resort
                # line (the store must survive) — the primary's next
                # sweep re-pushes once space frees
                tracked.mark_event("refused_enospc")
                return
            tracked.mark_event("applied")
            if msg.xattrs:
                try:
                    for name, value in msg.xattrs.items():
                        if name == HashInfo.XATTR_KEY:
                            # cls xattrs ride pushes, but a stale hinfo
                            # record must never clobber the fresh one
                            # written above
                            continue
                        self.store.setattr((msg.pool_id, msg.oid, 0),
                                           name, value)
                except NotImplementedError:
                    pass
        finally:
            tracked.finish()

    # -- peering (GetInfo/GetLog exchange, reference PeeringState) -----------

    async def _handle_pg_info(self, msg: MPGInfoReq) -> None:
        log = self._pglog(msg.pool_id, msg.pg)
        try:
            await self.messenger.send(
                tuple(msg.reply_to),
                MPGInfoReply(tid=msg.tid, osd_id=self.osd_id,
                             last_update=log.head, log_tail=log.tail,
                             past_members=sorted(self._past_members.get(
                                 (msg.pool_id, msg.pg), ()))),
            )
        except (ConnectionError, OSError):
            pass

    async def _handle_pg_log_req(self, msg: MPGLogReq) -> None:
        log = self._pglog(msg.pool_id, msg.pg)
        delta = log.entries_after(tuple(msg.since))
        reply = MPGLogReply(tid=msg.tid, osd_id=self.osd_id,
                            pool_id=msg.pool_id, pg=msg.pg,
                            backfill=delta is None,
                            entries=[e.encode() for e in (delta or [])])
        try:
            await self.messenger.send(tuple(msg.reply_to), reply)
        except (ConnectionError, OSError):
            pass

    async def _peer_pg(self, pool: PoolInfo, pg: int,
                       acting: List[int]) -> Tuple[Dict[int, Tuple[int, int]], bool]:
        """GetInfo round: each acting peer's last_update.  Returns
        (peer -> last_update, any_needs_backfill)."""
        log = self._pglog(pool.pool_id, pg)
        peers = [o for o in acting
                 if o != CRUSH_ITEM_NONE and o != self.osd_id]
        tid = uuid.uuid4().hex
        q = self._collector(tid)
        sent = 0
        for osd in set(peers):
            try:
                await self.messenger.send(
                    self.osdmap.addr_of(osd),
                    MPGInfoReq(pool_id=pool.pool_id, pg=pg, tid=tid,
                               reply_to=self.addr))
                sent += 1
            except TRANSPORT_ERRORS:
                pass
        infos: Dict[int, Tuple[int, int]] = {self.osd_id: log.head}
        # short timeout: one dropped frame must not stall the recovery
        # window; the retry loop re-peers and lossless replay catches up
        for r in await self._gather(tid, q, sent, timeout=0.8):
            infos[r.osd_id] = tuple(r.last_update)
            peer_past = getattr(r, "past_members", None)
            if peer_past:
                # union interval history: a primary that missed intervals
                # (down / newly added) inherits the scope its peers saw
                self._past_members.setdefault(
                    (pool.pool_id, pg), set()).update(peer_past)
        backfill = any(
            log.calc_missing(v) is None for v in infos.values()
        )
        return infos, backfill

    async def _merge_log_entries(self, pool_id: int, pg: int,
                                 entries: List[LogEntry]) -> List[LogEntry]:
        """Adopt authoritative log entries; local entries NEWER than the
        incoming base are divergent — writes a dead primary never committed
        cluster-wide — and get rolled back (shard dropped + log rewound,
        the reference's divergent-entry rollback).  Returns merged entries."""
        log = self._pglog(pool_id, pg)
        entries = sorted(entries, key=lambda e: e.version)
        if not entries:
            return []
        base = entries[0].prior_version
        divergent = log.divergent_against(base) if base < log.head else []
        txn = Transaction()
        for d in divergent:
            if d.version >= entries[0].version:
                continue  # same entry arriving again, not divergence
            for oid, shard in list(self._list_pool_objects(pool_id)):
                if oid == d.oid:
                    txn.delete((pool_id, d.oid, shard))
            self._cache_drop(pool_id, d.oid)
        if divergent:
            log.rewind_to(base)
        merged = []
        for e in entries:
            if e.version > log.head:
                self._log_in_txn(txn, pool_id, pg, e)
                merged.append(e)
        if txn.writes or txn.deletes or txn.omap_sets or txn.omap_rms:
            self.store.queue_transaction(txn)
        return merged

    async def _push_log_to_peer(self, pool_id: int, pg: int, osd: int,
                                entries: List[LogEntry]) -> None:
        """Unsolicited authoritative log push (tid='') so a caught-up
        peer's log head advances with the objects it just received."""
        if not entries:
            return
        try:
            await self.messenger.send(
                self.osdmap.addr_of(osd),
                MPGLogReply(tid="", osd_id=self.osd_id, pool_id=pool_id,
                            pg=pg, entries=[e.encode() for e in entries]))
        except TRANSPORT_ERRORS:
            pass

    # -- scrub (be_deep_scrub role, ECBackend.cc:2530) -----------------------

    def _scrub_shard_state(self, key: Tuple[int, str, int],
                           shard: int) -> Tuple[bool, bool, int, int]:
        """(present, crc_ok, version, crc) for a stored shard: the blob crc
        must match BOTH the shard meta and the stored cumulative HashInfo
        entry (hinfo_key, the reference's be_deep_scrub comparison against
        hinfo's cumulative crc, ECBackend.cc:2530).  The raw crc rides the
        reply so the primary can cross-check it against its own hinfo."""
        try:
            got = self.store.read(key)
        except IOError:
            return True, False, 0, 0  # unreadable = scrub error
        if got is None:
            return False, False, 0, 0
        chunk, meta = got
        crc = shard_crc(chunk)
        # accept-either: a persisted chunk_crc may predate a checksum
        # algorithm change (crc32c vs zlib) — scrub must not flag every
        # pre-upgrade object as corrupted
        ok = crc == meta.chunk_crc or crc_verify_any(chunk, meta.chunk_crc)
        try:
            raw = self.store.getattr(key, HashInfo.XATTR_KEY)
        except (IOError, OSError):
            raw = None  # unreadable xattr: scrub treats as missing hinfo
        if raw:
            try:
                h = HashInfo.decode(raw)
                if shard < len(h.crcs):
                    ok = ok and (h.crcs[shard] == crc
                                 or crc_verify_any(chunk, h.crcs[shard])) \
                        and h.total_chunk_size == len(chunk)
            except (ValueError, KeyError, TypeError):
                ok = False  # unparseable hinfo is itself a scrub error
        return True, ok, meta.version, crc

    def _hinfo_cross_check(self, pool_id: int, oid: str,
                           acting: List[int]) -> Optional[HashInfo]:
        """The primary's own stored hinfo record, IF it is clean (no splice
        since the last full write): then its per-shard crcs are
        authoritative for every shard and scrub replies can be compared
        against it — catching a shard whose blob, meta crc AND own hinfo
        entry were all consistently rewritten.  Dirty records (stale
        non-self entries) opt out, which is exactly what HashInfo.dirty
        exists to mark."""
        for shard, osd in enumerate(acting):
            if osd != self.osd_id:
                continue
            try:
                raw = self.store.getattr((pool_id, oid, shard),
                                         HashInfo.XATTR_KEY)
            except (IOError, OSError):
                return None
            if not raw:
                return None
            try:
                h = HashInfo.decode(raw)
            except (ValueError, KeyError, TypeError):
                return None
            return None if h.dirty else h
        return None

    async def _handle_scrub_shard(self, msg: MScrubShard) -> None:
        key = (msg.pool_id, msg.oid, msg.shard)
        present, crc_ok, version, crc = self._scrub_shard_state(key, msg.shard)
        try:
            await self.messenger.send(
                tuple(msg.reply_to),
                MScrubShardReply(tid=msg.tid, osd_id=self.osd_id,
                                 shard=msg.shard, present=present,
                                 crc_ok=crc_ok, version=version, crc=crc))
        except (ConnectionError, OSError):
            pass

    # -- cache tier (reference HitSet + tiering agent, here over the
    #    planar HBM residency; policy classes in ceph_tpu/rados/tiering.py) --

    def _tier_enabled(self, pool: PoolInfo) -> bool:
        return (pool.pool_type == "ec"
                and bool(self.conf.get("osd_tier_enabled", True)))

    def _tier_opt(self, pool: PoolInfo, key: str, default, cast):
        """One tier tunable: the pool's mon-settable opt (reference
        `ceph osd pool set NAME hit_set_period ...`) wins over the OSD
        config default; garbage values fall back to the default rather
        than wedging the read path."""
        opts = getattr(pool, "opts", {}) or {}
        raw = opts.get(key)
        if raw is None:
            raw = self.conf.get(f"osd_{key}", default)
        try:
            return cast(raw)
        except (TypeError, ValueError):
            return cast(default)

    def _tier_archive(self, pool: PoolInfo, pg: int) -> HitSetArchive:
        """The PG's hit-set archive; a pool-param change RETUNES it in
        place (HitSetArchive.retune) so temperature history survives —
        rebuilding from scratch (the r10 behavior) read every resident
        as cold and the next agent pass evicted the working set."""
        key = (pool.pool_id, pg)
        period = max(1e-3, self._tier_opt(pool, "hit_set_period", 2.0,
                                          float))
        count = max(1, self._tier_opt(pool, "hit_set_count", 8, int))
        target = self._tier_opt(pool, "hit_set_target_size", 128, int)
        fpp = self._tier_opt(pool, "hit_set_fpp", 0.05, float)
        arch = self._hit_sets.get(key)
        if arch is None:
            arch = HitSetArchive(period, count, target, fpp,
                                 seed=(pool.pool_id << 20) | pg)
            self._hit_sets[key] = arch
            self.tier_perf.set("hit_sets", len(self._hit_sets))
        elif arch.params_key() != (period, count, target, fpp):
            arch.retune(period, count, target, fpp)
        return arch

    def _tier_cache_mode(self, pool: PoolInfo) -> str:
        """The pool's cache mode (mon-validated pool opt `cache_mode`
        over the osd_tier_cache_mode default).  writeback engages only
        with the paged store underneath (dirty bits live there); an
        unknown value reads as writethrough — never half-engage."""
        opts = getattr(pool, "opts", {}) or {}
        mode = opts.get("cache_mode") or self.conf.get(
            "osd_tier_cache_mode", "writethrough")
        return mode if mode in ("writeback", "writethrough") \
            else "writethrough"

    def _tier_dirty_ratio(self) -> float:
        """Dirty high-water as a fraction of the tier target (reference
        cache_target_dirty_ratio): tightest of the OSD default and any
        pool's mon-set opt, same composition rule as the full ratio."""
        ratio = float(self.conf.get("osd_cache_target_dirty_ratio", 0.4)
                      or 0.4)
        if self.osdmap is not None:
            for pool in self.osdmap.pools.values():
                raw = (getattr(pool, "opts", {}) or {}).get(
                    "cache_target_dirty_ratio")
                if raw:
                    try:
                        ratio = min(ratio, float(raw))
                    except (TypeError, ValueError):
                        pass
        return min(max(ratio, 0.01), 1.0)

    def _install_resident(self, pkey, planar, version: int,
                          object_size: int, k: int) -> bool:
        """Install a planar_encode_async product as a CLEAN resident.
        The paged store gets the trim (drop the encode lane's pow2 pad
        before paging — the fragmentation win) and the data-row
        boundary (shed_parity's partial-eviction line); the monolithic
        store keeps its r10 shape.  False = paged refusal (pool full of
        dirty / oversized), the caller stays cold."""
        _, all_bits, n_rows, n_cols, pw = planar
        store = self._planar
        if self._paged_store() is not None:
            return store.put_planar(
                pkey, all_bits, w=pw, n_rows=n_rows,
                meta=(version, n_cols, object_size),
                trim=n_cols, data_rows=k * pw)
        store.put_planar(pkey, all_bits, w=pw, n_rows=n_rows,
                         meta=(version, n_cols, object_size))
        return True

    def _tier_write_install(self, op: MOSDOp, pool: PoolInfo, pg: int,
                            acting: List[int], nbytes: int,
                            full: bool) -> Optional[str]:
        """Write-path tier hook, the r10 OPEN tail closed: writes record
        hits in the PG hit set like reads do (write heat is heat), and
        resident installation goes through the SAME recency/throttle
        gate as read promotion — no more unconditional installs making a
        hot write set indistinguishable from a cold one under pressure.
        Returns None (no residency), "clean" (install after commit, the
        write-through shape) or "writeback" (install dirty pages and
        defer the local shard store apply to flush)."""
        if not self._tier_enabled(pool):
            # residency predates the tier: a disabled tier keeps the
            # unconditional EC-pipeline install (and records nothing)
            return "clean" if full and self._planar is not None else None
        if getattr(op, "fadvise", "") == "dontneed":
            return None
        arch = self._tier_archive(pool, pg)
        rotated = arch.record(op.oid)
        self.tier_perf.inc("write_hits_recorded")
        if rotated:
            self.tier_perf.inc("hitset_rotations")
            worst = max((a.estimated_fpp()
                         for a in self._hit_sets.values()), default=0.0)
            self.tier_perf.set("hitset_fpp_ppm", int(worst * 1e6))
            self._replicate_hit_set(pool, pg, acting, arch)
        if not full or self._planar is None or not nbytes:
            return None
        recency_min = self._tier_opt(
            pool, "min_write_recency_for_promote", 1, int)
        if getattr(op, "fadvise", "") != "willneed" \
                and arch.recency(op.oid) < recency_min:
            self.tier_perf.inc("write_install_gated")
            return None
        if not planar_eligible(self._codec(pool)):
            return None  # the encode will skip planing anyway
        if not self._promote_throttle.allow(nbytes):
            self.tier_perf.inc("write_install_throttled")
            return None
        self.tier_perf.inc("write_installs")
        if self._tier_cache_mode(pool) == "writeback" \
                and self._paged_store() is not None:
            return "writeback"
        return "clean"

    def _tier_writeback_install(self, op: MOSDOp, pool: PoolInfo,
                                pg: int, planar, version: int,
                                object_size: int, entry,
                                local_shards: List[int], shard_crcs,
                                hinfo_blob: bytes, data) -> set:
        """Writeback install: the local shards' store applies are
        DEFERRED — the PG log entry commits now (same txn discipline as
        the write-through apply), the shard bytes live in resident
        pages marked dirty, and the flush contract (WritebackRecord)
        rides the entry so flush-before-evict / demote / scrub / RMW
        can replay the apply byte-identically later.  Returns the set
        of shards whose apply was deferred; empty = the paged pool
        refused (caller falls back to write-through).  Durability is
        UNCHANGED versus write-through: the remote k+m-1 shards commit
        exactly as before, the log entry is persisted, and losing this
        process loses its local shards either way (store and pages are
        both process-local) — what writeback buys is the local crc +
        store transaction off the hot write path, batched into the
        agent's flush cadence."""
        from ceph_tpu.rados.pagestore import WritebackRecord

        store = self._paged_store()
        _, all_bits, n_rows, n_cols, pw = planar
        # failsafe BEFORE any mutation, exactly like _apply_shard_write:
        # a write whose eventual flush could not land must refuse now,
        # not wedge as unflushable dirt
        if self._failsafe_full(len(local_shards) * n_cols):
            raise ENOSPCError(
                f"osd.{self.osd_id} failsafe full: refusing "
                f"writeback install of {len(local_shards)} shards")
        k = self._codec(pool).get_data_chunk_count()
        rec = WritebackRecord(
            pool_id=op.pool_id, oid=op.oid, pg=pg, version=version,
            object_size=object_size, hinfo=hinfo_blob,
            shards=tuple(local_shards),
            crcs={s: shard_crcs[s] for s in local_shards
                  if shard_crcs is not None})
        pkey = self._planar_key(op.pool_id, op.oid)
        ok = store.put_planar(
            pkey, all_bits, w=pw, n_rows=n_rows,
            meta=(version, n_cols, object_size),
            trim=n_cols, data_rows=k * pw,
            dirty_rows=[(s * pw, (s + 1) * pw) for s in local_shards],
            dirty_info=rec)
        if not ok:
            return set()
        # the log entry commits in its own txn NOW — flush replays only
        # the data apply, never the log (the log is what reads validate
        # the resident against)
        txn = Transaction()
        self._log_in_txn(txn, op.pool_id, pg, entry)
        self.store.queue_transaction(txn)
        if isinstance(data, bytes) and len(data) == object_size:
            store.memo_put(pkey, version, data)
        return set(local_shards)

    def _tier_flush_key(self, pkey) -> bool:
        """Flush one dirty resident: replay the deferred local shard
        applies from its pages (byte-identical to the write-through
        path — same version, hinfo, crc) and clear the dirty bits.
        Generation-tokened: an overwrite that re-installed mid-flush
        keeps ITS dirt.  False leaves the entry dirty (ENOSPC, raced
        install) — eviction stays refused."""
        store = self._paged_store()
        if store is None:
            return True
        snap = store.peek_dirty(pkey)
        if snap is None:
            return True
        info, gen = snap
        if isinstance(info, CacheDirtyRecord):
            # raw fast-ack record: no deferred shard applies to replay —
            # only the async destage plane (_tier_flush_raw_key) may
            # move it (it owns the encode and the k+m fan-out)
            return False
        einfo = store.entry_info(pkey)
        if einfo is None or not einfo[2] or einfo[2][0] != info.version:
            return False  # raced a re-install; the new dirt flushes later
        # defense in depth: the PG log head is the authority on the
        # object's newest version.  A record the log has moved past
        # (a newer write or delete landed write-through) must NEVER
        # replay — it would stamp old bytes over the committed newer
        # shard.  The superseding op owns the object now; the dirt is
        # moot, clear it.
        ent = self._pglog(info.pool_id, info.pg).latest_entry(info.oid)
        if ent is not None and (ent.op != "write"
                                or ent.object_version != info.version):
            store.clear_dirty(pkey, gen)
            return True
        total = 0
        for shard in info.shards:
            blob = planar_shard_bytes(store, pkey, info.version, shard)
            if blob is None:
                return False
            try:
                if not self._apply_shard_write(
                        info.pool_id, info.oid, shard, blob,
                        info.version, info.object_size,
                        hinfo=info.hinfo,
                        chunk_crc=info.crcs.get(shard)):
                    return False
            except ENOSPCError:
                return False
            total += len(blob)
        if store.clear_dirty(pkey, gen):
            store.perf.inc("flushes")
            store.perf.inc("flush_bytes", total)
        return True

    def _my_dirty_items(self, store, pool_id: Optional[int] = None,
                        pg: int = -1):
        """THIS OSD's dirty residents ((key, WritebackRecord, gen,
        dirty_since), oldest-dirty first), optionally scoped to one
        pool / PG.  The one home for the shared-store key-namespace
        rule (keys are (osd_id, pool_id, oid) — see _planar_key): the
        flush planes must never flush, or skip, another colocated
        OSD's dirt."""
        out = []
        for key, info, gen, since in store.dirty_items():
            if not (isinstance(key, tuple) and len(key) == 3
                    and key[0] == self.osd_id) or info is None:
                continue
            if pool_id is not None and info.pool_id != pool_id:
                continue
            if pg >= 0 and info.pg != pg:
                continue
            out.append((key, info, gen, since))
        return out

    def _cache_dirty_summary(self) -> List[Tuple[str, List[int]]]:
        """The safe-to-destroy roster riding MPing (v5): every
        un-destaged dirty object this OSD holds, with the full live-copy
        holder set.  Raw fast-ack records carry their cache replica
        roster (the acked bytes exist ONLY on those peers until
        destage); deferred-apply WritebackRecords are purely local dirt.
        The mon's predicates refuse destroy/stop while a target is the
        last live holder of any key."""
        store = self._paged_store()
        if store is None:
            return []
        out: List[Tuple[str, List[int]]] = []
        for _key, info, _gen, _since in self._my_dirty_items(store):
            key = f"{info.pool_id}:{info.oid}"
            if isinstance(info, CacheDirtyRecord):
                holders = sorted({*info.peers, info.primary, self.osd_id})
            else:
                holders = [self.osd_id]
            out.append((key, holders))
        return out

    def _tier_flush_pass(self, store, target: int, forced: bool) -> None:
        """The agent's flush plane: dirty residents flush when dirty
        bytes exceed cache_target_dirty_ratio x target, when they age
        past osd_tier_flush_age, or unconditionally under fullness
        pressure (NEARFULL on the backing store forces dirty flush
        ahead of eviction — the r15 hook)."""
        if not store.has_dirty():
            return
        ratio = self._tier_dirty_ratio()
        age = float(self.conf.get("osd_tier_flush_age", 5.0) or 0)
        now = time.monotonic()
        dirty_target = int(target * ratio)
        for key, _info, _gen, since in self._my_dirty_items(store):
            if isinstance(_info, CacheDirtyRecord):
                continue  # raw records destage via _tier_flush_raw_pass
            over = store.dirty_bytes > dirty_target
            aged = age > 0 and (now - since) >= age
            if not (forced or over or aged):
                continue
            if self._tier_flush_key(key):
                self.tier_perf.inc("flush_agent")
            else:
                self.tier_perf.inc("flush_error")

    def _dirty_subread_fence(self, msg, got):
        """Writeback fence for peer sub-reads: when this OSD's local
        shard apply is still deferred in dirty pages, a peer asking for
        the shard (shard hunt, recovery pull, a new primary's quorum
        read) must see the ACKED bytes, not the stale/absent store
        blob.  Someone reading the backing store ends the deferral:
        flush the resident and serve the fresh store read — version,
        crc, and hinfo all land consistent in one move."""
        store = self._paged_store()
        if store is None:
            return got
        pkey = self._planar_key(msg.pool_id, msg.oid)
        snap = store.peek_dirty(pkey)
        if snap is None or snap[0] is None:
            return got
        rec = snap[0]
        if isinstance(rec, CacheDirtyRecord):
            return got  # raw record: _raw_subread_fence already ran
        if msg.shard not in rec.shards:
            return got
        if got is not None and got[1].version >= rec.version:
            return got
        if not self._tier_flush_key(pkey):
            self.tier_perf.inc("flush_error")
            return got
        self.tier_perf.inc("dirty_subread_served")
        try:
            return self.store.read((msg.pool_id, msg.oid, msg.shard))
        except IOError:
            return got

    def _tier_flush_demoted(self) -> None:
        """Flush every dirty resident whose PG this OSD no longer leads
        (map-change hook).  Writeback must never be the only copy of
        acked data once primaryship moved: the new primary's sub-reads
        and recovery hit our BACKING store, so the deferred applies
        land before we stop answering for the PG."""
        store = self._paged_store()
        if store is None or not store.has_dirty() or self.osdmap is None:
            return
        for key, info, _gen, _since in self._my_dirty_items(store):
            pool = self.osdmap.pools.get(info.pool_id)
            if pool is None:
                store.drop(key, force=True)  # pool gone: data gone too
                continue
            if isinstance(info, CacheDirtyRecord):
                # raw fast-ack dirt moves by REPLICATION, not local
                # flush: _tier_raw_replay_sweep (same map hook) pushes
                # the copy to the new primary / destages inherited dirt
                continue
            if info.pg >= pool.pg_num:
                if self._tier_flush_key(key):
                    self.tier_perf.inc("flush_demote")
                continue
            acting = self.osdmap.pg_to_acting(pool, info.pg)
            if self._primary(pool, info.pg, acting) != self.osd_id:
                if self._tier_flush_key(key):
                    self.tier_perf.inc("flush_demote")
                else:
                    self.tier_perf.inc("flush_error")

    # -- replicated-writeback fast ack (r22): a full-object put under
    #    cache_mode writeback commits the RAW object on a cache quorum
    #    (primary dirty pages + osd_cache_min_size-1 acting peers'
    #    adopted copies, MCacheDirty/MCacheDirtyAck) and acks there; the
    #    k+m encode and sub-write fan-out run later as a classed
    #    background op (CLASS_FLUSH).  Primary death before flush is
    #    recovered by the new primary replaying the freshest replica
    #    copy (_tier_raw_replay_sweep) and completing the destage. ----

    async def _tier_fast_ack_write(self, op: MOSDOp, pool: PoolInfo,
                                   pg: int, acting: List[int], data,
                                   object_size: int, span,
                                   mark) -> Optional[MOSDOpReply]:
        """The fast-ack put: install the raw dirty object locally,
        replicate it to the first cache_min_size-1 live acting peers,
        ack at that quorum.  None = the quorum cannot form or the store
        refused — the caller falls back to synchronous write-through
        (the degradation contract, counted wb_quorum_short)."""
        store = self._paged_store()
        if store is None:
            return None
        cache_min = max(1, self._tier_opt(pool, "cache_min_size", 2, int))
        peers: List[int] = []
        for osd in acting:
            if osd in (CRUSH_ITEM_NONE, self.osd_id) or osd in peers:
                continue  # pg_to_acting already holed-out down members
            peers.append(osd)
        peers = peers[:cache_min - 1]
        if len(peers) < cache_min - 1:
            self.tier_perf.inc("wb_quorum_short")
            return None
        # failsafe BEFORE any mutation (the _apply_shard_write rule): a
        # put whose eventual flush could not land must refuse now, not
        # wedge as unflushable dirt
        if self._failsafe_full(object_size):
            return None
        raw = bytes(data)
        pkey = self._planar_key(op.pool_id, op.oid)
        log = self._pglog(op.pool_id, pg)
        # synchronous window: eversion -> raw install -> log txn with
        # no awaits, the same discipline as the EC path — a concurrent
        # log merge cannot advance the head under a version we already
        # handed out
        entry = LogEntry(version=log.next_version(self.osdmap.epoch),
                         op="write", oid=op.oid, prior_version=log.head,
                         reqid=op.reqid)
        version = pack_eversion(entry.version)
        entry.object_version = version
        entry.cache_peers = (self.osd_id,) + tuple(peers)
        rec = CacheDirtyRecord(
            pool_id=op.pool_id, oid=op.oid, pg=pg, version=version,
            object_size=object_size, primary=self.osd_id,
            peers=(self.osd_id,) + tuple(peers))
        if not store.put_raw(pkey, raw, meta=(version, -1, object_size),
                             dirty_info=rec):
            self.tier_perf.inc("wb_quorum_short")
            return None  # paged pool refused: write-through instead
        entry_blob = entry.encode()
        txn = Transaction()
        self._log_in_txn(txn, op.pool_id, pg, entry)
        self.store.queue_transaction(txn)
        store.memo_put(pkey, version, raw)
        span.event("raw dirty installed")
        mark("wb_raw_installed")
        tid = uuid.uuid4().hex
        q = self._collector(tid)
        sends = []
        for osd in peers:
            sends.append(self.messenger.send(
                self.osdmap.addr_of(osd),
                MCacheDirty(
                    pool_id=op.pool_id, pg=pg, oid=op.oid, op="install",
                    data=raw, version=version, object_size=object_size,
                    tid=tid, reply_to=self.addr, log_entry=entry_blob,
                    peers=list(rec.peers), from_osd=self.osd_id,
                    epoch=self.osdmap.epoch)))
        sent = 0
        for got in await asyncio.gather(*sends, return_exceptions=True):
            if got is None:
                sent += 1
            elif not isinstance(got, TRANSPORT_ERRORS):
                raise got
        mark("cache_repl_sent")
        replies = await self._gather(tid, q, sent)
        acks = 1 + sum(1 for r in replies if r.ok)  # self + adopters
        span.event(f"cache quorum {acks}/{cache_min}")
        if acks < cache_min:
            # an adopter refused or died mid-replication: the raw copy
            # is NOT on cache_min_size processes, so the fast ack's
            # durability claim does not hold.  Degrade THIS op to the
            # synchronous bar: destage the EC shards inline and ack only
            # if that lands at pool min_size (write-through durability).
            self.tier_perf.inc("wb_quorum_short")
            if await self._tier_flush_raw_key(pkey):
                self._cache_put(op.pool_id, op.oid, version, raw)
                mark("wb_inline_flushed")
                return MOSDOpReply(ok=True)
            self._mark_failed_write(op.reqid)
            self._cache_drop(op.pool_id, op.oid)
            self._tier_raw_clear_peers(rec)
            return MOSDOpReply(
                ok=False, code=-errno.EBUSY,
                error=f"writeback acked by {acks} < cache min_size "
                      f"{cache_min} and inline flush failed")
        self.tier_perf.inc("wb_repl_acks")
        self.tier_perf.inc("wb_repl_bytes", len(raw) * len(peers))
        self._update_flush_backlog()
        self._cache_put(op.pool_id, op.oid, version, raw)
        mark("wb_acked")
        return MOSDOpReply(ok=True)

    async def _handle_cache_dirty(self, msg: MCacheDirty) -> None:
        """Receiver half of the fast-ack pair.  op=install adopts the
        raw dirty copy (pages + memo + the PG log entry — the durability
        unit the ack claims); op=clear is the owner's post-flush (or
        failed-write) release, version-fenced so a newer adopted copy
        keeps its dirt.  An install landing on the PG's CURRENT primary
        from a non-primary sender is a recovery push: adopt, then
        complete the dead installer's deferred destage."""
        store = self._paged_store()
        pkey = self._planar_key(msg.pool_id, msg.oid)
        if msg.op == "clear":
            if store is not None:
                snap = store.peek_dirty(pkey)
                if snap is not None \
                        and isinstance(snap[0], CacheDirtyRecord) \
                        and snap[0].version <= msg.version:
                    store.clear_dirty(pkey, snap[1])
                    store.drop(pkey, force=True)
                self._update_flush_backlog()
            return
        ok = store is not None and self.osdmap is not None
        recovery_push = False
        if ok:
            # interval fence (the _apply_sub_write rule): catch up when
            # the sender's map is newer, refuse a deposed sender
            if msg.epoch > self.osdmap.epoch:
                await self._fetch_full_map()
            pool = self.osdmap.pools.get(msg.pool_id)
            if pool is None:
                ok = False
            else:
                acting = self.osdmap.pg_to_acting(pool, msg.pg)
                prim = self._primary(pool, msg.pg, acting)
                if prim == self.osd_id and msg.from_osd != self.osd_id:
                    recovery_push = True
                elif prim not in (msg.from_osd, None):
                    ok = False
        if ok:
            cur = store.resident_meta(pkey)
            if cur and cur[0] >= msg.version:
                # duplicate / stale push: our copy is already at (or
                # past) this version — adopting would rewind.  Ack ok:
                # the sender's durability claim holds either way.
                pass
            else:
                raw = as_bytes(msg.data)
                peers = tuple(int(x) for x in (msg.peers or ()))
                rec = CacheDirtyRecord(
                    pool_id=msg.pool_id, oid=msg.oid, pg=msg.pg,
                    version=msg.version, object_size=msg.object_size,
                    primary=(self.osd_id if recovery_push
                             else msg.from_osd),
                    peers=peers or (msg.from_osd, self.osd_id))
                if store.put_raw(pkey, raw,
                                 meta=(msg.version, -1, msg.object_size),
                                 dirty_info=rec):
                    if msg.log_entry:
                        entry = LogEntry.decode(msg.log_entry)
                        entry.version = tuple(entry.version)
                        entry.prior_version = tuple(entry.prior_version)
                        txn = Transaction()
                        self._log_in_txn(txn, msg.pool_id, msg.pg, entry)
                        self.store.queue_transaction(txn)
                    store.memo_put(pkey, msg.version, raw)
                    # a stale decode of the OLD version must die, but
                    # NOT the raw pages we just installed — so the
                    # extent cache only, never _cache_drop
                    self._extent_cache.drop((msg.pool_id, msg.oid))
                    self.tier_perf.inc("wb_dirty_adopted")
                    self._update_flush_backlog()
                else:
                    ok = False
        if msg.tid:
            try:
                await self.messenger.send(
                    tuple(msg.reply_to),
                    MCacheDirtyAck(tid=msg.tid, osd=self.osd_id, ok=ok))
            except TRANSPORT_ERRORS:
                pass
        if ok and recovery_push:
            # we are the PG's new primary holding a pushed copy of a
            # dead primary's acked write: finish its flush
            self._spawn_tier_task(self._tier_flush_raw_key(pkey))

    def _tier_raw_clear_peers(self, rec: CacheDirtyRecord) -> None:
        """Fire-and-forget release of the peers' adopted copies (post
        flush, or failed-write cleanup).  Version-fenced at the
        receiver; a lost clear is mopped up by the adopted-copy GC in
        _tier_flush_raw_pass."""
        if self.osdmap is None:
            return

        async def _clear_one(osd: int) -> None:
            try:
                await self.messenger.send(
                    self.osdmap.addr_of(osd),
                    MCacheDirty(pool_id=rec.pool_id, pg=rec.pg,
                                oid=rec.oid, op="clear",
                                version=rec.version,
                                from_osd=self.osd_id,
                                epoch=self.osdmap.epoch))
            except TRANSPORT_ERRORS:
                pass

        for osd in rec.peers:
            if osd == self.osd_id or osd not in self.osdmap.osds:
                continue
            self._spawn_tier_task(_clear_one(osd))

    async def _tier_flush_any(self, pkey) -> bool:
        """Route one dirty resident to its flush plane: raw fast-ack
        records destage through the async encode+fan-out path, legacy
        WritebackRecords replay synchronously.  The one entry point for
        the RMW / scrub fences (both async contexts)."""
        store = self._paged_store()
        if store is None:
            return True
        snap = store.peek_dirty(pkey)
        if snap is None:
            return True
        if isinstance(snap[0], CacheDirtyRecord):
            return await self._tier_flush_raw_key(pkey)
        return self._tier_flush_key(pkey)

    async def _tier_flush_raw_key(self, pkey,
                                  background: bool = False) -> bool:
        """Destage one raw fast-ack record: k+m encode the raw object,
        fan the sub-writes out exactly as the write path would have, and
        clear the dirt at pool min_size acks.  Generation-tokened like
        _tier_flush_key: an overwrite that re-installed mid-encode keeps
        ITS dirt (we simply stop owning the flush).  False leaves the
        entry dirty for the next pass."""
        store = self._paged_store()
        if store is None:
            return True
        if pkey in self._raw_flush_inflight:
            return False  # single-flight: another plane is destaging
        snap = store.peek_dirty(pkey)
        if snap is None:
            return True
        rec, gen = snap
        if not isinstance(rec, CacheDirtyRecord):
            return self._tier_flush_key(pkey)
        if self.osdmap is None:
            return False
        pool = self.osdmap.pools.get(rec.pool_id)
        if pool is None or rec.pg >= pool.pg_num:
            store.drop(pkey, force=True)  # pool gone: data gone too
            return True
        acting = self.osdmap.pg_to_acting(pool, rec.pg)
        if self._primary(pool, rec.pg, acting) != self.osd_id:
            return False  # not ours: the replay sweep routes it
        # PG-log-head defense (the _tier_flush_key rule): a record the
        # log moved past must never stamp old bytes over newer shards
        ent = self._pglog(rec.pool_id, rec.pg).latest_entry(rec.oid)
        if ent is not None and (ent.op != "write"
                                or ent.object_version != rec.version):
            # superseded (newer write / delete landed): the dirt is moot
            store.clear_dirty(pkey, gen)
            store.drop(pkey, force=True)
            self._update_flush_backlog()
            return True
        # ent None (trimmed window) still flushes: the record itself is
        # the durability contract, the entry just rides along when held
        data = store.memo_get(pkey, rec.version)
        if data is None:
            data = store.read_raw(pkey)
        if data is None:
            return False  # raced a drop/re-install; next pass re-peeks
        self._raw_flush_inflight.add(pkey)
        try:
            return await self._tier_flush_raw_inner(
                pkey, store, rec, gen, pool, acting, ent, bytes(data),
                background)
        finally:
            self._raw_flush_inflight.discard(pkey)

    async def _tier_flush_raw_inner(self, pkey, store,
                                    rec: CacheDirtyRecord, gen: int,
                                    pool: PoolInfo, acting: List[int],
                                    ent, data: bytes,
                                    background: bool) -> bool:
        if background:
            # classed background op: the destage waits its dmClock turn
            # under CLASS_FLUSH (above best_effort — the backlog holds
            # acked client data), cost scaled to the encode size
            await self._background_throttle(
                CLASS_FLUSH, (rec.pool_id << 20) | rec.pg,
                cost=max(1, len(data) // 65536))
        codec = self._codec(pool)
        sinfo = self._sinfo(pool)
        planar = await planar_encode_async(codec, sinfo, data,
                                           queue=self._ec_queue)
        if planar is not None:
            blobs = planar[0]
        else:
            blobs = await batched_encode_async(codec, sinfo, data,
                                               queue=self._ec_queue)
        # revalidate after the awaits: an overwrite that re-installed
        # mid-encode owns the dirt now (gen moved), and a map change may
        # have deposed us (the sweep re-routes)
        snap = store.peek_dirty(pkey)
        if snap is None or snap[1] != gen:
            return True  # superseded: this flush is no longer needed
        acting = self.osdmap.pg_to_acting(pool, rec.pg)
        if self._primary(pool, rec.pg, acting) != self.osd_id:
            return False
        n = codec.get_chunk_count()
        shard_crcs = [shard_crc(blobs[i]) for i in range(n)]
        hinfo_blob = self._hinfo_for(pool, blobs, crcs=shard_crcs)
        entry_blob = ent.encode() if ent is not None else b""
        self.tier_perf.inc("flush_encodes")
        tid = uuid.uuid4().hex
        local_ok = 0
        remote: List[Tuple[int, int]] = []
        for shard, osd in enumerate(acting):
            if osd == CRUSH_ITEM_NONE:
                continue
            if osd == self.osd_id:
                try:
                    if self._apply_shard_write(
                            rec.pool_id, rec.oid, shard,
                            memoryview(np.ascontiguousarray(blobs[shard])),
                            rec.version, rec.object_size, pg=rec.pg,
                            entry=ent, hinfo=hinfo_blob,
                            chunk_crc=shard_crcs[shard]):
                        local_ok += 1
                except ENOSPCError:
                    return False
            else:
                remote.append((shard, osd))
        q = self._collector(tid)
        sends = []
        for shard, osd in remote:
            chunk = memoryview(np.ascontiguousarray(blobs[shard]))
            sends.append(self.messenger.send(
                self.osdmap.addr_of(osd),
                MECSubWrite(
                    pool_id=rec.pool_id, pg=rec.pg, oid=rec.oid,
                    shard=shard, chunk=chunk, version=rec.version,
                    object_size=rec.object_size,
                    chunk_crc=shard_crcs[shard], tid=tid,
                    reply_to=self.addr, log_entry=entry_blob,
                    hinfo=hinfo_blob, from_osd=self.osd_id,
                    epoch=self.osdmap.epoch)))
        sent = 0
        for got in await asyncio.gather(*sends, return_exceptions=True):
            if got is None:
                sent += 1
            elif not isinstance(got, TRANSPORT_ERRORS):
                raise got
        replies = await self._gather(tid, q, sent)
        acks = local_ok + sum(1 for r in replies if r.ok)
        if acks < pool.min_size:
            return False  # stays dirty; the next pass retries
        if store.clear_dirty(pkey, gen):
            store.perf.inc("flushes")
            store.perf.inc("flush_bytes", len(data))
            if planar is not None:
                # the raw entry served its purpose: swap the planar
                # rows in as a CLEAN resident (reads keep their
                # zero-shard-read path) and re-seed the memo
                if self._install_resident(pkey, planar, rec.version,
                                          rec.object_size,
                                          codec.get_data_chunk_count()):
                    store.memo_put(pkey, rec.version, data)
            self._tier_raw_clear_peers(rec)
        self._update_flush_backlog()
        return True

    async def _tier_flush_raw_pass(self) -> None:
        """The agent's raw destage plane: fast-ack records flush on the
        same dirty-ratio / age / fullness triggers as the legacy plane,
        throttled as CLASS_FLUSH background work; adopted copies whose
        write our PG log shows superseded (a lost clear) are GC'd."""
        self._update_flush_backlog()
        store = self._paged_store()
        if store is None or not store.has_dirty() or self.osdmap is None:
            return
        ratio = self._tier_dirty_ratio()
        age = float(self.conf.get("osd_tier_flush_age", 5.0) or 0)
        target = self._tier_effective_target()
        forced = bool(self._my_full_state())
        dirty_target = int(target * ratio)
        now = time.monotonic()
        for key, rec, gen, since in self._my_dirty_items(store):
            if not isinstance(rec, CacheDirtyRecord):
                continue
            pool = self.osdmap.pools.get(rec.pool_id)
            if pool is None:
                store.drop(key, force=True)
                continue
            acting = self.osdmap.pg_to_acting(pool, rec.pg)
            prim = self._primary(pool, rec.pg, acting)
            if prim != self.osd_id:
                # adopted copy: our only job is holding it until the
                # owner's clear.  GC when OUR log proves the write
                # superseded (delete / newer write landed) — the clear
                # was lost, the copy is moot.
                ent = self._pglog(rec.pool_id, rec.pg).latest_entry(
                    rec.oid)
                if ent is not None and (ent.op != "write"
                                        or ent.object_version
                                        > rec.version):
                    store.clear_dirty(key, gen)
                    store.drop(key, force=True)
                continue
            over = store.dirty_bytes > dirty_target
            aged = age > 0 and (now - since) >= age
            # inherited raw dirt (we lead the PG but the record names a
            # dead installer as primary — possible when the replay
            # sweep's one-shot recovery flush failed transiently, e.g.
            # min_size short mid-recovery) is a dead primary's acked
            # write: destage it NOW, not at the age/ratio leisure
            inherited = rec.primary != self.osd_id
            if not (forced or over or aged or inherited):
                continue
            if await self._tier_flush_raw_key(key, background=True):
                self.tier_perf.inc("flush_agent")
            else:
                self.tier_perf.inc("flush_error")
        self._update_flush_backlog()

    def _tier_raw_replay_sweep(self) -> None:
        """Map-change hook for raw fast-ack dirt — the durability half
        of the replicated-writeback contract.  A cache peer that
        outlived the writeback primary PUSHES its adopted copy to the
        PG's new primary; a new primary holding inherited raw dirt (its
        own adopted copy) completes the dead installer's deferred
        destage.  Steady state (the installer still leads the PG) is a
        no-op."""
        store = self._paged_store()
        if store is None or not store.has_dirty() or self.osdmap is None:
            return
        for key, rec, _gen, _since in self._my_dirty_items(store):
            if not isinstance(rec, CacheDirtyRecord):
                continue
            pool = self.osdmap.pools.get(rec.pool_id)
            if pool is None or rec.pg >= pool.pg_num:
                store.drop(key, force=True)
                continue
            acting = self.osdmap.pg_to_acting(pool, rec.pg)
            prim = self._primary(pool, rec.pg, acting)
            if prim is None:
                continue
            if prim == self.osd_id:
                if rec.primary != self.osd_id:
                    self._spawn_tier_task(self._tier_flush_raw_key(key))
            elif rec.primary != prim:
                # the installer lost the PG (died, or we were demoted
                # holding our own record): hand the copy to the new
                # primary so it can replay and destage
                self._spawn_tier_task(self._tier_raw_push(key, rec, prim))

    async def _tier_raw_push(self, pkey, rec: CacheDirtyRecord,
                             target: int) -> None:
        """Push our raw dirty copy to ``target`` (the PG's new primary).
        Our copy stays dirty until the destaging primary's post-flush
        clear — the push hands over the bytes, not the custody."""
        store = self._paged_store()
        if store is None or self.osdmap is None \
                or target not in self.osdmap.osds:
            return
        data = store.memo_get(pkey, rec.version)
        if data is None:
            data = store.read_raw(pkey)
        if data is None:
            return
        ent = self._pglog(rec.pool_id, rec.pg).latest_entry(rec.oid)
        blob = ent.encode() if ent is not None and getattr(
            ent, "object_version", 0) == rec.version else b""
        try:
            await self.messenger.send(
                self.osdmap.addr_of(target),
                MCacheDirty(
                    pool_id=rec.pool_id, pg=rec.pg, oid=rec.oid,
                    op="install", data=bytes(data), version=rec.version,
                    object_size=rec.object_size, log_entry=blob,
                    peers=list(rec.peers), from_osd=self.osd_id,
                    epoch=self.osdmap.epoch))
            self.tier_perf.inc("wb_repl_bytes", len(data))
        except TRANSPORT_ERRORS:
            pass

    async def _raw_subread_fence(self, msg, rec: CacheDirtyRecord, got):
        """Raw-record sibling of _dirty_subread_fence: the acked bytes
        exist only as a raw dirty object — no EC shard of this version
        exists anywhere yet.  On the record's OWNER a peer reading the
        backing store ends the deferral (flush, then serve the fresh
        store read); on a holder of an ADOPTED copy the requested shard
        is synthesized from the raw bytes without mutating anything —
        the store stays untouched and the copy stays dirty until the
        owner's clear (a new primary's quorum read must see the acked
        write without stealing custody)."""
        if got is not None and got[1].version >= rec.version:
            return got
        pkey = self._planar_key(msg.pool_id, msg.oid)
        pool = self.osdmap.pools.get(msg.pool_id) if self.osdmap else None
        if pool is None:
            return got
        if rec.primary == self.osd_id:
            if not await self._tier_flush_raw_key(pkey):
                self.tier_perf.inc("flush_error")
                return got
            self.tier_perf.inc("dirty_subread_served")
            try:
                return self.store.read((msg.pool_id, msg.oid, msg.shard))
            except IOError:
                return got
        store = self._paged_store()
        if store is None:
            return got
        data = store.memo_get(pkey, rec.version)
        if data is None:
            data = store.read_raw(pkey)
        if data is None:
            return got
        planar = await planar_encode_async(self._codec(pool),
                                           self._sinfo(pool),
                                           bytes(data), queue=None)
        if planar is None or msg.shard >= self._codec(
                pool).get_chunk_count():
            return got
        blob = bytes(np.ascontiguousarray(planar[0][msg.shard]))
        self.tier_perf.inc("dirty_subread_served")
        return (blob, ShardMeta(version=rec.version,
                                object_size=rec.object_size))

    def _update_flush_backlog(self) -> None:
        """flush_backlog_bytes gauge: acked-but-not-EC-durable raw
        dirty bytes this OSD currently holds (own records + adopted
        copies)."""
        store = self._paged_store()
        if store is None:
            return
        total = 0
        for _key, rec, _gen, _since in self._my_dirty_items(store):
            if isinstance(rec, CacheDirtyRecord):
                total += rec.object_size
        self.tier_perf.set("flush_backlog_bytes", total)

    def _spawn_tier_task(self, coro) -> None:
        """Fire-and-forget a tier coroutine on the running loop, tracked
        in the messenger's task set (the _tier_observe_read idiom).  No
        loop (sync test context): close the coroutine and skip — every
        caller is a best-effort hook whose next trigger retries."""
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            coro.close()
            return
        t = loop.create_task(coro)
        self.messenger._tasks.add(t)
        t.add_done_callback(self.messenger._tasks.discard)

    def _tier_observe_read(self, op: MOSDOp, reply: MOSDOpReply) -> None:
        """Read-path tier hook (reference PrimaryLogPG::maybe_promote):
        record the hit in the PG's hit-set archive and, when the
        object's recency crosses min_read_recency_for_promote (or the
        client fadvised willneed), promote its full stripe into the
        planar store — throttled by osd_tier_promote_max_objects_sec /
        _bytes_sec.  fadvise=dontneed reads neither record nor promote
        (scans and backups must not heat the working set)."""
        if op.fadvise == "dontneed" or self.osdmap is None:
            return
        pool = self.osdmap.pools.get(op.pool_id)
        if pool is None or not self._tier_enabled(pool):
            return
        pg, acting = self._acting(pool, op.oid)
        if self._primary(pool, pg, acting) != self.osd_id:
            return
        arch = self._tier_archive(pool, pg)
        rotated = arch.record(op.oid)
        self.tier_perf.inc("read_hits_recorded")
        if rotated:
            self.tier_perf.inc("hitset_rotations")
            worst = max((a.estimated_fpp()
                         for a in self._hit_sets.values()), default=0.0)
            self.tier_perf.set("hitset_fpp_ppm", int(worst * 1e6))
            self._replicate_hit_set(pool, pg, acting, arch)
        if self._planar is None:
            return
        # already resident at this version?  resident_meta: a policy
        # probe must not refresh LRU position, pollute the hit/miss
        # ratio, or (paged store) pay a page-table gather
        pkey = self._planar_key(op.pool_id, op.oid)
        rmeta = self._planar.resident_meta(pkey)
        if rmeta and rmeta[0] == reply.version:
            return
        if pkey in self._promoting:
            return  # racing reads fund one encode, not N
        recency_min = self._tier_opt(pool, "min_read_recency_for_promote",
                                     1, int)
        if op.fadvise != "willneed" and arch.recency(op.oid) < recency_min:
            return
        nbytes = len(reply.data)
        if not nbytes:
            return
        # eligibility BEFORE the throttle: a pool whose codec can never
        # plane (mapped/bit-layout plugins) must not burn shared tokens
        # on promotions that are guaranteed to skip — that would starve
        # promotable pools on the same OSD
        if not planar_eligible(self._codec(pool)):
            self.tier_perf.inc("promote_skipped")
            return
        if not self._promote_throttle.allow(nbytes):
            self.tier_perf.inc("promote_throttled")
            return
        # materialize once, AFTER the throttle: a scatter reply's views
        # are copied only for promotions that will actually run
        data = as_bytes(reply.data)
        self._promoting.add(pkey)
        t = asyncio.get_running_loop().create_task(
            self._promote_object(pool, op.oid, data, reply.version))
        self.messenger._tasks.add(t)
        t.add_done_callback(self.messenger._tasks.discard)

    async def _promote_object(self, pool: PoolInfo, oid: str, data: bytes,
                              version: int) -> None:
        """Pack the object's full stripe into the planar store as a
        device resident via the packed-bit lane; subsequent reads serve
        from the resident fast path (zero shard reads, zero decode) with
        byte-identical results — the serving path re-validates the
        resident's version against the PG log on every read."""
        try:
            await self._promote_object_inner(pool, oid, data, version)
        finally:
            self._promoting.discard(self._planar_key(pool.pool_id, oid))

    async def _promote_object_inner(self, pool: PoolInfo, oid: str,
                                    data: bytes, version: int) -> None:
        tracked = self.ctx.op_tracker.create(
            f"tier_promote({pool.pool_id} {oid})")
        try:
            tracked.mark_event("encode_dispatched")
            planar = await planar_encode_async(
                self._codec(pool), self._sinfo(pool), data,
                queue=self._ec_queue)
            if planar is None:
                # codec not planar-eligible (mapped/bit-layout plugins)
                self.tier_perf.inc("promote_skipped")
                tracked.mark_event("skipped")
                return
            # staleness gate: between the read and this install a write
            # may have landed.  The log check and the install below are
            # synchronous (no await between them), so a write appending
            # a newer entry either already moved the head (we skip) or
            # will install its own newer resident after ours.  A TRIMMED
            # log (latest_entry None — long-lived objects outlive the
            # per-PG log window) is NOT stale: no entry means no recent
            # write, and the serving paths re-validate the resident's
            # version on every read anyway, so a mis-install can never
            # be served.
            pg = self.osdmap.object_to_pg(pool, oid)
            ent = self._pglog(pool.pool_id, pg).latest_entry(oid)
            if ent is not None and (ent.op != "write"
                                    or ent.object_version != version):
                self.tier_perf.inc("promote_stale")
                tracked.mark_event("stale")
                return
            pkey = self._planar_key(pool.pool_id, oid)
            if not self._install_resident(
                    pkey, planar, version, len(data),
                    self._codec(pool).get_data_chunk_count()):
                # paged pool full of dirty / oversized resident: the
                # promotion stays cold and retries on a later read
                self.tier_perf.inc("promote_skipped")
                tracked.mark_event("refused")
                return
            # the promoted bytes ARE the pack of the resident's data
            # rows at this version: seed the exit-boundary memo so the
            # first resident hit serves host bytes with zero device
            # work (the pack is already paid — it happened as part of
            # this promote's encode)
            self._planar.memo_put(pkey, version, data)
            self.tier_perf.inc("promote")
            self.tier_perf.inc("promote_bytes", len(data))
            tracked.mark_event("installed")
        except (asyncio.CancelledError, GeneratorExit):
            raise
        except Exception as e:
            self.tier_perf.inc("promote_skipped")
            self.ctx.log.error(
                "osd", f"tier promote {oid}: {type(e).__name__}: {e}")
        finally:
            tracked.finish()

    def _replicate_hit_set(self, pool: PoolInfo, pg: int,
                           acting: List[int], arch: HitSetArchive) -> None:
        """Push the PG's encoded archive to the acting peers at rotation
        (reference hit_set_persist): a failover primary seeds its
        temperature state from the freshest received archive instead of
        restarting every object at cold.  Sends ride their own task —
        the read path must not serialize on peer sockets."""
        peers = [a for a in acting
                 if a not in (CRUSH_ITEM_NONE, self.osd_id)]
        if not peers:
            return
        msg = MOSDPGHitSet(pool_id=pool.pool_id, pg=pg,
                           from_osd=self.osd_id, epoch=self.osdmap.epoch,
                           archive=arch.encode())
        span = None
        if self._trace_on:
            span = self.ctx.tracer.new_trace("hitset push")
            span.tag("osd", self.osd_id).tag("pg", f"{pool.pool_id}.{pg}")
            msg.trace_id, msg.span_id = span.context()

        async def _send() -> None:
            tracked = self.ctx.op_tracker.create(
                f"hitset_push({pool.pool_id}.{pg})")
            try:
                for osd in peers:
                    info = self.osdmap.osds.get(osd)
                    if info is None or not info.up:
                        continue
                    try:
                        await self.messenger.send(
                            self.osdmap.addr_of(osd), msg)
                    except TRANSPORT_ERRORS:
                        pass  # the peer catches the next rotation's push
                tracked.mark_event("pushed")
            finally:
                tracked.finish()
                if span is not None:
                    span.finish()

        t = asyncio.get_running_loop().create_task(_send())
        self.messenger._tasks.add(t)
        t.add_done_callback(self.messenger._tasks.discard)

    def _handle_pg_hit_set(self, msg: MOSDPGHitSet) -> None:
        if msg.from_osd == self.osd_id or self.osdmap is None:
            return
        pool = self.osdmap.pools.get(msg.pool_id)
        if pool is None or msg.pg >= pool.pg_num:
            return
        acting = self.osdmap.pg_to_acting(pool, msg.pg)
        if self._primary(pool, msg.pg, acting) == self.osd_id:
            return  # we lead this PG: our live archive is authoritative
        key = (msg.pool_id, msg.pg)
        # epoch fencing: pushes from different senders have no ordering
        # on the wire — a delayed final push from a DEAD former primary
        # must not overwrite the fresher archive the current primary
        # already sent (the exact failover window the replication
        # exists for)
        if msg.epoch < self._hit_set_epochs.get(key, 0):
            return
        try:
            arch = HitSetArchive.decode(as_bytes(msg.archive))
        except ValueError:
            return  # truncated/foreign blob: keep local state
        self._hit_sets[key] = arch
        self._hit_set_epochs[key] = msg.epoch
        self.tier_perf.set("hit_sets", len(self._hit_sets))

    def _tier_effective_target(self) -> int:
        """The byte budget the agent enforces against: the OSD config
        (osd_tier_target_max_bytes, 0 = the planar store's capacity)
        tightened by any pool's mon-set target_max_bytes — the store is
        one process-shared HBM pool, so the tightest configured bound
        governs."""
        if self._planar is None:
            return 0
        target = int(self.conf.get("osd_tier_target_max_bytes", 0) or 0) \
            or self._planar.capacity_bytes
        if self.osdmap is not None:
            for pool in self.osdmap.pools.values():
                raw = (getattr(pool, "opts", {}) or {}).get(
                    "target_max_bytes")
                if raw:
                    try:
                        t = int(raw)
                    except (TypeError, ValueError):
                        continue
                    if t > 0:
                        target = min(target, t)
        return target

    def _tier_full_ratio(self) -> float:
        ratio = float(self.conf.get("osd_cache_target_full_ratio", 0.8)
                      or 0.8)
        if self.osdmap is not None:
            for pool in self.osdmap.pools.values():
                raw = (getattr(pool, "opts", {}) or {}).get(
                    "cache_target_full_ratio")
                if raw:
                    try:
                        ratio = min(ratio, float(raw))
                    except (TypeError, ValueError):
                        pass
        return min(max(ratio, 0.01), 1.0)

    def _maybe_schedule_tier_agent(self) -> None:
        """Tier agent scheduling (reference PrimaryLogPG::agent_work via
        the OSD's agent queue): at most ONE pass in flight, scheduled
        through the sharded op queue's best_effort class so mClock/WPQ
        arbitrate it against client and recovery work — the same
        discipline as the scrub scheduler."""
        if (self._planar is None or self.osdmap is None
                or self._tier_agent_busy
                or not self.conf.get("osd_tier_enabled", True)):
            return
        interval = float(self.conf.get("osd_tier_agent_interval", 0.5)
                         or 0)
        if interval <= 0:
            return
        now = time.monotonic()
        if now - self._last_tier_scan < interval:
            return
        self._last_tier_scan = now
        self._tier_agent_busy = True

        async def _enqueue() -> None:
            try:
                await self.op_queue.enqueue(
                    -2, self._tier_agent_pass, CLASS_BEST_EFFORT, cost=1)
            except BaseException:
                self._tier_agent_busy = False
                raise

        t = asyncio.get_running_loop().create_task(_enqueue())
        self.messenger._tasks.add(t)
        t.add_done_callback(self.messenger._tasks.discard)

    async def _tier_agent_pass(self) -> None:
        # the evict agent's pass is a tracked op like any other: a
        # wedged agent shows up in dump_ops_in_flight with its age
        tracked = self.ctx.op_tracker.create("tier_agent_pass")
        try:
            with self.tier_perf.time_avg("agent_pass_s"):
                # raw destage plane first: fast-ack dirt is acked client
                # data whose EC durability is still pending — it always
                # outranks eviction housekeeping (and eviction needs the
                # entries clean anyway)
                await self._tier_flush_raw_pass()
                self._tier_agent_once()
            tracked.mark_event("evicted")
        finally:
            self._tier_agent_busy = False
            tracked.finish()

    def _tier_agent_once(self) -> None:
        """One flush/evict pass.  Flush plane first (paged store only):
        dirty residents flush on the dirty-ratio / age / fullness
        triggers, and ALWAYS before their eviction — writeback pages are
        never dropped unflushed.  Then eviction: when resident bytes
        exceed cache_target_full_ratio of the effective target (which
        fullness pressure on the backing store SHRINKS by
        osd_tier_full_target_factor — the r15 nearfull hook), evict this
        OSD's residents coldest-temperature-first until back under, at
        O(page) granularity on the paged store: a candidate first sheds
        its parity-row page suffix (the data prefix keeps serving reads)
        and is fully dropped only if still needed.  An entry the LRU
        already dropped underneath the plan is a COUNTED no-op
        (agent_evict_noop), never an error — either side may win that
        race."""
        store = self._planar
        if store is None:
            return
        target = self._tier_effective_target()
        full_state = self._my_full_state()
        if full_state:
            # NEARFULL (or worse) on the backing store is eviction
            # pressure: the tier's effective target shrinks so residency
            # sheds while the store drains, and dirty pages flush AHEAD
            # of the eviction that needs them clean
            factor = float(self.conf.get("osd_tier_full_target_factor",
                                         0.5) or 0.5)
            target = int(target * min(max(factor, 0.0), 1.0))
        self.tier_perf.set("resident_target_bytes", target)
        if target <= 0:
            return
        paged = self._paged_store()
        if paged is not None:
            self._tier_flush_pass(paged, target, forced=bool(full_state))
        high = int(target * self._tier_full_ratio())
        if store.resident_bytes <= high:
            self.tier_perf.inc("agent_skip")
            return
        self.tier_perf.inc("agent_pass")
        self.ctx.dout("osd", 5,
                      f"tier agent pass: resident {store.resident_bytes} "
                      f"> high {high} (target {target})")
        excess = store.resident_bytes - high
        mine = [(k, b) for k, b in store.entries_snapshot()
                if isinstance(k, tuple) and len(k) == 3
                and k[0] == self.osd_id]
        my_bytes = sum(b for _, b in mine)
        # the store is process-shared and every colocated OSD's agent
        # fires on the same excess: evict only OUR proportional share of
        # it, or N agents would each purge the full excess (Nx
        # over-eviction -> promote/evict thrash).  Rounding up keeps the
        # shares covering the whole excess; the next pass (one agent
        # interval away) mops up any remainder.
        need = min(my_bytes, excess * my_bytes
                   // max(1, store.resident_bytes) + 1)

        def temp_of(key) -> float:
            _osd, pool_id, oid = key
            pool = self.osdmap.pools.get(pool_id) if self.osdmap else None
            if pool is None:
                return 0.0
            arch = self._hit_sets.get(
                (pool_id, self.osdmap.object_to_pg(pool, oid)))
            return arch.temperature(oid) if arch is not None else 0.0

        freed = 0
        # the FULL coldest-first ranking (need=my_bytes covers every
        # entry): pages let eviction run in two tiers of violence —
        # first shed only PARITY page suffixes across the cold tail
        # (data prefixes keep serving resident reads at k/n footprint;
        # parity reconstructs from the store on demand), and only if
        # that cannot cover the excess, drop whole entries
        ranked = eviction_candidates(mine, temp_of, max(my_bytes, 1))
        if paged is not None:
            for key, _nb in ranked:
                if freed >= need:
                    break
                freed += paged.shed_parity(key)
        shed_total = freed
        for key, nbytes in ranked:
            if freed >= need:
                break
            if paged is not None:
                if paged.is_dirty(key):
                    _snap = paged.peek_dirty(key)
                    if _snap is not None \
                            and isinstance(_snap[0], CacheDirtyRecord):
                        # acked raw copy: only the async destage plane
                        # (or the owner's post-flush clear) releases it
                        continue
                    # flush-before-evict: an unflushable dirty entry is
                    # skipped, never dropped
                    if self._tier_flush_key(key):
                        self.tier_perf.inc("flush_evict")
                    else:
                        self.tier_perf.inc("flush_error")
                        continue
                # nbytes was snapshotted before the shed phase freed
                # this entry's parity pages
                nbytes = min(nbytes, paged.entry_nbytes(key))
            if store.drop(key):
                freed += nbytes
                self.tier_perf.inc("agent_evict")
                self.tier_perf.inc("agent_evict_bytes", nbytes)
            else:
                self.tier_perf.inc("agent_evict_noop")
        if shed_total:
            self.ctx.dout("osd", 5,
                          f"tier agent shed {shed_total} parity bytes "
                          f"(partial residency), dropped "
                          f"{max(0, freed - shed_total)} more")

    def tier_status(self) -> dict:
        """`tier status` admin-socket shape."""
        store = self._planar
        paged = self._paged_store()
        out = {
            "enabled": bool(self.conf.get("osd_tier_enabled", True)),
            "device_residency": store is not None,
            "resident_bytes": store.resident_bytes if store else 0,
            "memo_bytes": store.memo_bytes if store else 0,
            "resident_entries": len(store.entries_snapshot())
            if store else 0,
            "target_max_bytes": self._tier_effective_target(),
            "cache_target_full_ratio": self._tier_full_ratio(),
            "cache_target_dirty_ratio": self._tier_dirty_ratio(),
            "cache_mode": {
                pool.name: self._tier_cache_mode(pool)
                for pool in (self.osdmap.pools.values()
                             if self.osdmap else [])
                if pool.pool_type == "ec"},
            "hit_set_archives": len(self._hit_sets),
            # page occupancy / dirty bytes (None = monolithic r10 store)
            "pagestore": paged.page_stats() if paged is not None else None,
            "perf": self.tier_perf.dump(),
        }
        return out

    def _dump_hit_sets(self) -> dict:
        return {f"{pool_id}.{pg}": arch.dump()
                for (pool_id, pg), arch in sorted(self._hit_sets.items())}

    def _maybe_schedule_scrubs(self) -> None:
        """Self-scheduled deep scrub (reference osd_scrub_sched.h: PGs
        scrub themselves on configurable intervals, not only on operator
        request).  The due-scan is throttled, runs at most one scrub at
        a time, and runs it on its OWN task — the beacon loop must never
        block behind a scrub gather or the mon would mark this OSD down.
        A freshly-seen PG starts with a STAGGERED deadline (rank-spread
        fraction of the interval) so daemon start does not trigger a
        scrub burst."""
        interval = float(self.conf.get("osd_deep_scrub_interval", 3600.0)
                         or 0)
        if interval <= 0 or self.osdmap is None:
            return
        now = time.monotonic()
        if now - self._last_scrub_scan < max(interval / 20.0, 0.05):
            return
        if self._scrub_task is not None and not self._scrub_task.done():
            return  # one scrub at a time (reference scrub reservations)
        self._last_scrub_scan = now
        due: Optional[Tuple[float, PoolInfo, int]] = None
        for pool in list(self.osdmap.pools.values()):
            for pg in range(pool.pg_num):
                acting = self.osdmap.pg_to_acting(pool, pg)
                if self._primary(pool, pg, acting) != self.osd_id:
                    continue
                last = self._last_scrub.get((pool.pool_id, pg))
                if last is None:
                    # stagger the first due time across PGs and OSDs
                    self._last_scrub[(pool.pool_id, pg)] = now -                         interval * (((pg * 31 + self.osd_id * 17) % 97)
                                    / 97.0)
                    continue
                if now - last < interval:
                    continue
                if due is None or last < due[0]:
                    due = (last, pool, pg)
        if due is None:
            return
        _, pool, pg = due
        self._last_scrub[(pool.pool_id, pg)] = now

        async def _run() -> None:
            try:
                await self._deep_scrub_pg(pool, pg)
            except Exception:
                self.perf.inc("recovery_errors")

        self._scrub_task = asyncio.get_running_loop().create_task(_run())

    async def _deep_scrub_pg(self, pool: PoolInfo, pg: int) -> Dict[str, int]:
        """Deep scrub the objects of ONE PG this OSD leads."""
        return await self.deep_scrub_pool(pool, only_pg=pg)

    async def _pg_admin_scrub(self, pgid: str,
                              repair: bool = False) -> Dict[str, object]:
        """`ceph pg scrub/repair <pgid>` (MCommand tell aimed at the
        primary).  Scrub: one deep-scrub pass of the PG (mismatches
        raise PG_INCONSISTENT and self-repair).  Repair: scrub, then a
        forced-backfill statechart pass (catches silently-missing
        shards the logs cannot see), then a VERIFY re-scrub — zero
        mismatches on the verify pass clears the PG's inconsistency
        record."""
        try:
            pool_part, pg_part = str(pgid).split(".", 1)
            pool_id, pg = int(pool_part), int(pg_part, 16)
        except (ValueError, AttributeError):
            raise ValueError(f"bad pgid {pgid!r} (want <pool>.<hexpg>)")
        pool = self.osdmap.pools.get(pool_id) if self.osdmap else None
        if pool is None or pg < 0 or pg >= pool.pg_num:
            raise ValueError(f"no such pg {pgid!r}")
        acting = self.osdmap.pg_to_acting(pool, pg)
        primary = self._primary(pool, pg, acting)
        if primary != self.osd_id:
            raise ValueError(
                f"osd.{self.osd_id} is not primary of {pgid} "
                f"(primary is osd.{primary})")
        summary: Dict[str, object] = dict(
            await self._deep_scrub_pg(pool, pg))
        if repair:
            m = self._machine(pool_id, pg)
            try:
                await self._peer_and_recover_pg(
                    m, pool, pg, acting, force_backfill=True,
                    reset_interval=True)
            except (ConnectionError, OSError, asyncio.TimeoutError):
                pass  # verify scrub below judges the outcome
            verify = await self._deep_scrub_pg(pool, pg)
            summary["repaired"] = (int(summary.get("repaired", 0))
                                   + verify["repaired"])
            summary["errors_after_repair"] = verify["errors"]
            summary["verified_clean"] = verify["errors"] == 0
        summary["pgid"] = f"{pool_id}.{pg:x}"
        return summary

    async def deep_scrub_pool(self, pool: PoolInfo,
                              only_pg: int = -1) -> Dict[str, int]:
        """Primary-led deep scrub: every acting shard of every object this
        OSD is primary for recomputes its crc against stored meta; bad or
        missing shards are repaired by re-encode + push.

        Per-object work waits its dmClock turn under CLASS_SCRUB (the
        background-profile ride), mismatches are counted PER PG into
        ``_scrub_errors`` (-> OSD_SCRUB_ERRORS / PG_INCONSISTENT on the
        ping health field), and a pass that verifies a previously
        inconsistent PG clean CLEARS its entry — the repair-confirmed
        lifecycle `ceph pg repair` drives."""
        # writeback fence: scrub compares STORED shards, and a dirty
        # resident means our local shard's apply is still deferred —
        # flush first or every dirty object reads as a mismatch and
        # kicks a repair storm against bytes that were never wrong
        ps = self._paged_store()
        if ps is not None and ps.has_dirty():
            for key, _info, _gen, _since in self._my_dirty_items(
                    ps, pool_id=pool.pool_id, pg=only_pg):
                if await self._tier_flush_any(key):
                    self.tier_perf.inc("flush_scrub")
                else:
                    self.tier_perf.inc("flush_error")
        scrubbed = errors = repaired = 0
        pg_errors: Dict[int, int] = {}
        pg_repaired: Dict[int, int] = {}
        pgs_scanned: Set[int] = set()
        oids = sorted({
            oid for oid, _ in self._list_pool_objects(pool.pool_id)
            if only_pg < 0
            or self.osdmap.object_to_pg(pool, oid) == only_pg})
        # include objects whose shards live elsewhere (scoped to the one
        # PG when scrubbing one PG — peers filter server-side)
        for oid, shard, _v in await self._list_all_shards(pool.pool_id,
                                                          pg=only_pg):
            if oid not in oids:
                oids.append(oid)
        for oid in oids:
            pg, acting = self._acting(pool, oid)
            if self._primary(pool, pg, acting) != self.osd_id:
                continue
            if only_pg >= 0 and pg != only_pg:
                continue
            # classed background work: each object's scrub fan-out waits
            # its CLASS_SCRUB turn against client/recovery traffic
            await self._background_throttle(
                CLASS_SCRUB, (pool.pool_id << 20) | pg)
            pgs_scanned.add(pg)
            scrubbed += 1
            bad: List[Tuple[int, int]] = []  # (shard, osd)
            tid = uuid.uuid4().hex
            q = self._collector(tid)
            sent = 0
            local_results: List[MScrubShardReply] = []
            for shard, osd in enumerate(acting):
                if osd == CRUSH_ITEM_NONE:
                    continue
                if osd == self.osd_id:
                    present, ok, _v, crc = self._scrub_shard_state(
                        (pool.pool_id, oid, shard), shard)
                    local_results.append(MScrubShardReply(
                        osd_id=self.osd_id, shard=shard,
                        present=present, crc_ok=ok, crc=crc))
                else:
                    try:
                        await self.messenger.send(
                            self.osdmap.addr_of(osd),
                            MScrubShard(pool_id=pool.pool_id, oid=oid,
                                        shard=shard, tid=tid,
                                        reply_to=self.addr))
                        sent += 1
                    except TRANSPORT_ERRORS:
                        pass
            replies = local_results + await self._gather(tid, q, sent,
                                                         timeout=2.0)
            by_shard = {r.shard: r for r in replies}
            x_bad: List[Tuple[int, int]] = []
            xcheck = (self._hinfo_cross_check(pool.pool_id, oid, acting)
                      if pool.pool_type == "ec" else None)
            for shard, osd in enumerate(acting):
                if osd == CRUSH_ITEM_NONE:
                    continue
                r = by_shard.get(shard)
                if r is None or not r.present or not r.crc_ok:
                    bad.append((shard, osd))
                elif xcheck is not None and shard < len(xcheck.crcs) \
                        and xcheck.crcs[shard] != r.crc:
                    # cross-shard comparison against the primary's clean
                    # hinfo record: self-consistent rewrites still fail
                    x_bad.append((shard, osd))
            if x_bad:
                # a record disagreeing with more shards than the code can
                # even repair is itself the suspect copy: fall back to
                # self-checks only (the reference majority-votes hinfo)
                codec = self._codec(pool)
                m_count = codec.get_coding_chunk_count()
                if len(x_bad) <= m_count:
                    bad.extend(x_bad)
            if not bad:
                # the object is clean: its rollback slots are stale
                # retention — trim them (the reference trims rollback
                # extents once the interval is stable; scrub is our hook)
                txn = Transaction()
                for shard, osd in enumerate(acting):
                    if osd == self.osd_id:
                        txn.delete((pool.pool_id, oid, shard + PREV_SLOT))
                    elif osd != CRUSH_ITEM_NONE:
                        try:
                            await self.messenger.send(
                                self.osdmap.addr_of(osd),
                                MECSubDelete(pool_id=pool.pool_id, pg=pg,
                                             oid=oid,
                                             shard=shard + PREV_SLOT,
                                             tid="", reply_to=self.addr))
                        except TRANSPORT_ERRORS:
                            pass
                if txn.deletes:
                    self.store.queue_transaction(txn)
            if bad:
                errors += len(bad)
                pg_errors[pg] = pg_errors.get(pg, 0) + len(bad)
                self.perf.inc("scrub_errors_found", len(bad))
                # repair: reconstruct WITHOUT the damaged shards and
                # re-push them
                read = await self._do_read(
                    MOSDOp(op="read", pool_id=pool.pool_id, oid=oid),
                    exclude_shards=frozenset(s for s, _ in bad))
                if read.ok:
                    encoded = await self._encode_for(
                        pool, as_bytes(read.data), oid=oid,
                        version=read.version)
                    for shard, osd in bad:
                        push = MPushShard(
                            pool_id=pool.pool_id, pg=pg, oid=oid, shard=shard,
                            chunk=bytes(encoded[shard]), version=read.version,
                            object_size=len(read.data),
                            hinfo=self._hinfo_for(pool, encoded))
                        if osd == self.osd_id:
                            self._apply_push(push)
                            repaired += 1
                        else:
                            try:
                                await self.messenger.send(
                                    self.osdmap.addr_of(osd), push)
                                repaired += 1
                            except TRANSPORT_ERRORS:
                                continue
                        pg_repaired[pg] = pg_repaired.get(pg, 0) + 1
                        self.perf.inc("scrub_repaired")
        # raise/clear the per-PG inconsistency record this pass proved.
        # Mismatches RAISE (the repair that just ran is unverified until
        # a later pass re-reads the pushed shards); a scanned PG with
        # zero mismatches whose entry was raised earlier is repair-
        # confirmed — CLEAR it (the next ping omits the check).
        now = time.time()
        for pg in pgs_scanned:
            key = (pool.pool_id, pg)
            n_err = pg_errors.get(pg, 0)
            if n_err:
                first = key not in self._scrub_errors
                self._scrub_errors[key] = {
                    "errors": n_err,
                    "repaired": pg_repaired.get(pg, 0),
                    "stamp": now}
                if first:
                    self.clog.error(
                        f"pg {pool.pool_id}.{pg:x} deep-scrub: "
                        f"{n_err} inconsistent shard(s), "
                        f"{pg_repaired.get(pg, 0)} repaired")
            elif self._scrub_errors.pop(key, None) is not None:
                self.clog.info(
                    f"pg {pool.pool_id}.{pg:x} repair verified clean "
                    f"(PG_INCONSISTENT cleared)")
        return {"scrubbed": scrubbed, "errors": errors, "repaired": repaired}

    async def _list_all_shards(self, pool_id: int, pg: int = -1):
        """Union shard listing (oid, shard, version) across up OSDs,
        optionally scoped to one PG (peers filter server-side)."""
        tid = uuid.uuid4().hex
        peers = [o for o in self.osdmap.osds.values()
                 if o.up and o.osd_id != self.osd_id]
        q = self._collector(tid)
        sent = 0
        for o in peers:
            try:
                await self.messenger.send(
                    o.addr, MListShards(pool_id=pool_id, pg=pg, tid=tid,
                                        reply_to=self.addr))
                sent += 1
            except TRANSPORT_ERRORS:
                pass
        out = []
        pool = self.osdmap.pools.get(pool_id)
        for oid, shard in self._list_pool_objects(pool_id):
            if (pg >= 0 and pool is not None
                    and self.osdmap.object_to_pg(pool, oid) != pg):
                continue
            got = self._store_read((pool_id, oid, shard))
            if got is not None:
                out.append((oid, shard, got[1].version))
        for r in await self._gather(tid, q, sent):
            out.extend((o, s, v) for (o, s, v) in r.entries)
        return out

    # -- recovery ------------------------------------------------------------

    async def repair_pool(self, pool: PoolInfo) -> int:
        """Admin/safety-net repair: run one full statechart pass (GetInfo
        -> GetLog -> GetMissing -> recover/backfill) for every PG of the
        pool this OSD leads.  Normal recovery does NOT come through here —
        it is event-driven per PG from _on_map (_kick_peering)."""
        async def one(pg: int) -> int:
            pushed = 0
            # iterate to a verified no-op pass: pushes are fire-and-forget
            # and an admin repair must leave the PG actually clean, not
            # merely "progress was made"
            for round_ in range(4):
                acting = self.osdmap.pg_to_acting(pool, pg)
                if self._primary(pool, pg, acting) != self.osd_id:
                    return pushed
                m = self._machine(pool.pool_id, pg)
                try:
                    done, p = await self._peer_and_recover_pg(
                        m, pool, pg, acting,
                        force_backfill=self.conf.get("osd_repair_full_sweep",
                                                     True),
                        reset_interval=True)
                    pushed += p
                    if done:
                        return pushed
                except (ConnectionError, OSError, asyncio.TimeoutError):
                    pass
                except ErasureCodeError as e:
                    # a codec failure is NOT recoverable by retrying
                    # forever: surface it, don't spin an eternal loop
                    self.perf.inc("recovery_errors")
                    self.ctx.log.error(
                        "osd",
                        f"repair pg {pool.pool_id}.{pg} codec error: {e}")
                    return pushed
                except Exception as e:
                    self.perf.inc("recovery_errors")
                    self.ctx.log.error(
                        "osd",
                        f"repair pg {pool.pool_id}.{pg}: {type(e).__name__}: {e}")
                await asyncio.sleep(0.25)
            return pushed

        # PGs peer concurrently (reservations bound the actual backfill
        # concurrency); a zombie peer stalling one PG's RPCs must not
        # serialize the whole pool's recovery behind it
        jobs = [
            one(pg) for pg in range(pool.pg_num)
            if self._primary(pool, pg,
                             self.osdmap.pg_to_acting(pool, pg)) == self.osd_id
        ]
        if not jobs:
            return 0
        return sum(await asyncio.gather(*jobs))

    def _scope_osds(self, pool: PoolInfo, pg: int,
                    up_only: bool = True) -> List[int]:
        """The OSDs that can possibly hold shards of this PG: current
        acting, crush up-set, and every member of intervals since the PG
        was last clean (_past_members / _prior_acting — the reference's
        past_intervals role).  Deletes, shard hunts, and backfill scans
        contact only this set instead of broadcasting to the cluster.
        ``up_only=False`` returns the full holder set including down
        members — decisions that treat absence-of-shards as proof (the
        unfound revert, verified-absent replies) must check that EVERY
        possible holder is up and was heard from, not just the up ones."""
        key = (pool.pool_id, pg)
        scope = {a for a in self.osdmap.pg_to_acting(pool, pg)
                 if a != CRUSH_ITEM_NONE}
        scope.update(a for a in self._raw_up(pool, pg)
                     if a != CRUSH_ITEM_NONE)
        scope.update(a for a in self._prior_acting.get(key, [])
                     if a != CRUSH_ITEM_NONE)
        scope.update(self._past_members.get(key, ()))
        if not up_only:
            return [o for o in scope if o in self.osdmap.osds]
        return [o for o in scope
                if self.osdmap.osds.get(o) and self.osdmap.osds[o].up]

    def _scope_all_up(self, pool: PoolInfo, pg: int) -> bool:
        """Is every POSSIBLE holder of this PG (including past-interval
        members) up right now?  The bar for treating shard absence as
        proof rather than suspicion."""
        return all(
            self.osdmap.osds.get(o) and self.osdmap.osds[o].up
            for o in self._scope_osds(pool, pg, up_only=False))

    def _reserve_lease(self) -> float:
        return float(self.conf.get("osd_backfill_reserve_lease", 300.0)
                     or 300.0)

    @staticmethod
    def _absent_reply(hunt_complete: bool, what: str) -> MOSDOpReply:
        """Typed reply for a fruitless shard hunt: VERIFIED absence only
        when every possible holder answered; otherwise the client must
        retry, not take "no" for an answer."""
        if hunt_complete:
            return MOSDOpReply(ok=False, code=-errno.ENOENT,
                               error="object not found")
        return MOSDOpReply(ok=False, code=-errno.EAGAIN,
                           error=f"{what} unavailable (holders unreachable "
                                 "or listing incomplete)")

    async def _gather_holdings(
        self, pool: PoolInfo, pg: int = -1,
        osds: Optional[List[int]] = None,
    ) -> Tuple[Dict[str, Set[Tuple[int, int, int]]], bool]:
        """(oid -> {(shard, osd, version)}, complete).  Versions matter —
        a stale shard sitting at its acting position is NOT healthy
        redundancy.  With ``pg``/``osds`` given, the listing is scoped to
        one PG's objects on its possible holders; the default remains the
        pool-wide all-up-OSDs union (stray sweep / scrub).

        ``complete`` is True only when EVERY queried peer answered: a
        partial listing makes healthy objects look under-replicated, and
        any decision that treats absence as doneness (Clean, pg_temp
        clear, stray purge) must refuse to act on it."""
        tid = uuid.uuid4().hex
        if osds is None:
            peers = [o.osd_id for o in self.osdmap.osds.values()
                     if o.up and o.osd_id != self.osd_id]
        else:
            peers = [o for o in osds if o != self.osd_id]
        q = self._collector(tid)
        sent = 0
        complete = True
        for osd in peers:
            try:
                await self.messenger.send(
                    self.osdmap.addr_of(osd),
                    MListShards(pool_id=pool.pool_id, tid=tid,
                                reply_to=self.addr, pg=pg))
                sent += 1
            except TRANSPORT_ERRORS:
                complete = False  # unreachable peer: listing is partial
        holdings: Dict[str, Set[Tuple[int, int, int]]] = {}
        for oid, shard in self._list_pool_objects(pool.pool_id):
            if pg >= 0 and self.osdmap.object_to_pg(pool, oid) != pg:
                continue
            got = self._store_read((pool.pool_id, oid, shard))
            if got is not None:
                holdings.setdefault(oid, set()).add((shard, self.osd_id, got[1].version))
        # short timeout: a just-killed peer can still be "up" in our map
        # (heartbeat grace), its send buffers, and no reply ever comes —
        # recovery must not stall a full RPC window on every zombie
        replies = await self._gather(tid, q, sent, timeout=1.5)
        if len(replies) < sent:
            complete = False
        for r in replies:
            for oid, shard, version in r.entries:
                # re-filter: a peer on an older map may lack the pool and
                # skip its pg filter, returning the whole pool's shards
                if pg >= 0 and self.osdmap.object_to_pg(pool, oid) != pg:
                    continue
                holdings.setdefault(oid, set()).add((shard, r.osd_id, version))
        return holdings, complete

    def _raw_up(self, pool: PoolInfo, pg: int) -> List[int]:
        """The CRUSH mapping filtered to up OSDs — backfill's TARGET set.
        With pg_temp installed, `acting` (who serves IO) and this up-set
        (who should eventually hold the data) differ; backfill pushes to
        the up-set so the override can be cleared (reference up vs acting,
        OSDMap.cc:2673)."""
        return [
            a if a != CRUSH_ITEM_NONE and self.osdmap.osds.get(a)
            and self.osdmap.osds[a].up else CRUSH_ITEM_NONE
            for a in self.osdmap.pg_to_placed(pool, pg)
        ]

    async def _maybe_request_pg_temp(self, pool: PoolInfo, pg: int,
                                     acting: List[int]) -> None:
        """This PG needs backfill: ask the mon to install the prior
        interval's acting set as pg_temp so the data-holding members keep
        serving IO meanwhile (reference MOSDPGTemp request flow,
        OSDMonitor::prepare_pgtemp)."""
        key = (pool.pool_id, pg)
        if self.osdmap.pg_temp.get(key):
            return  # an override is already serving
        prior = self._prior_acting.get(key)
        if not prior or list(prior) == list(acting):
            return
        live = [a for a in prior
                if a != CRUSH_ITEM_NONE and self.osdmap.osds.get(a)
                and self.osdmap.osds[a].up]
        if len(live) < pool.min_size:
            return  # the prior set cannot serve either
        try:
            await self._mon_rpc(
                MOSDPGTemp(pool_id=pool.pool_id, pg=pg, acting=list(prior),
                           from_osd=self.osd_id), MMapReply)
        except TRANSPORT_ERRORS:
            pass

    async def _clear_done_pg_temps(
        self, pool: PoolInfo, pushed: int,
        holdings: Optional[Dict[str, Set[Tuple[int, int, int]]]] = None,
    ) -> None:
        """Backfill-completion check for PGs we serve under pg_temp: once
        every object's newest version covers all up-set positions, ask the
        mon to drop the override so the map returns to the CRUSH mapping.
        Reuses the caller's holdings when no pushes were issued this round
        (nothing moved, so they're still current)."""
        temp_pgs = [pg for (pid, pg) in self.osdmap.pg_temp
                    if pid == pool.pool_id]
        temp_pgs = [pg for pg in temp_pgs
                    if self._primary(pool, pg,
                                     self.osdmap.pg_to_acting(pool, pg))
                    == self.osd_id]
        if not temp_pgs:
            return
        if pushed or holdings is None:
            if pushed:
                await asyncio.sleep(0.3)  # fire-and-forget pushes land
            holdings = {}
            listing_ok = True
            for pg in temp_pgs:  # scoped per-PG listings, not O(pool)
                h, ok = await self._gather_holdings(
                    pool, pg=pg, osds=self._scope_osds(pool, pg))
                holdings.update(h)
                listing_ok &= ok
            if not listing_ok:
                return  # partial view: clearing the override on it could
                        # hand IO to members that are not actually caught up
        k_need = (self._codec(pool).get_data_chunk_count()
                  if pool.pool_type == "ec" else 1)
        incomplete: Set[int] = set()
        for oid, locs in holdings.items():
            pg = self.osdmap.object_to_pg(pool, oid)
            if pg not in temp_pgs or pg in incomplete:
                continue
            got = self._newest_complete(locs, k_need)
            if got is None:
                incomplete.add(pg)
                continue
            _newest, at_newest = got
            if self._missing_up_positions(pool, pg, at_newest):
                incomplete.add(pg)
        for pg in temp_pgs:
            if pg in incomplete:
                continue
            # complete (or the PG holds no objects at all): drop override
            try:
                await self._mon_rpc(
                    MOSDPGTemp(pool_id=pool.pool_id, pg=pg, acting=[],
                               from_osd=self.osd_id), MMapReply)
                self._prior_acting.pop((pool.pool_id, pg), None)
            except TRANSPORT_ERRORS:
                pass

    async def _recover_shard_subchunk(
        self, pool: PoolInfo, pg: int, oid: str, lost: int,
        holders: Dict[int, int], newest: int,
    ) -> Optional[Tuple[bytes, int, bytes]]:
        """Bandwidth-efficient single-shard repair for sub-chunk codecs
        (CLAY): each helper ships only the repair sub-chunk byte ranges of
        its blob instead of whole chunks (reference fragmented helper
        reads ECBackend.cc:1049-1071 + ErasureCodeClay.cc:396
        repair_one_lost_chunk; the runs come from
        minimum_to_decode's SubChunkPlan).  Returns (shard_blob,
        object_size, hinfo_blob) or None when the generic full-decode path
        must run.
        """
        codec = self._codec(pool)
        sinfo = self._sinfo(pool)
        sub = codec.get_sub_chunk_count()
        if sub <= 1:
            return None
        try:
            plan = codec.minimum_to_decode({lost}, set(holders))
        except ErasureCodeError:
            return None
        runs = next(iter(plan.values()))
        if all(r == [(0, sub)] for r in plan.values()):
            return None  # plan is whole-chunk: no sub-chunk saving
        cs = sinfo.chunk_size
        sc_size = cs // sub
        # stat one helper for the object extent -> stripe count (its stored
        # hinfo record rides along for the push)
        stat_shard = next(iter(plan))
        stat = await self._sub_read_extents(pool, pg, oid, stat_shard,
                                            holders[stat_shard], [(0, 0)],
                                            want_hinfo=True)
        if stat is None or stat[2] != newest:
            return None
        object_size = stat[1]
        helper_hinfo = stat[3]
        n_stripes = max(1, -(-object_size // sinfo.stripe_width))
        extents = [(s * cs + idx * sc_size, cnt * sc_size)
                   for s in range(n_stripes) for (idx, cnt) in runs]
        rb = sum(cnt for _i, cnt in runs) * sc_size  # per-stripe bytes
        pieces: Dict[int, bytes] = {}
        for shard, shard_runs in plan.items():
            got = await self._sub_read_extents(pool, pg, oid, shard,
                                               holders[shard], extents)
            if got is None or got[2] != newest or len(got[0]) != rb * n_stripes:
                return None
            pieces[shard] = got[0]
            self.perf.inc("recovery_subchunk_bytes", len(got[0]))
        out: List[bytes] = []
        for s in range(n_stripes):
            stripe_chunks = {
                shard: np.frombuffer(buf[s * rb:(s + 1) * rb], dtype=np.uint8)
                for shard, buf in pieces.items()
            }
            decoded = codec.decode({lost}, stripe_chunks, cs)
            out.append(bytes(decoded[lost]))
        blob = b"".join(out)
        # ship the helper's hinfo record with the push only when it is
        # clean AND agrees with the reconstruction; otherwise the push
        # carries none and the target dirties its own entry
        hinfo_blob = b""
        if helper_hinfo:
            try:
                h = HashInfo.decode(helper_hinfo)
                if (not h.dirty and lost < len(h.crcs)
                        and crc_verify_any(blob, h.crcs[lost])):
                    hinfo_blob = helper_hinfo
            except (ValueError, KeyError, TypeError):
                pass  # garbled helper hinfo: target recomputes its own
        return blob, object_size, hinfo_blob

    async def _sub_read_extents(
        self, pool: PoolInfo, pg: int, oid: str, shard: int, osd: int,
        extents: List[Tuple[int, int]], want_hinfo: bool = False,
    ) -> Optional[Tuple[bytes, int, int, bytes]]:
        """One extent sub-read -> (bytes, object_size, version, hinfo) or
        None.  hinfo is only fetched/shipped when want_hinfo is set (the
        once-per-recovery stat probe) — hot-path stripe-RMW sub-reads skip
        the xattr lookup and the extra wire bytes."""
        if osd == self.osd_id:
            got = self._store_read((pool.pool_id, oid, shard))
            if got is None:
                return None
            blob, meta = got
            payload = b"".join(bytes(blob[o:o + l]) for o, l in extents)
            hraw = None
            if want_hinfo:
                try:
                    hraw = self.store.getattr((pool.pool_id, oid, shard),
                                              HashInfo.XATTR_KEY)
                except NotImplementedError:
                    pass
            return payload, meta.object_size, meta.version, hraw or b""
        tid = uuid.uuid4().hex
        q = self._collector(tid)
        try:
            await self.messenger.send(
                self.osdmap.addr_of(osd),
                MECSubRead(pool_id=pool.pool_id, pg=pg, oid=oid, shard=shard,
                           tid=tid, reply_to=self.addr, extents=extents,
                           want_hinfo=want_hinfo))
        except TRANSPORT_ERRORS:
            self._collectors.pop(tid, None)
            return None
        for r in await self._gather(tid, q, 1, timeout=2.0):
            if r.ok:
                return (as_bytes(r.chunk), r.object_size, r.version,
                        getattr(r, "hinfo", b""))
        return None

    async def _push_reencoded(self, pool: PoolInfo, pg: int,
                              items, rebalance: bool = False) -> int:
        """Re-encode a recovery round's worth of objects and push their
        missing shards.  Every object without a planar-resident (or
        replicated) fast path rides ONE group-aware EC submit
        (ecutil.batched_encode_group_async -> BatchingQueue.submit_group)
        — one queue lock, one worker wakeup, one coalesced dispatch for
        the whole stripe group.  ``items``: (oid, data, version, missing)."""
        if not items:
            return 0
        encoded_by_idx: Dict[int, Any] = {}
        group_idx: List[int] = []
        group_bufs: List[bytes] = []
        for i, (oid, data, version, _missing) in enumerate(items):
            if pool.pool_type != "ec":
                encoded_by_idx[i] = OSD._AllShards(data)
                continue
            if self._planar is not None:
                # residency: the resident planar rows at this version ARE
                # the encoded object — one pack, zero matmuls
                rows = planar_rows(
                    self._planar, self._planar_key(pool.pool_id, oid),
                    version)
                if rows is not None:
                    encoded_by_idx[i] = rows
                    continue
            group_idx.append(i)
            group_bufs.append(data)
        if group_bufs:
            encoded_list = await batched_encode_group_async(
                self._codec(pool), self._sinfo(pool), group_bufs,
                queue=self._ec_queue)
            for i, enc in zip(group_idx, encoded_list):
                encoded_by_idx[i] = enc
        pushed = 0
        for i, (oid, data, version, missing) in enumerate(items):
            encoded = encoded_by_idx[i]
            xattrs = self._cls_xattrs(pool.pool_id, oid)
            hinfo_blob = self._hinfo_for(pool, encoded)
            for shard, osd in missing:
                push = MPushShard(
                    pool_id=pool.pool_id, pg=pg, oid=oid, shard=shard,
                    chunk=bytes(encoded[shard]), version=version,
                    object_size=len(data), xattrs=xattrs, hinfo=hinfo_blob,
                )
                if osd == self.osd_id:
                    self._apply_push(push)
                else:
                    try:
                        await self.messenger.send(self.osdmap.addr_of(osd),
                                                  push)
                    except TRANSPORT_ERRORS:
                        continue
                pushed += 1
                self._note_backfill_push(len(push.chunk), rebalance)
        return pushed

    @staticmethod
    def _newest_complete(
        locs: Set[Tuple[int, int, int]], k_need: int,
    ) -> Optional[Tuple[int, Set[Tuple[int, int]]]]:
        """Newest COMPLETE version of one object's shard holdings: group
        (shard, osd, version) triples by version, keep versions with at
        least k_need distinct shards (decodable), and return (newest such
        version, {(shard, osd)} holding it) — or None when nothing is
        decodable.  Membership is by (shard, osd) pair: a shard may
        legitimately live on several OSDs mid-backfill (old holder + new
        target).  Rollback-slot copies (shard >= PREV_SLOT) normalize to
        their real shard id: they are decodable data for their version but
        must not inflate the DISTINCT-shard count.  Shared by backfill
        push planning and pg_temp completion so the two can never disagree
        about doneness."""
        shards_at: Dict[int, Set[int]] = {}
        for (shard, _osd, v) in locs:
            shards_at.setdefault(v, set()).add(shard % PREV_SLOT)
        viable = [v for v, sh in shards_at.items() if len(sh) >= k_need]
        if not viable:
            return None
        newest = max(viable)
        # membership counts LIVE slots only: a rollback-slot copy decodes,
        # but it must not satisfy seat coverage — it dies with the shard
        # that displaced it, so backfill needs a live home for the data
        return newest, {(shard, osd) for shard, osd, v in locs
                        if v == newest and shard < PREV_SLOT}

    def _missing_up_positions(
        self, pool: PoolInfo, pg: int, at_newest: Set[Tuple[int, int]],
    ) -> List[Tuple[int, int]]:
        """Up-set positions (shard, osd) not holding the newest complete
        version — the push targets backfill must fill."""
        return [
            (shard, osd)
            for shard, osd in enumerate(self._raw_up(pool, pg))
            if osd != CRUSH_ITEM_NONE and (shard, osd) not in at_newest
        ]

    async def _backfill_pool(
        self, pool: PoolInfo,
    ) -> Tuple[int, Dict[str, Set[Tuple[int, int, int]]]]:
        """Pool-wide backfill: per-PG scoped sweeps over every PG this OSD
        leads (each contacts only that PG's possible holders)."""
        pushed = 0
        merged: Dict[str, Set[Tuple[int, int, int]]] = {}
        for pg in range(pool.pg_num):
            acting = self.osdmap.pg_to_acting(pool, pg)
            if self._primary(pool, pg, acting) != self.osd_id:
                continue
            p, holdings, _covered = await self._backfill_pg(pool, pg)
            pushed += p
            merged.update(holdings)
        return pushed, merged

    def _note_backfill_push(self, nbytes: int, rebalance: bool) -> None:
        """Account one pushed shard: backfill_bytes_moved always; the
        rebalance pair only for pure placement moves (the bench arm's
        MB/s-moved numerator — recovery of lost redundancy is a
        different operator question than rebalance cost)."""
        self.perf.inc("backfill_bytes_moved", nbytes)
        if rebalance:
            self.perf.inc("rebalance_push")
            self.perf.inc("rebalance_bytes_moved", nbytes)

    async def _backfill_pg(
        self, pool: PoolInfo, pg: int,
    ) -> Tuple[int, Dict[str, Set[Tuple[int, int, int]]], bool]:
        """Scoped backfill of ONE PG (reference backfill): list shards on
        the PG's possible holders only, reconstruct and push whatever is
        missing from the up-set positions, and purge strays once the
        up-set is fully covered.  Returns (shards_pushed, the gathered
        holdings, fully_covered).

        Classing: a sweep over a DEGRADED acting set (holes — lost
        redundancy) is CLASS_RECOVERY; a sweep moving data because
        membership/weights changed with full redundancy intact (out /
        in / reweight / crush reweight) is CLASS_REBALANCE — per-object
        work waits its dmClock turn so client traffic keeps its
        reservation while data moves."""
        gather_epoch = self.osdmap.epoch
        bg_class = (CLASS_RECOVERY
                    if any(a == CRUSH_ITEM_NONE for a in
                           self.osdmap.pg_to_acting(pool, pg))
                    else CLASS_REBALANCE)
        rebalance = bg_class == CLASS_REBALANCE
        # snapshot BEFORE the gather: the revert decision must be made
        # about the cluster as it was when the listing was taken.  A
        # holder that was down during the gather (never queried) but up
        # by decision time would otherwise make its unseen shards count
        # as verified-absent (TOCTOU).  The queried set is the up-filtered
        # scope at this same instant, so "all holders up at gather_epoch
        # AND every queried peer answered" == complete knowledge.
        holders_all_up = self._scope_all_up(pool, pg)
        holdings, listing_ok = await self._gather_holdings(
            pool, pg=pg, osds=self._scope_osds(pool, pg))
        if self.osdmap.epoch != gather_epoch:
            # the map moved mid-gather: the listing may straddle two
            # membership views — never revert on it
            holders_all_up = False
        k_need = (self._codec(pool).get_data_chunk_count()
                  if pool.pool_type == "ec" else 1)
        pushed = 0
        # objects whose re-encode is deferred into one group submit:
        # (oid, data, version, missing) tuples
        pending_encode: List[Tuple[str, bytes, int, List[Tuple[int, int]]]] = []
        # a partial listing (unanswered peer) makes healthy objects look
        # under-replicated: never declare coverage (or purge) on one
        fully_covered = listing_ok
        for oid, locs in holdings.items():
            # classed background work: each object's reconstruct+push
            # waits its turn under the sweep's dmClock class
            await self._background_throttle(
                bg_class, (pool.pool_id << 20) | pg)
            acting = self.osdmap.pg_to_acting(pool, pg)
            # newest COMPLETE version wins; shards newer than it are
            # uncommitted leftovers of a failed write -> roll them back
            # (reference divergent-entry rollback, ECBackend rollback)
            got = self._newest_complete(locs, k_need)
            if got is None:
                continue
            newest, at_newest = got
            # shards NEWER than the newest complete version are either
            # leftovers of a failed write, a concurrent write racing this
            # scan, or an acked write whose holders died (unfound).  The
            # reference leaves resolving this to the operator
            # (mark_unfound_lost revert) because reverting wrongly
            # DESTROYS an acked write; the automated revert here therefore
            # fires only when absence is proof, not suspicion:
            #   - every possible holder of the PG (including down/past-
            #     interval members, who may be holding the missing shards
            #     through a restart) is up and answered the listing;
            #   - the version has stayed partial for at least
            #     osd_unfound_revert_grace seconds AND across two complete
            #     listings (in-flight acks get time to land);
            #   - osd_auto_revert_unfound has not been switched off (the
            #     operator escape hatch to reference behavior).
            newer_partial = {v for (_s, _o, v) in locs if v > newest}
            if newer_partial:
                fully_covered = False  # unresolved versions: never purge
            if newer_partial and listing_ok and holders_all_up \
                    and self.conf.get("osd_auto_revert_unfound", True):
                grace = float(
                    self.conf.get("osd_unfound_revert_grace", 30.0) or 30.0)
                seen = self._partial_newer.setdefault((pool.pool_id, pg), {})
                now = time.monotonic()
                for v_bad in newer_partial:
                    first_seen = seen.get((oid, v_bad))
                    if first_seen is None or now - first_seen < grace:
                        continue  # first sighting / inside grace: wait
                    for shard, osd, v in locs:
                        if v != v_bad or shard >= PREV_SLOT:
                            continue
                        rb = MECSubRollback(pool_id=pool.pool_id, pg=pg,
                                            oid=oid, shard=shard,
                                            bad_version=v_bad,
                                            reply_to=self.addr)
                        if osd == self.osd_id:
                            self._handle_sub_rollback(rb)
                        else:
                            try:
                                await self.messenger.send(
                                    self.osdmap.addr_of(osd), rb)
                            except TRANSPORT_ERRORS:
                                pass
            # push targets are the UP-SET positions: identical to acting
            # normally, but under pg_temp the override serves IO while
            # backfill fills the crush-mapped members
            missing = self._missing_up_positions(pool, pg, at_newest)
            if not missing:
                continue
            fully_covered = False  # pushes are in flight; purge next round
            if len(missing) == 1 and pool.pool_type == "ec":
                # single lost shard: try the sub-chunk repair path (CLAY)
                # — helpers move sub_chunk_no/q of a chunk, not k chunks
                lost, target = missing[0]
                hold = {shard: osd for shard, osd, v in locs if v == newest}
                hold.pop(lost, None)
                got = await self._recover_shard_subchunk(
                    pool, pg, oid, lost, hold, newest)
                if got is not None:
                    blob, osize, sub_hinfo = got
                    push = MPushShard(
                        pool_id=pool.pool_id, pg=pg, oid=oid, shard=lost,
                        chunk=blob, version=newest, object_size=osize,
                        xattrs=self._cls_xattrs(pool.pool_id, oid),
                        hinfo=sub_hinfo)
                    if target == self.osd_id:
                        self._apply_push(push)
                    else:
                        try:
                            await self.messenger.send(
                                self.osdmap.addr_of(target), push)
                        except TRANSPORT_ERRORS:
                            continue
                    pushed += 1
                    self._note_backfill_push(len(blob), rebalance)
                    continue
            # READING: gather k chunks (degraded-read machinery); the
            # re-encode is DEFERRED so every object this round joins one
            # whole-stripe-group submit to the EC tier (below)
            read_op = MOSDOp(op="read", pool_id=pool.pool_id, oid=oid)
            reply = await self._do_read(read_op)
            if not reply.ok:
                continue
            pending_encode.append((oid, as_bytes(reply.data), reply.version,
                                   missing))
        # re-encode at each object's CURRENT version: deterministic encode
        # makes pushed shards byte-identical to the originals, and the
        # version stays consistent with surviving shards.  All plain
        # re-encodes of this round ride ONE group-aware submit
        # (BatchingQueue.submit_group) — the recovery half of the
        # whole-stripe-group handoff.
        pushed += await self._push_reencoded(pool, pg, pending_encode,
                                             rebalance=rebalance)
        if listing_ok and holders_all_up:
            # refresh the partial-version watchlist: entries keep their
            # first-seen time across sweeps (the grace clock), entries no
            # longer partial drop out, new ones start their clock now.
            # Accrual requires FULL visibility (every possible holder up
            # and answering): grace accumulated during an outage that
            # hides the shards would be worthless evidence.
            prev = self._partial_newer.get((pool.pool_id, pg), {})
            now = time.monotonic()
            observed: Dict[Tuple[str, int], float] = {}
            for oid, locs in holdings.items():
                got = self._newest_complete(locs, k_need)
                base = got[0] if got else 0
                for (_s, _o, v) in locs:
                    if v > base:
                        observed[(oid, v)] = prev.get((oid, v), now)
            self._partial_newer[(pool.pool_id, pg)] = observed
        elif not holders_all_up:
            # incomplete visibility invalidates any accrued grace
            self._partial_newer.pop((pool.pool_id, pg), None)
        if fully_covered and not self.osdmap.pg_temp.get((pool.pool_id, pg)):
            # strays seen this pass block Clean like in-flight pushes do:
            # deletes are fire-and-forget (and the purge skips entirely
            # when the epoch moved mid-gather — routine while OTHER PGs'
            # pg_temp churn bumps the map), so Clean — which pops the
            # _past_members scope that makes the stray OSD visible at
            # all — must wait for a later pass to VERIFY the listing
            # shows nothing outside the up set.  Without this, an `osd
            # out` drain races the map churn of its own rebalance and
            # strands the out OSD's shards forever.
            if await self._purge_strays(pool, pg, holdings, gather_epoch):
                fully_covered = False
        return pushed, holdings, fully_covered

    async def _purge_strays(
        self, pool: PoolInfo, pg: int,
        holdings: Dict[str, Set[Tuple[int, int, int]]],
        gather_epoch: int,
    ) -> bool:
        """Once every up-set position holds the newest complete version
        and no override is serving, copies on OSDs OUTSIDE the up set are
        strays from prior intervals: delete them (reference stray purge
        after activation, PG::purge_strays).  Without this, moved-away
        shards would linger forever and the shard hunt could resurrect a
        deleted object from them.  Delete-sending is skipped when the map
        moved since the holdings were gathered — a "stray" under the old
        map may be an acting member under the new one.  Returns True when
        the listing contained ANY stray shard (purged or deferred): the
        caller must not declare Clean until a later pass verifies the
        strays gone."""
        up = {osd for osd in self._raw_up(pool, pg) if osd != CRUSH_ITEM_NONE}
        stray_osds: Dict[int, Set[str]] = {}
        for oid, locs in holdings.items():
            for _shard, osd, _v in locs:
                if osd not in up:
                    stray_osds.setdefault(osd, set()).add(oid)
        if not stray_osds:
            return False
        if self.osdmap.epoch != gather_epoch:
            return True  # defer: re-gather under the settled map
        for osd, oids in stray_osds.items():
            for oid in oids:
                try:
                    await self.messenger.send(
                        self.osdmap.addr_of(osd),
                        MECSubDelete(pool_id=pool.pool_id, pg=pg, oid=oid,
                                     shard=-1, tid="", reply_to=self.addr))
                    self.perf.inc("stray_purged")
                except TRANSPORT_ERRORS:
                    pass
        return True
