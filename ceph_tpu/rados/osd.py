"""OSD daemon: the EC data plane.

Role-equivalent of the reference's OSD + ECBackend (reference
src/osd/OSD.cc, src/osd/ECBackend.cc): boots against the mon, heartbeats,
and for PGs where it is primary drives the EC pipeline in the reference's
order — submit -> write plan -> encode -> per-shard fan-out -> commit
gather -> client ack (ECBackend.cc:1525 -> 1889 -> 1989 -> 2159) — with the
TPU twist that encode/decode ride the pool codec's device dispatch (and the
codec's batching, plugin=tpu).  Degraded reads reconstruct transparently
(objects_read_and_reconstruct, ECBackend.cc:2401); recovery re-creates
missing shards on the current acting set and pushes them (RecoveryOp
IDLE->READING->WRITING, ECBackend.cc:590-745).

Client and sub-ops ride a sharded op queue (op_shardedwq, OSD.h:1590) with
a pluggable WPQ/mClock scheduler (osd_op_queue); PG id pins an op to a
shard so per-PG ordering holds.  Liveness is two-tier like the reference:
OSD<->OSD heartbeats (OSD::heartbeat OSD.cc:5837, handle_osd_ping :5417)
produce MOSDFailure reports to the mon when a peer misses its grace, and
the mon's own laggard scan is the fallback.  Per-daemon observability:
perf counters, TrackedOp timelines, and an optional admin socket
(`status`, `perf dump`, `dump_ops_in_flight`).

Divergences from the reference, by design of the slice: no PG log/peering
state machine yet (repair is list-diff driven, one in-flight write per
object version), single-stripe objects (the full ECUtil stripe cache is
round-2 work).
"""

from __future__ import annotations

import asyncio
import pickle
import time
import uuid
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from ceph_tpu.common.context import Context
from ceph_tpu.common.perf_counters import PerfCountersBuilder
from ceph_tpu.ec.interface import ErasureCodeError
from ceph_tpu.ec.registry import registry
from ceph_tpu.rados.crush import CRUSH_ITEM_NONE
from ceph_tpu.rados.messenger import Messenger
from ceph_tpu.rados.monclient import MonTargets
from ceph_tpu.rados.scheduler import CLASS_CLIENT, CLASS_RECOVERY, ShardedOpQueue
from ceph_tpu.rados.store import MemStore, ObjectStore, ShardMeta, Transaction, shard_crc
from ceph_tpu.rados.types import (
    MBootReply,
    MGetMap,
    MECSubDelete,
    MECSubRead,
    MECSubReadReply,
    MECSubWrite,
    MECSubWriteReply,
    MFetchShards,
    MFetchShardsReply,
    MListShards,
    MListShardsReply,
    MMapReply,
    MOSDFailure,
    MOSDOp,
    MOSDOpReply,
    MOSDPing,
    MOsdBoot,
    MPing,
    MPushShard,
    OSDMap,
    PoolInfo,
)


class OSD:
    def __init__(
        self,
        mon_addr: Tuple[str, int],
        store: Optional[ObjectStore] = None,
        conf: Optional[dict] = None,
        osd_id: int = -1,
    ):
        self.conf = conf or {}
        # one mon addr or a monmap list; RPCs rotate on mon failure
        self.mons = MonTargets(mon_addr)
        self.store = store or MemStore()
        self.osd_id = osd_id
        self.messenger = Messenger(f"osd.{osd_id}", self.conf, entity_type="osd")
        self.osdmap: Optional[OSDMap] = None
        self._codecs: Dict[int, object] = {}
        self._pending: Dict[str, asyncio.Future] = {}
        self._collectors: Dict[str, asyncio.Queue] = {}
        self._ping_task: Optional[asyncio.Task] = None
        self._hb_task: Optional[asyncio.Task] = None
        self._repair_task: Optional[asyncio.Task] = None
        self.addr: Optional[Tuple[str, int]] = None
        self._stopped = False
        # observability (CephContext role): perf counters + op tracker;
        # the admin socket starts only when admin_socket_dir is configured
        self.ctx = Context(f"osd.{osd_id}",
                           conf if isinstance(conf, dict) else None)
        self.perf = self.ctx.perf.add(
            PerfCountersBuilder("osd")
            .add_u64_counter("op", "client ops")
            .add_u64_counter("op_w", "client writes")
            .add_u64_counter("op_r", "client reads")
            .add_time_avg("op_lat", "client op latency")
            .add_u64_counter("subop_w", "EC sub-writes applied")
            .add_u64_counter("subop_r", "EC sub-reads served")
            .add_u64_counter("recovery_push", "recovery shards pushed")
            .add_u64_counter("op_queued", "ops entering the sharded queue")
            .add_u64_counter("op_dequeued", "ops drained")
            .add_time_avg("op_queue_lat", "op service time")
            .add_u64_counter("heartbeat_failures", "peer failures reported")
            .create_perf_counters()
        )
        self.op_queue = ShardedOpQueue(
            int(self.conf.get("osd_op_num_shards", 4) or 4), self.conf,
            perf=self.perf)
        # OSD<->OSD heartbeat state (two-tier failure detection);
        # _hb_reported maps peer -> last MOSDFailure stamp so reports
        # re-send while the peer stays silent (evidence at the mon expires)
        self._hb_last: Dict[int, float] = {}
        self._hb_reported: Dict[int, float] = {}

    # -- lifecycle -----------------------------------------------------------

    async def start(self) -> int:
        self.messenger.dispatcher = self._dispatch
        self.addr = await self.messenger.bind()
        boot = MOsdBoot(osd_id=self.osd_id, addr=self.addr)
        # a no-quorum window answers boot with osd_id=-1: retry, don't run
        # as a ghost daemon the mon will never recognize
        for attempt in range(8):
            reply = await self._mon_rpc(boot, MBootReply)
            if reply.osd_id >= 0:
                break
            self.mons.rotate()
            await asyncio.sleep(0.25 * (attempt + 1))
        else:
            raise RuntimeError("mon refused boot (no quorum?)")
        self.osd_id = reply.osd_id
        self.messenger.name = f"osd.{self.osd_id}"
        self.osdmap = reply.osdmap
        # centralized config distributed at boot (ConfigMonitor role)
        cluster_conf = getattr(reply, "cluster_conf", None)
        if cluster_conf:
            if hasattr(self.conf, "set"):
                # per-key: one bad replicated value must not brick boot
                for k, v in cluster_conf.items():
                    try:
                        self.conf.set(k, v, source="mon")
                    except ValueError:
                        pass
            else:
                for k, v in cluster_conf.items():
                    self.conf.setdefault(k, v)
        interval = self.conf.get("osd_heartbeat_interval", 0.3)
        loop = asyncio.get_running_loop()
        self._ping_task = loop.create_task(self._ping_loop(interval))
        self._hb_task = loop.create_task(self._heartbeat_loop(interval))
        self.op_queue.start()
        self.ctx.name = f"osd.{self.osd_id}"
        asok_dir = self.conf.get("admin_socket_dir")
        if asok_dir:
            self.ctx.asok.register(
                "status", lambda a: self.status(), "osd status")
            await self.ctx.asok.start(f"{asok_dir}/osd.{self.osd_id}.asok")
        return self.osd_id

    def status(self) -> dict:
        return {
            "osd_id": self.osd_id,
            "epoch": self.osdmap.epoch if self.osdmap else 0,
            "op_queue_depth": self.op_queue.depth(),
            "hb_peers": sorted(self._hb_last),
        }

    async def stop(self) -> None:
        self._stopped = True
        for t in (self._ping_task, self._hb_task, self._repair_task):
            if t:
                t.cancel()
        await self.op_queue.stop()
        await self.ctx.shutdown()
        await self.messenger.shutdown()
        close = getattr(self.store, "close", None)
        if close is not None:
            close()

    @property
    def mon_addr(self):
        return self.mons.current

    async def _ping_loop(self, interval: float) -> None:
        while not self._stopped:
            try:
                await self.messenger.send(
                    self.mons.current,
                    MPing(osd_id=self.osd_id,
                          epoch=self.osdmap.epoch if self.osdmap else 0,
                          addr=self.addr or ("", 0)),
                )
            except Exception:
                self.mons.rotate()  # that mon looks dead
            await asyncio.sleep(interval)

    async def _heartbeat_loop(self, interval: float) -> None:
        """OSD<->OSD liveness (maybe_update_heartbeat_peers + heartbeat,
        OSD.cc:5278,5837): ping every up peer; a peer silent past the grace
        is reported to the mon as MOSDFailure."""
        grace = float(self.conf.get("osd_heartbeat_grace", 2.0) or 2.0)
        while not self._stopped:
            await asyncio.sleep(interval)
            if self.osdmap is None:
                continue
            now = time.monotonic()
            peers = [o for o in self.osdmap.osds.values()
                     if o.up and o.osd_id != self.osd_id]
            for o in peers:
                try:
                    await self.messenger.send(
                        o.addr, MOSDPing(op="ping", from_osd=self.osd_id,
                                         stamp=now,
                                         epoch=self.osdmap.epoch))
                except Exception:
                    pass
                last = self._hb_last.setdefault(o.osd_id, now)
                last_report = self._hb_reported.get(o.osd_id, -1e9)
                if now - last > grace and now - last_report > grace:
                    # re-report each grace interval while the peer stays
                    # silent: the mon ages out stale reporter evidence, so
                    # one-shot reports could never meet a multi-reporter
                    # threshold (reference re-sends MOSDFailure too)
                    self._hb_reported[o.osd_id] = now
                    self.perf.inc("heartbeat_failures")
                    try:
                        await self.messenger.send(
                            self.mons.current,
                            MOSDFailure(target_osd=o.osd_id,
                                        from_osd=self.osd_id,
                                        failed_for=now - last))
                    except Exception:
                        pass
            # prune state for peers no longer up in the map
            live = {o.osd_id for o in peers}
            for dead in list(self._hb_last):
                if dead not in live:
                    self._hb_last.pop(dead, None)
                    self._hb_reported.pop(dead, None)

    async def _mon_rpc(self, msg, reply_type):
        """Send to a mon and wait for the typed reply; rotate through the
        monmap on timeout (peons forward writes to the leader)."""
        key = f"monrpc-{reply_type.__name__}"
        last: Exception = TimeoutError("no mon reachable")
        for _ in range(len(self.mons)):
            fut: asyncio.Future = asyncio.get_running_loop().create_future()
            self._pending[key] = fut
            try:
                await self.messenger.send(self.mons.current, msg)
                return await asyncio.wait_for(fut, timeout=10)
            except (ConnectionError, OSError, asyncio.TimeoutError) as e:
                last = e
                self.mons.rotate()
        raise last

    # -- codecs --------------------------------------------------------------

    def _codec(self, pool: PoolInfo):
        codec = self._codecs.get(pool.pool_id)
        if codec is None:
            profile = dict(pool.profile)
            codec = registry.factory(
                profile.get("plugin", "jerasure"), profile.get("directory", ""), profile
            )
            self._codecs[pool.pool_id] = codec
        return codec

    # -- dispatch ------------------------------------------------------------

    async def _dispatch(self, conn, msg) -> None:
        if isinstance(msg, MMapReply):
            if msg.osdmap is not None:
                self._on_map(msg.osdmap)
            elif msg.incrementals and self.osdmap is not None:
                # apply the delta chain to a copy; on a broken chain fall
                # back to a full-map fetch (reference subscriber behavior)
                m = pickle.loads(pickle.dumps(self.osdmap, protocol=5))
                if all(m.apply_incremental(inc) for inc in msg.incrementals):
                    self._on_map(m)
                else:
                    asyncio.get_running_loop().create_task(self._fetch_full_map())
            fut = self._pending.pop("monrpc-MMapReply", None)
            if fut and not fut.done():
                fut.set_result(msg)
        elif isinstance(msg, MBootReply):
            fut = self._pending.pop("monrpc-MBootReply", None)
            if fut and not fut.done():
                fut.set_result(msg)
        elif isinstance(msg, MOSDPing):
            if msg.op == "ping":
                try:
                    await conn.send(MOSDPing(op="reply", from_osd=self.osd_id,
                                             stamp=msg.stamp))
                except (ConnectionError, OSError):
                    pass
            else:
                self._hb_last[msg.from_osd] = time.monotonic()
                self._hb_reported.pop(msg.from_osd, None)
        elif isinstance(msg, MOSDOp):
            # client ops ride the sharded op queue: PG-pinned shard keeps
            # per-PG order; scheduler arbitrates client vs recovery
            # classes; a full queue blocks HERE so the messenger stops
            # reading and backpressure reaches the sender
            pg_key = self._pg_key_of(msg)
            await self.op_queue.enqueue(
                pg_key, lambda: self._handle_client_op(conn, msg),
                CLASS_RECOVERY if msg.op == "repair" else CLASS_CLIENT,
                cost=max(1, len(msg.data) // 4096),
            )
        elif isinstance(msg, MECSubWrite):
            await self._handle_sub_write(msg)
        elif isinstance(msg, MECSubRead):
            await self._handle_sub_read(msg)
        elif isinstance(msg, MECSubDelete):
            await self._handle_sub_delete(msg)
        elif isinstance(msg, MListShards):
            await self._handle_list_shards(msg)
        elif isinstance(msg, MFetchShards):
            await self._handle_fetch_shards(msg)
        elif isinstance(msg, MPushShard):
            self._apply_push(msg)
        elif isinstance(
            msg, (MECSubWriteReply, MECSubReadReply, MListShardsReply, MFetchShardsReply)
        ):
            q = self._collectors.get(msg.tid)
            if q is not None:
                q.put_nowait(msg)

    async def _fetch_full_map(self) -> None:
        try:
            await self._mon_rpc(MGetMap(min_epoch=0), MMapReply)
        except Exception:
            pass

    def _on_map(self, osdmap: OSDMap) -> None:
        old = self.osdmap
        if old is not None and osdmap.epoch <= old.epoch:
            return
        self.osdmap = osdmap
        # invalidate only codecs whose pool profile actually changed —
        # plugin=tpu codecs carry jit caches worth keeping across epochs
        for pool_id in list(self._codecs):
            new_pool = osdmap.pools.get(pool_id)
            old_pool = old.pools.get(pool_id) if old else None
            if new_pool is None or old_pool is None or new_pool.profile != old_pool.profile:
                self._codecs.pop(pool_id, None)
        if self.conf.get("osd_auto_repair", True):
            if self._repair_task is None or self._repair_task.done():
                self._repair_task = asyncio.get_running_loop().create_task(
                    self._delayed_repair()
                )

    async def _delayed_repair(self) -> None:
        await asyncio.sleep(self.conf.get("osd_repair_delay", 0.5))
        try:
            for pool in list(self.osdmap.pools.values()):
                if pool.pool_type == "ec":
                    await self.repair_pool(pool)
        except Exception:
            pass

    # -- sub-op RPC plumbing -------------------------------------------------

    def _collector(self, tid: str) -> asyncio.Queue:
        q: asyncio.Queue = asyncio.Queue()
        self._collectors[tid] = q
        return q

    async def _gather(self, tid: str, q: asyncio.Queue, expected: int, timeout: float = 5.0):
        out = []
        try:
            for _ in range(expected):
                out.append(await asyncio.wait_for(q.get(), timeout=timeout))
        except asyncio.TimeoutError:
            pass
        finally:
            self._collectors.pop(tid, None)
        return out

    # -- client ops (primary) ------------------------------------------------

    def _store_read(self, key):
        """store.read with EIO absorbed to a missing-shard result: a bad
        local shard must degrade, never crash, the op (EIO handling the
        reference tests via bluestore read-error injection)."""
        try:
            return self.store.read(key)
        except IOError:
            return None

    def _pg_key_of(self, op: MOSDOp) -> int:
        if self.osdmap is None:
            return 0
        pool = self.osdmap.pools.get(op.pool_id)
        if pool is None:
            return op.pool_id
        return (op.pool_id << 20) | self.osdmap.object_to_pg(pool, op.oid)

    async def _handle_client_op(self, conn, op: MOSDOp) -> None:
        tracked = self.ctx.op_tracker.create(
            f"osd_op({op.op} {op.pool_id}:{op.oid})")
        t0 = time.monotonic()
        self.perf.inc("op")
        if op.op == "write":
            self.perf.inc("op_w")
        elif op.op == "read":
            self.perf.inc("op_r")
        try:
            await self._handle_client_op_inner(conn, op, tracked)
        finally:
            self.perf.tinc("op_lat", time.monotonic() - t0)
            tracked.finish()

    async def _handle_client_op_inner(self, conn, op: MOSDOp,
                                      tracked) -> None:
        tracked.mark_event("reached_pg")
        try:
            if op.op == "write":
                reply = await self._do_write(op)
            elif op.op == "read":
                reply = await self._do_read(op)
            elif op.op == "delete":
                reply = await self._do_delete(op)
            elif op.op == "list":
                oids = sorted({oid for oid, _ in self.store.list_objects(op.pool_id)})
                reply = MOSDOpReply(ok=True, oids=oids)
            elif op.op == "repair":
                pool = self.osdmap.pools.get(op.pool_id)
                if pool is not None:
                    await self.repair_pool(pool)
                reply = MOSDOpReply(ok=True)
            else:
                reply = MOSDOpReply(ok=False, error=f"bad op {op.op}")
        except ErasureCodeError as e:
            reply = MOSDOpReply(ok=False, error=f"ec error: {e}")
        except Exception as e:
            reply = MOSDOpReply(ok=False, error=f"{type(e).__name__}: {e}")
        reply.reqid = op.reqid
        try:
            await conn.send(reply)
        except ConnectionError:
            pass

    def _acting(self, pool: PoolInfo, oid: str) -> Tuple[int, List[int]]:
        pg = self.osdmap.object_to_pg(pool, oid)
        return pg, self.osdmap.pg_to_acting(pool, pg)

    def _primary(self, pool: PoolInfo, pg: int, acting: List[int]):
        return self.osdmap.primary_of(acting, seed=(pool.pool_id << 20) | pg)

    async def _do_write(self, op: MOSDOp) -> MOSDOpReply:
        pool = self.osdmap.pools[op.pool_id]
        codec = self._codec(pool)
        pg, acting = self._acting(pool, op.oid)
        if self._primary(pool, pg, acting) != self.osd_id:
            return MOSDOpReply(ok=False, error="not primary")
        live = [a for a in acting if a != CRUSH_ITEM_NONE]
        if len(live) < pool.min_size:
            return MOSDOpReply(
                ok=False,
                error=f"degraded below min_size ({len(live)}/{pool.min_size})",
            )
        n = codec.get_chunk_count()
        encoded = codec.encode(set(range(n)), op.data)
        version = time.time_ns()
        tid = uuid.uuid4().hex
        remote: List[Tuple[int, int]] = []  # (shard, osd)
        for shard, osd in enumerate(acting):
            if osd == CRUSH_ITEM_NONE:
                continue
            chunk = bytes(encoded[shard])
            if osd == self.osd_id:
                self._apply_shard_write(
                    op.pool_id, op.oid, shard, chunk, version, len(op.data)
                )
            else:
                remote.append((shard, osd))
        q = self._collector(tid)
        sent = 0
        for shard, osd in remote:
            chunk = bytes(encoded[shard])
            msg = MECSubWrite(
                pool_id=op.pool_id, pg=pg, oid=op.oid, shard=shard, chunk=chunk,
                version=version, object_size=len(op.data),
                chunk_crc=shard_crc(chunk), tid=tid, reply_to=self.addr,
            )
            try:
                await self.messenger.send(self.osdmap.addr_of(osd), msg)
                sent += 1
            except Exception:
                pass  # failed send counts as a missing ack, not a 5s stall
        replies = await self._gather(tid, q, sent)
        acks = 1 + sum(1 for r in replies if r.ok)  # self + remote
        if acks < pool.min_size:
            return MOSDOpReply(
                ok=False, error=f"write acked by {acks} < min_size {pool.min_size}"
            )
        return MOSDOpReply(ok=True)

    async def _do_read(self, op: MOSDOp) -> MOSDOpReply:
        pool = self.osdmap.pools[op.pool_id]
        codec = self._codec(pool)
        pg, acting = self._acting(pool, op.oid)
        k = codec.get_data_chunk_count()
        available = {
            shard: osd for shard, osd in enumerate(acting) if osd != CRUSH_ITEM_NONE
        }
        # ask the codec which shards suffice (subchunk-aware plan); the
        # wanted shards are the codec's DATA positions, which mapped codecs
        # (lrc) place at chunk_index(i), not at 0..k-1
        mapping = codec.get_chunk_mapping()
        want = {mapping[i] if mapping else i for i in range(k)}
        try:
            plan = codec.minimum_to_decode(want, set(available))
        except ErasureCodeError:
            return MOSDOpReply(ok=False, error="not enough shards up")
        tid = uuid.uuid4().hex
        chunks: Dict[int, bytes] = {}
        versions: Dict[int, int] = {}
        sizes: Dict[int, int] = {}
        remote = []
        for shard in plan:
            osd = available[shard]
            if osd == self.osd_id:
                got = self._store_read((op.pool_id, op.oid, shard))
                if got is not None:
                    chunks[shard] = got[0]
                    versions[shard] = got[1].version
                    sizes[shard] = got[1].object_size
            else:
                remote.append((shard, osd))
        q = self._collector(tid)
        sent = 0
        for shard, osd in remote:
            msg = MECSubRead(
                pool_id=op.pool_id, pg=pg, oid=op.oid, shard=shard, tid=tid,
                reply_to=self.addr,
            )
            try:
                await self.messenger.send(self.osdmap.addr_of(osd), msg)
                sent += 1
            except Exception:
                pass
        for r in await self._gather(tid, q, sent):
            if r.ok:
                chunks[r.shard] = r.chunk
                versions[r.shard] = r.version
                sizes[r.shard] = r.object_size
        # consistent-version cut: only shards at the newest version count
        newest = max(versions.values()) if versions else -1
        chunks = {s: c for s, c in chunks.items() if versions[s] == newest}
        if len(chunks) < k:
            # shard hunt across ALL up OSDs: shards carry their id, so a
            # degraded read survives placement drift between failure and
            # recovery (send_all_remaining_reads + missing-set role)
            hunted = await self._fetch_all_shards(op.pool_id, op.oid)
            if hunted:
                hunted_newest = max(v for (_, _, v, _) in hunted)
                if hunted_newest > newest:
                    newest = hunted_newest
                    chunks = {}
                for shard, chunk, version, osize in hunted:
                    if version == newest and shard not in chunks:
                        chunks[shard] = chunk
                        sizes[shard] = osize
                        versions[shard] = version
            if not chunks:
                return MOSDOpReply(ok=False, error="object not found")
            if len(chunks) < k:
                return MOSDOpReply(ok=False, error="cannot reconstruct: shards missing")
        object_size = sizes[max(sizes, key=lambda s: versions.get(s, 0))]
        arrays = {s: np.frombuffer(c, dtype=np.uint8) for s, c in chunks.items()}
        data = codec.decode_concat(arrays)
        return MOSDOpReply(ok=True, data=data[:object_size], version=newest)

    async def _do_delete(self, op: MOSDOp) -> MOSDOpReply:
        """Delete EVERY shard of the object on every up OSD, not just the
        current acting positions — stray shards left by placement drift
        would otherwise resurrect the object through the shard hunt."""
        pool = self.osdmap.pools[op.pool_id]
        pg, _ = self._acting(pool, op.oid)
        tid = uuid.uuid4().hex
        # local: drop any shard we hold
        txn = Transaction()
        for oid, shard in list(self.store.list_objects(op.pool_id)):
            if oid == op.oid:
                txn.delete((op.pool_id, op.oid, shard))
        self.store.queue_transaction(txn)
        peers = [
            o for o in self.osdmap.osds.values() if o.up and o.osd_id != self.osd_id
        ]
        q = self._collector(tid)
        sent = 0
        for o in peers:
            try:
                # shard=-1: drop every shard of the oid (one message per peer)
                await self.messenger.send(
                    o.addr,
                    MECSubDelete(pool_id=op.pool_id, pg=pg, oid=op.oid,
                                 shard=-1, tid=tid, reply_to=self.addr),
                )
                sent += 1
            except Exception:
                pass
        await self._gather(tid, q, sent)
        return MOSDOpReply(ok=True)

    # -- shard side ----------------------------------------------------------

    def _apply_shard_write(
        self, pool_id: int, oid: str, shard: int, chunk: bytes, version: int,
        object_size: int,
    ) -> None:
        txn = Transaction()
        txn.write(
            (pool_id, oid, shard),
            chunk,
            ShardMeta(version=version, object_size=object_size, chunk_crc=shard_crc(chunk)),
        )
        self.store.queue_transaction(txn)

    async def _handle_sub_write(self, msg: MECSubWrite) -> None:
        ok = True
        if msg.chunk_crc and shard_crc(msg.chunk) != msg.chunk_crc:
            ok = False  # corrupted in flight
        else:
            self._apply_shard_write(
                msg.pool_id, msg.oid, msg.shard, msg.chunk, msg.version, msg.object_size
            )
            self.perf.inc("subop_w")
        try:
            await self.messenger.send(
                tuple(msg.reply_to), MECSubWriteReply(tid=msg.tid, shard=msg.shard, ok=ok)
            )
        except Exception:
            pass

    async def _handle_sub_read(self, msg: MECSubRead) -> None:
        self.perf.inc("subop_r")
        try:
            got = self.store.read((msg.pool_id, msg.oid, msg.shard))
        except IOError:
            # EIO / checksum failure on our shard: reply error so the
            # primary reconstructs from other shards (the behavior
            # qa/standalone/erasure-code/test-erasure-eio.sh exercises)
            got = None
        if got is None:
            reply = MECSubReadReply(tid=msg.tid, shard=msg.shard, ok=False)
        else:
            chunk, meta = got
            reply = MECSubReadReply(
                tid=msg.tid, shard=msg.shard, ok=True, chunk=chunk,
                version=meta.version, object_size=meta.object_size,
            )
        try:
            await self.messenger.send(tuple(msg.reply_to), reply)
        except Exception:
            pass

    async def _handle_sub_delete(self, msg: MECSubDelete) -> None:
        txn = Transaction()
        if msg.shard < 0:  # whole-object delete
            for oid, shard in list(self.store.list_objects(msg.pool_id)):
                if oid == msg.oid:
                    txn.delete((msg.pool_id, msg.oid, shard))
        else:
            txn.delete((msg.pool_id, msg.oid, msg.shard))
        self.store.queue_transaction(txn)
        try:
            await self.messenger.send(
                tuple(msg.reply_to), MECSubWriteReply(tid=msg.tid, shard=msg.shard, ok=True)
            )
        except Exception:
            pass

    async def _fetch_all_shards(self, pool_id: int, oid: str):
        """Ask every up OSD for any shard of oid it holds; include our own."""
        out = []
        for oid2, shard in self.store.list_objects(pool_id):
            if oid2 == oid:
                got = self._store_read((pool_id, oid, shard))
                if got is not None:
                    out.append((shard, got[0], got[1].version, got[1].object_size))
        peers = [
            o for o in self.osdmap.osds.values() if o.up and o.osd_id != self.osd_id
        ]
        tid = uuid.uuid4().hex
        q = self._collector(tid)
        sent = 0
        for o in peers:
            try:
                await self.messenger.send(
                    o.addr,
                    MFetchShards(pool_id=pool_id, oid=oid, tid=tid, reply_to=self.addr),
                )
                sent += 1
            except Exception:
                pass
        for r in await self._gather(tid, q, sent):
            out.extend(tuple(s) for s in r.shards)
        return out

    async def _handle_fetch_shards(self, msg: MFetchShards) -> None:
        shards = []
        for oid, shard in self.store.list_objects(msg.pool_id):
            if oid == msg.oid:
                got = self._store_read((msg.pool_id, msg.oid, shard))
                if got is not None:
                    shards.append((shard, got[0], got[1].version, got[1].object_size))
        try:
            await self.messenger.send(
                tuple(msg.reply_to),
                MFetchShardsReply(tid=msg.tid, osd_id=self.osd_id, shards=shards),
            )
        except Exception:
            pass

    async def _handle_list_shards(self, msg: MListShards) -> None:
        entries = []
        for oid, shard in self.store.list_objects(msg.pool_id):
            got = self._store_read((msg.pool_id, oid, shard))
            if got is not None:
                entries.append((oid, shard, got[1].version))
        try:
            await self.messenger.send(
                tuple(msg.reply_to),
                MListShardsReply(tid=msg.tid, osd_id=self.osd_id, entries=entries),
            )
        except Exception:
            pass

    def _apply_push(self, msg: MPushShard) -> None:
        self.perf.inc("recovery_push")
        self._apply_shard_write(
            msg.pool_id, msg.oid, msg.shard, msg.chunk, msg.version, msg.object_size
        )

    # -- recovery ------------------------------------------------------------

    async def repair_pool(self, pool: PoolInfo) -> int:
        """Reconstruct and push shards missing from the current acting sets
        of objects this OSD is primary for.  Returns shards pushed."""
        codec = self._codec(pool)
        k = codec.get_data_chunk_count()
        # union of shard listings from all up OSDs
        tid = uuid.uuid4().hex
        peers = [
            o for o in self.osdmap.osds.values() if o.up and o.osd_id != self.osd_id
        ]
        q = self._collector(tid)
        sent = 0
        for o in peers:
            try:
                await self.messenger.send(
                    o.addr, MListShards(pool_id=pool.pool_id, tid=tid, reply_to=self.addr)
                )
                sent += 1
            except Exception:
                pass
        # oid -> {(shard, osd, version)}: versions matter — a stale shard
        # sitting at its acting position is NOT healthy redundancy
        holdings: Dict[str, Set[Tuple[int, int, int]]] = {}
        for oid, shard in self.store.list_objects(pool.pool_id):
            got = self._store_read((pool.pool_id, oid, shard))
            if got is not None:
                holdings.setdefault(oid, set()).add((shard, self.osd_id, got[1].version))
        for r in await self._gather(tid, q, sent):
            for oid, shard, version in r.entries:
                holdings.setdefault(oid, set()).add((shard, r.osd_id, version))
        pushed = 0
        for oid, locs in holdings.items():
            pg, acting = self._acting(pool, oid)
            if self._primary(pool, pg, acting) != self.osd_id:
                continue
            newest = max(v for (_, _, v) in locs)
            have = {shard: osd for shard, osd, v in locs if v == newest}
            missing = [
                (shard, osd)
                for shard, osd in enumerate(acting)
                if osd != CRUSH_ITEM_NONE and have.get(shard) != osd
            ]
            if not missing:
                continue
            # READING: gather k chunks (degraded-read machinery)
            read_op = MOSDOp(op="read", pool_id=pool.pool_id, oid=oid)
            reply = await self._do_read(read_op)
            if not reply.ok:
                continue
            # re-encode at the object's CURRENT version: deterministic encode
            # makes pushed shards byte-identical to the originals, and the
            # version stays consistent with surviving shards
            encoded = codec.encode(set(range(codec.get_chunk_count())), reply.data)
            version = reply.version
            for shard, osd in missing:
                chunk = bytes(encoded[shard])
                push = MPushShard(
                    pool_id=pool.pool_id, pg=pg, oid=oid, shard=shard, chunk=chunk,
                    version=version, object_size=len(reply.data),
                )
                if osd == self.osd_id:
                    self._apply_push(push)
                else:
                    try:
                        await self.messenger.send(self.osdmap.addr_of(osd), push)
                    except Exception:
                        continue
                pushed += 1
        return pushed
