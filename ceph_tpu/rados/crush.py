"""CRUSH-style placement: hierarchical straw2 buckets, rule steps with
firstn and indep modes, chooseleaf failure domains.

Functional equivalent of the reference's crush core + wrapper (reference
src/crush/mapper.c, src/crush/CrushWrapper.h): deterministic pseudo-random
placement computed identically by every party from the map alone.  The map
is a tree of typed buckets (root/rack/host/...) holding devices (ids >= 0)
or child buckets (ids < 0); rules are step programs
``take <root> -> choose/chooseleaf <mode> <n> <type> -> emit`` compiled by
``add_simple_rule`` exactly as the reference's
``ErasureCode::create_rule -> add_simple_rule(..., "indep")`` path does.

The property EC pools depend on is ``indep`` (crush_choose_indep,
mapper.c:630): positions in the acting set are *stable* — when a device
fails, surviving positions keep their shard index and the hole stays a hole
(CRUSH_ITEM_NONE) — because an EC chunk id is positional, unlike replica
copies (firstn, mapper.c:438, which fills forward).

Straw2 selection (mapper.c bucket_straw2_choose semantics): each item draws
ln(u)/weight and the maximum wins — exact weighted subset sampling with
minimal movement on weight change.  Bucket weights are the live sum of
descendant device weights, so marking a device out reweights its whole
subtree, as reweight-compat straw2 does.

Hash: 64-bit FNV-1a-folded mix rather than rjenkins1 — placement quality
and determinism are equivalent; byte-level parity with the reference's
mapping is NOT a goal of this layer (documented divergence; the EC chunk
bytes themselves are the byte-exact contract, not device selection).
"""

from __future__ import annotations

import math
import struct
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

CRUSH_ITEM_NONE = -1 << 30  # hole marker in indep mode (reference CRUSH_ITEM_NONE)

CHOOSE_TRIES = 19  # bounded retries per position (reference choose_total_tries=50)


def _mix(*vals: int) -> int:
    """Deterministic 64-bit hash of integers (placement draw)."""
    h = 0xCBF29CE484222325
    for v in vals:
        for b in struct.pack("<q", v & 0x7FFFFFFFFFFFFFFF):
            h ^= b
            h = (h * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    h ^= h >> 33
    h = (h * 0xFF51AFD7ED558CCD) & 0xFFFFFFFFFFFFFFFF
    h ^= h >> 33
    return h


@dataclass
class Bucket:
    """A straw2 bucket: items are device ids (>=0) or child buckets (<0)."""

    id: int
    type: str = "root"
    name: str = ""
    items: List[int] = field(default_factory=list)
    weights: Dict[int, float] = field(default_factory=dict)  # item -> weight


class CrushMap:
    DEVICE_TYPE = "osd"

    def __init__(self):
        self.buckets: Dict[int, Bucket] = {}
        self.rules: Dict[str, dict] = {}
        self.root_id: int = 0
        self._next_bucket_id = -1
        self._next_rule_id = 0
        # device -> stored crush weight (the caller's overlay overrides it,
        # the reference's crush-weight vs reweight split)
        self.device_weights: Dict[int, float] = {}

    # -- construction / editing (CrushWrapper role) --------------------------

    @classmethod
    def flat(cls, osd_ids: List[int]) -> "CrushMap":
        """One root bucket containing all OSDs (the vstart topology)."""
        m = cls()
        root = m.add_bucket("root", "default")
        for i in osd_ids:
            m.add_item(root, i, 1.0)
        return m

    @classmethod
    def with_hosts(cls, osd_ids: List[int], n_hosts: int) -> "CrushMap":
        """root -> host buckets -> OSDs (osd i on host i % n_hosts)."""
        m = cls()
        root = m.add_bucket("root", "default")
        hosts = []
        for h in range(n_hosts):
            hid = m.add_bucket("host", f"host{h}")
            m.add_item(root, hid, 0.0)
            hosts.append(hid)
        for i in osd_ids:
            m.add_item(hosts[i % n_hosts], i, 1.0)
        return m

    def add_bucket(self, type_: str, name: str) -> int:
        bid = self._next_bucket_id
        self._next_bucket_id -= 1
        self.buckets[bid] = Bucket(id=bid, type=type_, name=name)
        if type_ == "root" and self.root_id == 0:
            self.root_id = bid
        return bid

    def bucket_by_name(self, name: str) -> Optional[Bucket]:
        for b in self.buckets.values():
            if b.name == name:
                return b
        return None

    def add_item(self, bucket_id: int, item: int, weight: float = 1.0) -> None:
        b = self.buckets[bucket_id]
        if item not in b.items:
            b.items.append(item)
        b.weights[item] = weight
        if item >= 0:
            self.device_weights[item] = weight

    def remove_item(self, item: int) -> None:
        for b in self.buckets.values():
            if item in b.items:
                b.items.remove(item)
                b.weights.pop(item, None)
        self.device_weights.pop(item, None)

    def move_item(self, item: int, to_bucket: int, weight: float = 1.0) -> None:
        self.remove_item(item)
        self.add_item(to_bucket, item, weight)

    def set_weight(self, osd: int, weight: float) -> None:
        for b in self.buckets.values():
            if osd in b.weights and osd >= 0:
                b.weights[osd] = weight
        if osd >= 0:
            self.device_weights[osd] = weight

    def devices(self) -> List[int]:
        return sorted(
            i for b in self.buckets.values() for i in b.items if i >= 0
        )

    def parent_of(self, item: int) -> Optional[int]:
        """Containing bucket id, or None for the root / detached items."""
        for b in self.buckets.values():
            if item in b.items:
                return b.id
        return None

    def in_subtree(self, root: int, item: int) -> bool:
        """True when `item` sits anywhere under bucket `root` (the cycle
        guard for `crush move`: a bucket must never move under its own
        descendant)."""
        seen: Set[int] = set()
        stack = [root]
        while stack:
            bid = stack.pop()
            if bid >= 0 or bid in seen:
                continue
            seen.add(bid)
            b = self.buckets.get(bid)
            if b is None:
                continue
            if item in b.items:
                return True
            stack.extend(i for i in b.items if i < 0)
        return False

    def subtree_devices(self, item: int) -> List[int]:
        """Every device id under `item` (a device is its own subtree)."""
        if item >= 0:
            return [item]
        out: List[int] = []
        seen: Set[int] = set()
        stack = [item]
        while stack:
            bid = stack.pop()
            if bid in seen:
                continue
            seen.add(bid)
            b = self.buckets.get(bid)
            if b is None:
                continue
            for i in b.items:
                if i >= 0:
                    out.append(i)
                else:
                    stack.append(i)
        return sorted(out)

    def sig(self) -> Tuple:
        """Canonical topology signature — buckets (type/name/membership/
        stored weights), device weights, rule names.  OSDMapIncremental
        compares signatures so bucket-only edits (`crush move`,
        `crush add-bucket`) ship the crush map even when the device set
        itself did not change."""
        return (
            tuple(sorted(
                (bid, b.type, b.name, tuple(b.items),
                 tuple(sorted(b.weights.items())))
                for bid, b in self.buckets.items())),
            tuple(sorted(self.device_weights.items())),
            tuple(sorted(self.rules)),
        )

    # -- rules ---------------------------------------------------------------

    def add_simple_rule(
        self, name: str, root: str = "default", failure_domain: str = "osd",
        mode: str = "indep",
    ) -> int:
        """Reference CrushWrapper::add_simple_rule: compiles
        take/chooseleaf/emit steps; EC uses mode=indep
        (ErasureCode::create_rule, ErasureCode.cc:64)."""
        rule_id = self._next_rule_id
        self._next_rule_id += 1
        root_bucket = self.bucket_by_name(root)
        root_id = root_bucket.id if root_bucket else self.root_id
        if failure_domain == self.DEVICE_TYPE:
            steps = [("take", root_id), ("choose", mode, 0, self.DEVICE_TYPE),
                     ("emit",)]
        else:
            steps = [("take", root_id),
                     ("chooseleaf", mode, 0, failure_domain), ("emit",)]
        self.rules[name] = {"id": rule_id, "mode": mode, "steps": steps}
        return rule_id

    # -- the mapper ----------------------------------------------------------

    def _effective_weight(self, item: int, overlay: Dict[int, float],
                          memo: Dict[int, float]) -> float:
        """Device: overlay weight if given (down/out = 0), else the stored
        crush weight.  Bucket: sum of subtree."""
        if item >= 0:
            return overlay.get(item, self.device_weights.get(item, 1.0))
        if item in memo:
            return memo[item]
        memo[item] = 0.0  # cycle guard
        b = self.buckets.get(item)
        if b is not None:
            memo[item] = sum(
                self._effective_weight(i, overlay, memo) for i in b.items
            )
        return memo[item]

    def _straw2(self, bucket: Bucket, x: int, r: int, exclude: Set[int],
                overlay: Dict[int, float], memo: Dict[int, float]) -> Optional[int]:
        best, best_draw = None, -math.inf
        for item in bucket.items:
            if item in exclude:
                continue
            w = self._effective_weight(item, overlay, memo)
            if w <= 0:
                continue
            u = (_mix(x, item, r) & 0xFFFF) / 65536.0
            draw = math.log(u + 1.0 / 65536.0) / w
            if draw > best_draw:
                best, best_draw = item, draw
        return best

    def _descend(self, bucket: Bucket, x: int, r: int, want_type: str,
                 exclude: Set[int], overlay: Dict[int, float],
                 memo: Dict[int, float]) -> Optional[int]:
        """Walk down from bucket to an item of want_type via straw2 at each
        level (the recursive heart of crush_choose_*)."""
        node = bucket
        for _depth in range(16):
            c = self._straw2(node, x, r, exclude, overlay, memo)
            if c is None:
                return None
            if c >= 0:
                return c if want_type == self.DEVICE_TYPE else None
            child = self.buckets[c]
            if child.type == want_type:
                return c
            node = child
        return None

    def _leaf_of(self, bucket_id: int, x: int, r: int, exclude: Set[int],
                 overlay: Dict[int, float], memo: Dict[int, float]) -> Optional[int]:
        """Descend from a failure-domain bucket to one device."""
        if bucket_id >= 0:
            return bucket_id
        return self._descend(self.buckets[bucket_id], x, r,
                             self.DEVICE_TYPE, exclude, overlay, memo)

    def do_rule(self, rule_name: str, x: int, num_rep: int,
                weights: Dict[int, float]) -> List[int]:
        """Map input x (PG seed) to num_rep devices.

        indep mode (EC): each position r draws independently with bounded
        retries; an unplaceable position stays CRUSH_ITEM_NONE — holes are
        holes (mapper.c:630 crush_choose_indep).
        firstn mode (replication): forward-filled distinct choices
        (mapper.c:438 crush_choose_firstn)."""
        rule = self.rules.get(rule_name)
        if rule is None:
            rule = {"mode": "indep",
                    "steps": [("take", self.root_id),
                              ("choose", "indep", 0, self.DEVICE_TYPE),
                              ("emit",)]}
        overlay = dict(weights)
        memo: Dict[int, float] = {}
        working: List[int] = [self.root_id]
        out: List[int] = []
        for step in rule["steps"]:
            if step[0] == "take":
                working = [step[1]]
            elif step[0] in ("choose", "chooseleaf"):
                _, mode, n, want_type = step
                n = n or num_rep
                chooseleaf = step[0] == "chooseleaf"
                result: List[int] = []
                for take in working:
                    bucket = self.buckets[take]
                    if mode == "firstn":
                        result.extend(self._choose_firstn(
                            bucket, x, n, want_type, chooseleaf, overlay, memo))
                    else:
                        result.extend(self._choose_indep(
                            bucket, x, n, want_type, chooseleaf, overlay, memo))
                working = result
            elif step[0] == "emit":
                out.extend(working)
                working = [self.root_id]
        return out[:num_rep] if rule["mode"] == "firstn" else (
            out + [CRUSH_ITEM_NONE] * num_rep)[:num_rep]

    def _choose_firstn(self, bucket: Bucket, x: int, n: int, want_type: str,
                       chooseleaf: bool, overlay: Dict[int, float],
                       memo: Dict[int, float]) -> List[int]:
        out: List[int] = []
        chosen: Set[int] = set()
        leaves: Set[int] = set()
        for r in range(n * CHOOSE_TRIES):
            if len(out) == n:
                break
            c = self._descend(bucket, x, r, want_type, chosen, overlay, memo)
            if c is None:
                continue
            if chooseleaf:
                leaf = self._leaf_of(c, x, r, leaves, overlay, memo)
                if leaf is None:
                    continue
                chosen.add(c)
                leaves.add(leaf)
                out.append(leaf)
            else:
                chosen.add(c)
                out.append(c)
        return out

    def _choose_indep(self, bucket: Bucket, x: int, n: int, want_type: str,
                      chooseleaf: bool, overlay: Dict[int, float],
                      memo: Dict[int, float]) -> List[int]:
        """Multi-pass with per-position collision retry (mapper.c:630): each
        position's draw sequence r = pos + attempt*97 is independent of
        other positions' outcomes; a collision or dead device bumps only
        THAT position to its next attempt.  Unfilled positions stay
        CRUSH_ITEM_NONE — holes are holes, never compacted."""
        out = [CRUSH_ITEM_NONE] * n
        leaves_out = [CRUSH_ITEM_NONE] * n
        taken: Set[int] = set()
        taken_leaves: Set[int] = set()
        for attempt in range(CHOOSE_TRIES):
            undone = [p for p in range(n) if out[p] == CRUSH_ITEM_NONE]
            if not undone:
                break
            for pos in undone:
                r = pos + attempt * 97
                c = self._descend(bucket, x, r, want_type, taken, overlay, memo)
                if c is None:
                    continue
                if chooseleaf:
                    leaf = self._leaf_of(c, x, r, taken_leaves, overlay, memo)
                    if leaf is None:
                        continue
                    taken.add(c)
                    taken_leaves.add(leaf)
                    out[pos] = c
                    leaves_out[pos] = leaf
                else:
                    taken.add(c)
                    out[pos] = c
        return leaves_out if chooseleaf else out


class CrushTester:
    """Reference src/crush/CrushTester.cc role: statistical validation of a
    rule — coverage, balance, and (for indep) positional stability."""

    def __init__(self, crush: CrushMap):
        self.crush = crush

    def test(self, rule: str, num_rep: int, n_inputs: int = 1024,
             weights: Optional[Dict[int, float]] = None) -> Dict:
        weights = weights if weights is not None else {
            d: 1.0 for d in self.crush.devices()
        }
        per_device: Dict[int, int] = {}
        holes = 0
        for x in range(n_inputs):
            acting = self.crush.do_rule(rule, x, num_rep, weights)
            for a in acting:
                if a == CRUSH_ITEM_NONE:
                    holes += 1
                else:
                    per_device[a] = per_device.get(a, 0) + 1
        placed = sum(per_device.values())
        expected = placed / max(1, len(per_device))
        worst = max(
            (abs(c - expected) / expected for c in per_device.values()),
            default=0.0,
        )
        return {"per_device": per_device, "holes": holes,
                "placed": placed, "max_deviation": worst}

    def indep_stability(self, rule: str, num_rep: int, kill: int,
                        n_inputs: int = 256) -> Dict:
        """After killing a device, indep must not compact (positions that
        lost their device become holes or get a fresh device IN PLACE) and
        collateral movement of unaffected positions must be minimal
        (collision-retry cascades move a small fraction; CRUSH minimizes,
        not zeroes, movement)."""
        alive = {d: 1.0 for d in self.crush.devices()}
        moved = affected = total = 0
        for x in range(n_inputs):
            before = self.crush.do_rule(rule, x, num_rep, alive)
            after = self.crush.do_rule(rule, x, num_rep, {**alive, kill: 0.0})
            assert len(after) == len(before) == num_rep
            for pos, dev in enumerate(before):
                if dev == CRUSH_ITEM_NONE:
                    continue
                total += 1
                if dev == kill:
                    affected += 1
                    assert after[pos] != kill
                elif after[pos] != dev:
                    moved += 1
        return {"total": total, "affected": affected, "moved": moved,
                "collateral_ratio": moved / max(1, total - affected)}
