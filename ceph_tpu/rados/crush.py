"""CRUSH-style placement: straw2 buckets with firstn and indep modes.

Functional equivalent of the reference's crush core (reference
src/crush/mapper.c): deterministic pseudo-random placement computed
identically by every party from the map alone.  The property EC pools
depend on is ``indep`` (crush_choose_indep, mapper.c:630): positions in
the acting set are *stable* — when a device fails, surviving positions
keep their shard index and the hole stays a hole — because an EC chunk id
is positional, unlike replica copies (firstn).

Hash: 64-bit FNV-1a-folded mix rather than rjenkins1 — placement quality
and determinism are equivalent; byte-level parity with the reference's
mapping is NOT a goal of this layer (documented divergence; the EC chunk
bytes themselves are the byte-exact contract, not device selection).

Straw2 selection (mapper.c bucket_straw2_choose semantics): each item
draws ln(hash_unit)/weight and the maximum wins, which gives exact
weighted subset sampling and minimal data movement on weight changes.
"""

from __future__ import annotations

import math
import struct
from dataclasses import dataclass, field
from typing import Dict, List, Optional

CRUSH_ITEM_NONE = -1  # hole marker in indep mode (reference CRUSH_ITEM_NONE)


def _mix(*vals: int) -> int:
    """Deterministic 64-bit hash of integers (placement draw)."""
    h = 0xCBF29CE484222325
    for v in vals:
        for b in struct.pack("<q", v & 0x7FFFFFFFFFFFFFFF):
            h ^= b
            h = (h * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    h ^= h >> 33
    h = (h * 0xFF51AFD7ED558CCD) & 0xFFFFFFFFFFFFFFFF
    h ^= h >> 33
    return h


@dataclass
class Bucket:
    """A straw2 bucket: items are device ids (>=0) or child buckets (<0)."""

    id: int
    items: List[int] = field(default_factory=list)
    weights: Dict[int, float] = field(default_factory=dict)  # item -> weight

    def straw2_choose(self, x: int, r: int, exclude: set) -> Optional[int]:
        best, best_draw = None, -math.inf
        for item in self.items:
            w = self.weights.get(item, 1.0)
            if w <= 0 or item in exclude:
                continue
            u = (_mix(x, item, r) & 0xFFFF) / 65536.0
            draw = math.log(u + 1.0 / 65536.0) / w
            if draw > best_draw:
                best, best_draw = item, draw
        return best


@dataclass
class CrushMap:
    buckets: Dict[int, Bucket] = field(default_factory=dict)
    root_id: int = -1
    rules: Dict[str, dict] = field(default_factory=dict)
    _next_rule_id: int = 0

    @classmethod
    def flat(cls, osd_ids: List[int]) -> "CrushMap":
        """One root bucket containing all OSDs (the vstart topology)."""
        root = Bucket(id=-1, items=list(osd_ids), weights={i: 1.0 for i in osd_ids})
        return cls(buckets={-1: root}, root_id=-1)

    def set_weight(self, osd: int, weight: float) -> None:
        for b in self.buckets.values():
            if osd in b.weights:
                b.weights[osd] = weight

    def add_simple_rule(
        self, name: str, root: str = "default", failure_domain: str = "osd",
        mode: str = "indep",
    ) -> int:
        """Reference ErasureCode::create_rule -> add_simple_rule(...,"indep")."""
        rule_id = self._next_rule_id
        self._next_rule_id += 1
        self.rules[name] = {"id": rule_id, "mode": mode, "root": self.root_id}
        return rule_id

    # -- the mapper ----------------------------------------------------------

    def do_rule(self, rule_name: str, x: int, num_rep: int, weights: Dict[int, float]) -> List[int]:
        """Map input x (PG seed) to num_rep devices.

        indep mode (EC): each position r draws independently with bounded
        retries; an unplaceable position stays CRUSH_ITEM_NONE — holes are
        holes (mapper.c:630 crush_choose_indep).
        firstn mode (replication): sequential distinct choices."""
        rule = self.rules.get(rule_name, {"mode": "indep"})
        root = self.buckets[self.root_id]
        # overlay current reweights (out = weight 0)
        saved = dict(root.weights)
        for item, w in weights.items():
            if item in root.weights:
                root.weights[item] = w
        try:
            if rule.get("mode") == "firstn":
                out: List[int] = []
                exclude: set = set()
                for r in range(num_rep * 4):
                    c = root.straw2_choose(x, r, exclude)
                    if c is None:
                        break
                    exclude.add(c)
                    out.append(c)
                    if len(out) == num_rep:
                        break
                return out
            # indep: one draw per position; straw2_choose already excludes
            # taken items, so an unplaceable position stays a hole
            out = [CRUSH_ITEM_NONE] * num_rep
            taken: set = set()
            for r in range(num_rep):
                c = root.straw2_choose(x, r, taken)
                if c is not None:
                    taken.add(c)
                    out[r] = c
            return out
        finally:
            root.weights = saved
