"""Paged resident store: page-table HBM residency for the cache tier.

Role-equivalent of the KV-cache page pool in a production inference
stack (the Ragged Paged Attention idiom, arXiv:2604.15464: fixed-size
pages, a per-object page table, ragged last pages) applied to EC shard
residency.  The r10 PlanarShardStore holds every resident as ONE
monolithic device buffer whose width was pow2-bucketed for the encode
lane — mixed object sizes fragment the budget (a 68 KiB stripe pays for
128 KiB) and eviction is all-or-nothing per object.  Here the budget is
ONE preallocated u32 slab carved into fixed-size pages
(``osd_tier_page_bytes``): a resident's packed-bit plane words are
TRIMMED to their true width and flattened row-major across a page table
(ordered page ids, ragged last page), so

- millions of mixed-size objects share the pool at O(page) granularity
  (the pow2 pad never lands; ``frag_saved_bytes`` gauges the win),
- eviction frees exactly the pages it needs — including PARTIAL
  eviction: ``shed_parity`` drops the page suffix holding the parity
  rows while the data-row prefix keeps serving reads,
- every page carries a DIRTY bit, the substrate for writeback cache
  mode: a writeback install pins a :class:`WritebackRecord` (the
  deferred local store apply) with its dirty pages, ``drop`` refuses
  dirty entries until the owner flushes (flush-before-evict), and
  ``clear_dirty`` is generation-tokened so a flush that raced an
  overwrite can never mark the NEWER write clean.

The slab is committed lazily (fixed-size sub-slabs allocate on first
touch) and has TWO arms behind one page table:

- the HOST arm: sub-slabs are numpy arrays, installs/gathers are
  memcpys, the pack/unpack device boundaries
  (``to_packedbit``/``from_packedbit``) are paid at the page-table
  edge.  Byte-identical to the r20 behavior, and the only arm when no
  device backend is live.
- the DEVICE arm (``osd_tier_device_slab`` / ``CEPH_TPU_DEVICE_SLAB``,
  auto-on when a real device backend is live): sub-slabs are
  ``jax.Array``s and installs/gathers run through the jitted,
  donation-annotated scatter/take kernels in ``ceph_tpu/ops/slab.py``
  (the Ragged Paged Attention idiom, arXiv:2604.15464).  A promote's
  pack->install is ONE async H2D (``h2d_installs``); a queue-produced
  resident (``all_bits`` from the encode lane) installs device-native
  with ZERO host copies (``device_installs``); gathers stay on device
  and feed decode through the jitted ``from_packedbit`` path, so bytes
  leave HBM only at the declared exit boundaries (``d2h_gathers`` —
  see ``SLAB_IO_BOUNDARY`` and the codec/slab-host-roundtrip lint
  rule).  Eviction, dirty bits, shed_parity and the memo are PAGE
  TABLE bookkeeping — identical across both arms by construction.

Thread-safe under one mutex, same discipline as PlanarShardStore; the
OSD event loop, the batching worker, and tests may touch it
concurrently.  Device kernel dispatches run under that mutex too — the
lock sequences donated installs against gathers, which is what makes
donation safe (a gather can only ever see the pre- or post-install
slab reference, never the donated buffer after it was consumed).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from collections import OrderedDict
from typing import Any, Dict, Iterable, List, Optional, Tuple

import numpy as np

from ceph_tpu.common.perf_counters import PerfCounters, PerfCountersBuilder

_SLAB_SHIFT = 8  # 2**8 pages per lazily-committed sub-slab

# functions allowed to materialize slab-gather results on the host (the
# codec/slab-host-roundtrip lint rule's per-module exemption list): the
# pagestore's own packed-byte exit is read()
SLAB_IO_BOUNDARY = ("read",)

_STAGING_ALIGN = 4096


def install_staging(nbytes: int) -> memoryview:
    """Page-aligned host staging for rx->install payloads (the shm
    messenger's blob landing zone).  Alignment matters twice: the shm
    consumer's native gather lands ring views on page boundaries, and a
    later device install's H2D reads a page-aligned source — the
    pinnable shape where pinned DMA exists; on a CPU-only host it is
    honestly just aligned host memory.  The returned view keeps its
    backing allocation alive (numpy base chain)."""
    n = int(nbytes)
    raw = np.empty(n + _STAGING_ALIGN, dtype=np.uint8)
    off = (-raw.ctypes.data) % _STAGING_ALIGN
    return memoryview(raw[off:off + n]).cast("B")


def device_slab_resolved(flag: Optional[bool] = None) -> bool:
    """Whether the store's device arm engages.  CEPH_TPU_DEVICE_SLAB=1
    forces it on (CPU-backend tests exercise the jitted kernels on
    jax-cpu arrays), =0 forces the host arm; otherwise the config flag
    (``osd_tier_device_slab``; False pins the host arm) gates the AUTO
    rule — device arm only when a real device backend is live (an
    explicit JAX_PLATFORMS=cpu is an operator decision and wins, the
    shared_batching_queue discipline)."""
    env = os.environ.get("CEPH_TPU_DEVICE_SLAB", "")
    if env == "1":
        return True
    if env == "0":
        return False
    if flag is not None and not flag:
        return False
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        return False
    from ceph_tpu.utils.jaxdev import probe_backend

    return probe_backend() not in ("cpu", "unavailable")


@dataclass
class WritebackRecord:
    """The flush contract a writeback install pins with its dirty pages:
    everything the owner needs to replay the DEFERRED local store apply
    later — byte-identically to the write-through path — without the
    original write in hand.  Opaque to the store itself."""

    pool_id: int
    oid: str
    pg: int
    version: int
    object_size: int
    hinfo: bytes
    shards: Tuple[int, ...]           # local shards whose apply deferred
    crcs: Dict[int, int] = field(default_factory=dict)


@dataclass
class CacheDirtyRecord:
    """The flush contract a fast-ack writeback put pins with its RAW
    dirty object (w=0 entry, whole-object bytes — no EC encode happened
    yet): the k+m encode and sub-write fan-out are deferred entirely to
    the flush path.  ``primary`` names the OSD that installed the write
    (on a replica's adopted copy it is the writeback primary, not the
    holder); ``peers`` is the full cache replica set, primary included —
    the new primary replays the freshest copy from it after a primary
    death.  Generation-tokened and version-fenced exactly like
    :class:`WritebackRecord`; opaque to the store itself."""

    pool_id: int
    oid: str
    pg: int
    version: int
    object_size: int
    primary: int
    peers: Tuple[int, ...] = ()


class _Entry:
    __slots__ = ("pages", "dtype", "rows", "cols", "itemsize", "w",
                 "n_rows", "meta", "trim", "data_rows", "mono_bytes",
                 "total_words", "live_pages", "dirty", "dirty_info",
                 "dirty_since", "dirty_gen")


def build_pagestore_perf() -> PerfCounters:
    """The `pagestore` counter set (perf dump -> mgr /metrics -> BENCH)."""
    return (
        PerfCountersBuilder("pagestore")
        .add_u64_counter("admit", "residents installed into pages")
        .add_u64_counter("hit", "resident lookups served")
        .add_u64_counter("miss", "lookups that fell to the cold path")
        .add_u64_counter("evict", "whole residents evicted")
        .add_u64_counter("page_evictions", "pages freed by eviction "
                                           "(partial sheds included)")
        .add_u64_counter("parity_sheds",
                         "partial evictions that dropped only the "
                         "parity-row page suffix (data keeps serving)")
        .add_u64_counter("writeback_installs",
                         "dirty installs that deferred a local store "
                         "apply to flush")
        .add_u64_counter("flushes", "dirty residents flushed clean")
        .add_u64_counter("flush_bytes", "shard bytes written back by "
                                        "flushes")
        .add_u64_counter("evict_refused_dirty",
                         "drops refused because pages were dirty "
                         "(flush-before-evict held)")
        .add_u64_counter("install_refused",
                         "installs refused (pool full of dirty or "
                         "oversized resident)")
        .add_u64("pages_total", "page pool size (gauge)")
        .add_u64("pages_used", "pages currently owned by residents "
                               "(gauge)")
        .add_u64("dirty_pages", "pages carrying unflushed writeback "
                                "data (gauge)")
        .add_u64("dirty_bytes", "page bytes carrying unflushed "
                                "writeback data (gauge)")
        .add_u64("resident_bytes", "page bytes held by residents "
                                   "(gauge)")
        .add_u64("entries", "resident objects (gauge)")
        .add_u64("memo_bytes", "exit-boundary memo footprint, "
                               "page-rounded (gauge)")
        .add_u64("frag_saved_bytes",
                 "bytes the paged layout saves vs the monolithic "
                 "pow2-bucketed layout for the live residents (gauge, "
                 "floored at 0)")
        .add_u64("device_slabs", "committed device sub-slabs (gauge; 0 "
                                 "on the host arm)")
        .add_u64_counter("h2d_installs",
                         "installs whose page image crossed host->device "
                         "as ONE async copy (host-sourced bytes)")
        .add_u64_counter("device_installs",
                         "installs consumed device-native (queue-"
                         "produced residents; zero host copies)")
        .add_u64_counter("d2h_gathers",
                         "device->host materializations of gathered "
                         "slab bytes at the declared exit boundaries")
        .add_time_avg("pack_s", "device->host pack seconds at the exit "
                                "boundary")
        .add_time_avg("unpack_s", "host->device unpack seconds at "
                                  "admission")
        .create_perf_counters()
    )


class PagedResidentStore:
    """Drop-in residency manager behind the tier (PlanarShardStore
    surface: put_planar/get_planar/touch/gather_rows/drop/peek/memo),
    backed by the page pool above instead of per-object buffers."""

    def __init__(self, capacity_bytes: int = 256 << 20,
                 page_bytes: int = 64 << 10, queue: Optional[Any] = None,
                 device: Optional[bool] = None,
                 prewarm: bool = False):
        from ceph_tpu.common.lockdep import make_mutex

        page_bytes = max(256, int(page_bytes))
        page_bytes -= page_bytes % 4  # whole u32 words per page
        self.page_bytes = page_bytes
        self.page_words = page_bytes // 4
        self._pages_total = max(1, int(capacity_bytes) // page_bytes)
        self.queue = queue
        self._lock = make_mutex("pagestore")
        # arm selection: env override wins both ways, then an EXPLICIT
        # constructor choice (tests force the device arm on jax-cpu),
        # then the auto rule (device arm iff a real backend is live);
        # callers resolving a config flag pass device=None (auto) or
        # False (pinned host) via device_slab_resolved
        env = os.environ.get("CEPH_TPU_DEVICE_SLAB", "")
        if env in ("0", "1"):
            self.device_arm = env == "1"
        elif device is not None:
            self.device_arm = bool(device)
        else:
            self.device_arm = device_slab_resolved(None)
        self._slabs: List[Optional[np.ndarray]] = []
        self._dev_slabs: List[Optional[Any]] = []
        self.h2d_installs = 0
        self.device_installs = 0
        self.d2h_gathers = 0
        self._free: List[int] = []
        self._next_page = 0
        self._entries: "OrderedDict[Any, _Entry]" = OrderedDict()
        self._memo: Dict[Any, Tuple[Any, Any]] = {}
        self.memo_bytes = 0          # page-rounded (the r10 gauge could
        self._memo_raw: Dict[Any, int] = {}   # drift from residency)
        self._pages_used = 0
        self._dirty_page_count = 0
        self._gen = 0  # install generations: flush tokens never collide
        self._mono_bytes = 0         # monolithic-equivalent footprint
        self.admits = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.perf = build_pagestore_perf()
        self.perf.set("pages_total", self._pages_total)
        self.perf.resync = self._resync_gauges
        self.prewarmed = False
        if prewarm and self.device_arm:
            # compile the install/gather kernels for this page geometry
            # (every pow2 row bucket) at store build — the put window
            # must never pay an in-line XLA compile
            from ceph_tpu.ops.slab import prewarm as _slab_prewarm

            _slab_prewarm(self.page_words)
            self.prewarmed = True

    # -- capacity ------------------------------------------------------------

    @property
    def capacity_bytes(self) -> int:
        return self._pages_total * self.page_bytes

    @capacity_bytes.setter
    def capacity_bytes(self, value: int) -> None:
        # the budget is one shared pool: it only ever GROWS (the
        # shared_planar_store raise-the-budget rule); sub-slabs commit
        # lazily so raising the ceiling costs nothing up front
        with self._lock:
            self._pages_total = max(self._pages_total,
                                    max(1, int(value) // self.page_bytes))
            self.perf.set("pages_total", self._pages_total)

    @property
    def pages_total(self) -> int:
        return self._pages_total

    @property
    def pages_used(self) -> int:
        return self._pages_used

    @property
    def resident_bytes(self) -> int:
        return self._pages_used * self.page_bytes

    @property
    def dirty_pages(self) -> int:
        return self._dirty_page_count

    @property
    def dirty_bytes(self) -> int:
        return self._dirty_page_count * self.page_bytes

    # -- page pool (callers hold the lock) -----------------------------------

    def _page(self, pid: int) -> np.ndarray:
        slab = pid >> _SLAB_SHIFT
        while len(self._slabs) <= slab:
            self._slabs.append(None)
        if self._slabs[slab] is None:
            self._slabs[slab] = np.empty(
                (1 << _SLAB_SHIFT, self.page_words), dtype=np.uint32)
        return self._slabs[slab][pid & ((1 << _SLAB_SHIFT) - 1)]

    def _dev_slab(self, s: int):
        """Lazily-committed device sub-slab ``s`` (lock held).  The
        device arm's sibling of :meth:`_page`'s host commit — zeroed so
        the ragged install tail is well-defined."""
        from ceph_tpu.ops.slab import new_subslab

        while len(self._dev_slabs) <= s:
            self._dev_slabs.append(None)
        if self._dev_slabs[s] is None:
            self._dev_slabs[s] = new_subslab(1 << _SLAB_SHIFT,
                                             self.page_words)
            self.perf.set("device_slabs", self._device_slab_count())
        return self._dev_slabs[s]

    def _device_slab_count(self) -> int:
        return sum(1 for x in self._dev_slabs if x is not None)

    def _available_pages(self) -> int:
        return len(self._free) + (self._pages_total - self._next_page)

    def _alloc_page(self) -> Optional[int]:
        if self._free:
            return self._free.pop()
        if self._next_page < self._pages_total:
            pid = self._next_page
            self._next_page += 1
            return pid
        return None

    def _free_entry_pages(self, e: _Entry) -> int:
        freed = 0
        for i, pid in enumerate(e.pages):
            if pid is not None:
                self._free.append(pid)
                e.pages[i] = None
                freed += 1
        self._pages_used -= freed
        self._dirty_page_count -= len(e.dirty)
        e.dirty.clear()
        e.live_pages = 0
        return freed

    def _remove_entry(self, key: Any) -> int:
        """Free a key's pages and bookkeeping; lock held.  Returns pages
        freed."""
        e = self._entries.pop(key, None)
        if e is None:
            return 0
        freed = self._free_entry_pages(e)
        self._mono_bytes -= e.mono_bytes
        self._memo_discard(key)
        return freed

    def _sync_gauges(self) -> None:
        """Lock held."""
        self.perf.set("pages_used", self._pages_used)
        self.perf.set("dirty_pages", self._dirty_page_count)
        self.perf.set("dirty_bytes",
                      self._dirty_page_count * self.page_bytes)
        self.perf.set("resident_bytes",
                      self._pages_used * self.page_bytes)
        self.perf.set("entries", len(self._entries))
        self.perf.set("memo_bytes", self.memo_bytes)
        self.perf.set("pages_total", self._pages_total)
        self.perf.set("frag_saved_bytes", max(0, self.frag_saved_signed))
        self.perf.set("device_slabs", self._device_slab_count())

    def _resync_gauges(self) -> None:
        with self._lock:
            self._sync_gauges()

    @property
    def frag_saved_signed(self) -> int:
        """Monolithic-equivalent footprint minus actual page footprint.
        Positive = the pow2 pad the paged layout never allocated minus
        the ragged-tail waste it did; can go (slightly) negative for
        tiny residents whose tail waste exceeds their pad."""
        return self._mono_bytes - self._pages_used * self.page_bytes

    # -- install -------------------------------------------------------------

    @staticmethod
    def _trim_cols(dtype: np.dtype, cols: int, trim: Optional[int]) -> int:
        """Array columns to keep for a pre-pad packed byte width of
        ``trim``: u32 plane words carry 32 packed byte columns each;
        int8 plane columns are byte columns, rounded up to whole words
        so any bit-row range stays word-aligned in the flattened pool."""
        if not trim or trim <= 0:
            return cols
        if np.dtype(dtype) == np.uint32:
            return min(cols, -(-int(trim) // 32))
        return min(cols, ((int(trim) + 3) // 4) * 4)

    def _install_pages_locked(self, flat, total_words: int,
                              from_device: bool) -> List[Optional[int]]:
        """Device-arm install (lock held): allocate page ids, land the
        flat word image as page rows via ONE scatter kernel per touched
        sub-slab (ceph_tpu.ops.slab.slab_install, donation-annotated),
        and swap the donated sub-slab references under the lock.  A
        host-sourced image crosses h2d as ONE async copy of the whole
        zero-padded page image; a device-native image never touches
        host memory.  Returns the page-id list."""
        import jax.numpy as jnp

        from ceph_tpu.ops.slab import slab_install

        npages = -(-total_words // self.page_words) if total_words else 0
        pages: List[Optional[int]] = []
        for _ in range(npages):
            pid = self._alloc_page()
            assert pid is not None  # _available_pages said so
            pages.append(pid)
        if not npages:
            return pages
        pad = npages * self.page_words - total_words
        if from_device:
            buf = flat
            if pad:
                buf = jnp.concatenate(
                    [buf, jnp.zeros(pad, dtype=jnp.uint32)])
            self.device_installs += 1
            self.perf.inc("device_installs")
        else:
            host = np.zeros(npages * self.page_words, dtype=np.uint32)
            host[:total_words] = flat
            buf = jnp.asarray(host)  # the ONE h2d of the install
            self.h2d_installs += 1
            self.perf.inc("h2d_installs")
        rows = buf.reshape(npages, self.page_words)
        mask = (1 << _SLAB_SHIFT) - 1
        groups: "OrderedDict[int, List[int]]" = OrderedDict()
        for i, pid in enumerate(pages):
            groups.setdefault(pid >> _SLAB_SHIFT, []).append(i)
        for s, order in groups.items():
            idx = np.array([pages[i] & mask for i in order],
                           dtype=np.int32)
            if len(order) == npages:
                data = rows
            else:
                data = jnp.take(rows,
                                jnp.asarray(np.array(order,
                                                     dtype=np.int32)),
                                axis=0)
            # the old sub-slab reference is dropped HERE, under the
            # lock, before any gather can observe it — the donation
            # safety contract (slab.py docstring)
            self._dev_slabs[s] = slab_install(self._dev_slab(s), data,
                                              idx)
        return pages

    def put_planar(self, key: Any, bits, w: int = 8,
                   n_rows: Optional[int] = None, meta: Any = None,
                   trim: Optional[int] = None,
                   data_rows: Optional[int] = None,
                   dirty_rows: Optional[Iterable[Tuple[int, int]]] = None,
                   dirty_info: Any = None,
                   now: Optional[float] = None) -> bool:
        """Install a resident into pages.  ``trim`` (pre-pad packed byte
        width) drops the encode lane's pow2 pad before paging — the
        fragmentation win.  ``data_rows`` marks the bit-row prefix that
        is data (shed_parity boundary).  ``dirty_rows`` marks bit-row
        ranges whose backing-store apply is DEFERRED (writeback);
        ``dirty_info`` carries the owner's flush contract.  Returns
        False — nothing installed — when the pool cannot fit the
        resident even after evicting every clean colder entry (the
        caller falls back to the uninstalled path; refusal is counted,
        never an error)."""
        from_device = False
        if self.device_arm:
            from ceph_tpu.ops.slab import is_device_array

            from_device = (is_device_array(bits)
                           and str(bits.dtype) == "uint32")
        if from_device:
            # device-native install: a queue-produced resident (the
            # encode lane's packed-bit planes) never bounces through
            # host numpy — trim/flatten are device ops and the scatter
            # below consumes the same buffers
            rows, cols_full = int(bits.shape[0]), int(bits.shape[1])
            if n_rows is None:
                n_rows = rows // w
            itemsize = 4
            dtype = np.dtype(np.uint32)
            mono_bytes = rows * cols_full * itemsize
            cols = self._trim_cols(dtype, cols_full, trim)
            flat = (bits[:, :cols] if cols < cols_full else bits)
            flat = flat.reshape(-1)
        else:
            arr = np.asarray(bits)
            if n_rows is None:
                n_rows = arr.shape[0] // w
            rows, cols_full = int(arr.shape[0]), int(arr.shape[1])
            itemsize = arr.dtype.itemsize
            mono_bytes = rows * cols_full * itemsize
            cols = self._trim_cols(arr.dtype, cols_full, trim)
            if cols < cols_full:
                arr = arr[:, :cols]
            if np.dtype(arr.dtype) != np.uint32 and cols % 4:
                # non-u32 rows must stay word-aligned in the flattened
                # pool (gather addresses bit-rows as cols*itemsize//4
                # words) — pad the row width up to whole words; `trim`
                # keeps the true byte width for read()'s final slice
                pad = 4 - cols % 4
                arr = np.pad(np.asarray(arr), ((0, 0), (0, pad)))
                cols += pad
            dtype = np.dtype(arr.dtype)
            flat = np.ascontiguousarray(arr).reshape(-1)
            if flat.dtype != np.uint32:
                flat = flat.view(np.uint32)  # rows % 4 == 0 (w >= 4)
        total_words = rows * cols * itemsize // 4
        npages = max(1, -(-total_words // self.page_words))
        with self.perf.time_avg("unpack_s"), self._lock:
            self._remove_entry(key)
            if npages > self._pages_total:
                self.perf.inc("install_refused")
                self._sync_gauges()
                return False
            while self._available_pages() < npages:
                victim = None
                for k, e in self._entries.items():  # LRU-oldest first
                    if not e.dirty:
                        victim = k
                        break
                if victim is None:
                    self.perf.inc("install_refused")
                    self._sync_gauges()
                    return False
                freed = self._remove_entry(victim)
                self.evictions += 1
                self.perf.inc("evict")
                self.perf.inc("page_evictions", freed)
            e = _Entry()
            if self.device_arm:
                e.pages = self._install_pages_locked(flat, total_words,
                                                     from_device)
            else:
                e.pages = []
                off = 0
                while off < total_words:
                    pid = self._alloc_page()
                    assert pid is not None  # _available_pages said so
                    n = min(self.page_words, total_words - off)
                    self._page(pid)[:n] = flat[off:off + n]
                    e.pages.append(pid)
                    off += n
            e.dtype = dtype
            e.rows = rows
            e.cols = cols
            e.itemsize = itemsize
            e.w = w
            e.n_rows = n_rows
            e.meta = meta
            e.trim = trim
            e.data_rows = data_rows
            e.mono_bytes = mono_bytes
            e.total_words = total_words
            e.live_pages = len(e.pages)
            e.dirty = set()
            e.dirty_info = dirty_info
            e.dirty_since = time.monotonic() if now is None else now
            self._gen += 1
            e.dirty_gen = self._gen
            self._pages_used += len(e.pages)
            self._mono_bytes += mono_bytes
            if dirty_rows:
                row_words = cols * itemsize // 4
                for r0, r1 in dirty_rows:
                    p0 = (r0 * row_words) // self.page_words
                    p1 = -(-(r1 * row_words) // self.page_words)
                    e.dirty.update(range(p0, min(p1, len(e.pages))))
                self._dirty_page_count += len(e.dirty)
            self._entries[key] = e
            self._entries.move_to_end(key)
            self.admits += 1
            self._sync_gauges()
        self.perf.inc("admit")
        if dirty_rows and e.dirty:
            self.perf.inc("writeback_installs")
        return True

    # -- raw dirty objects (writeback fast-ack path) -------------------------

    def put_raw(self, key: Any, data: bytes, meta: Any = None,
                dirty_info: Any = None,
                now: Optional[float] = None) -> bool:
        """Install the WHOLE-OBJECT bytes as a raw dirty resident — the
        writeback fast-ack path's unit of replication (no EC encode has
        happened; the flush path owns the k+m destage).  Layout: one
        uint8 bit-row padded to a whole word, ``w=0`` as the raw
        sentinel (planar_rows/planar_shard_bytes see a zero-height
        gather range and fall through; ``trim`` keeps the true byte
        length).  Every page is dirty.  Same refusal contract as
        put_planar."""
        raw = np.frombuffer(bytes(data), dtype=np.uint8)
        if len(raw) % 4:
            raw = np.pad(raw, (0, 4 - len(raw) % 4))
        return self.put_planar(key, raw.reshape(1, -1), w=0, n_rows=1,
                               meta=meta, trim=len(data),
                               dirty_rows=[(0, 1)], dirty_info=dirty_info,
                               now=now)

    def is_raw(self, key: Any) -> bool:
        with self._lock:
            e = self._entries.get(key)
            return e is not None and e.w == 0

    def read_raw(self, key: Any) -> Optional[bytes]:
        """The raw entry's object bytes (None when absent, partial, or
        not a raw entry).  On the device arm the single materialization
        here is the declared d2h exit."""
        with self._lock:
            e = self._entries.get(key)
            if e is None or e.w != 0:
                return None
            trim = e.trim
            bits = self._gather_locked(e, 0, e.rows)
        if bits is None:
            return None
        out = np.asarray(bits).view(np.uint8).reshape(-1)
        self.note_d2h()
        return out[:trim].tobytes()

    # -- lookup --------------------------------------------------------------

    def _gather_locked(self, e: _Entry, r0: int, r1: int):
        row_words = e.cols * e.itemsize // 4
        w0, w1 = r0 * row_words, r1 * row_words
        if w1 > e.total_words or w0 < 0 or w1 <= w0:
            return None
        p0, p1 = w0 // self.page_words, -(-w1 // self.page_words)
        span = e.pages[p0:p1]
        if any(p is None for p in span):
            return None
        if self.device_arm:
            return self._gather_device_locked(e, r0, r1, w0, w1, p0,
                                              span)
        out = np.empty(w1 - w0, dtype=np.uint32)
        pos = 0
        for i, pid in enumerate(span):
            page = self._page(pid)
            start = (w0 - p0 * self.page_words) if i == 0 else 0
            avail = min(self.page_words,
                        e.total_words - (p0 + i) * self.page_words)
            take = min(avail - start, (w1 - w0) - pos)
            out[pos:pos + take] = page[start:start + take]
            pos += take
        if np.dtype(e.dtype) != np.uint32:
            return out.view(e.dtype).reshape(r1 - r0, e.cols)
        return out.reshape(r1 - r0, e.cols)

    def _gather_device_locked(self, e: _Entry, r0: int, r1: int,
                              w0: int, w1: int, p0: int,
                              span: List[int]):
        """Device-arm gather (lock held): one take kernel per touched
        sub-slab run, concatenated and sliced ON DEVICE.  The result is
        a fresh device buffer (never a slab view) — it stays valid
        across later donated installs and feeds the jitted decode path
        without leaving HBM; the host exit is read()/ecutil's
        ``_pack_rows`` (counted as ``d2h_gathers`` via note_d2h)."""
        import jax
        import jax.numpy as jnp

        from ceph_tpu.ops.slab import slab_gather

        mask = (1 << _SLAB_SHIFT) - 1
        parts = []
        i = 0
        while i < len(span):
            s = span[i] >> _SLAB_SHIFT
            idx = []
            while i < len(span) and (span[i] >> _SLAB_SHIFT) == s:
                idx.append(span[i] & mask)
                i += 1
            parts.append(slab_gather(self._dev_slab(s),
                                     np.array(idx, dtype=np.int32)))
        block = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
        flat = block.reshape(-1)
        start = w0 - p0 * self.page_words
        out = flat[start:start + (w1 - w0)]
        if np.dtype(e.dtype) != np.uint32:
            # little-endian u32 -> byte planes: bitcast appends a
            # trailing dim of 4 (LSB first), matching numpy .view on
            # the LE hosts this runs on (itemsize is 1 here — the
            # planes layout)
            out = jax.lax.bitcast_convert_type(out, jnp.int8)
        return out.reshape(r1 - r0, e.cols)

    def gather_rows(self, key: Any, r0: int, r1: int):
        """[r1-r0, cols] array gathered from the page table, or None
        when the entry is absent or any needed page was evicted (a
        partial resident can still serve any fully-covered row range —
        the data-row prefix after a parity shed).  No LRU side effects
        (``touch`` owns those)."""
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                return None
            return self._gather_locked(e, r0, r1)

    def touch(self, key: Any):
        """(w, n_rows, meta) with LRU refresh + hit/miss counting — the
        read path's entry probe, materializing nothing."""
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                self.misses += 1
            else:
                self._entries.move_to_end(key)
                self.hits += 1
        self.perf.inc("hit" if e is not None else "miss")
        return None if e is None else (e.w, e.n_rows, e.meta)

    def entry_info(self, key: Any):
        """(w, n_rows, meta) without LRU/counter side effects."""
        with self._lock:
            e = self._entries.get(key)
        return None if e is None else (e.w, e.n_rows, e.meta)

    def resident_meta(self, key: Any):
        """The entry's caller meta (the OSD stores (version, n_cols,
        object_size)), or None — the policy probe shape."""
        info = self.entry_info(key)
        return None if info is None else info[2]

    def get_planar(self, key: Any):
        """(bits, w, n_rows, meta) or None; refreshes LRU position.
        Gathers the WHOLE resident — None when partial (parity shed)."""
        got = self.touch(key)
        if got is None:
            return None
        w, n_rows, meta = got
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                return None
            bits = self._gather_locked(e, 0, e.rows)
        if bits is None:
            return None
        return (bits, w, n_rows, meta)

    def peek(self, key: Any):
        """get_planar without LRU order / counter side effects."""
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                return None
            bits = self._gather_locked(e, 0, e.rows)
        if bits is None:
            return None
        return (bits, e.w, e.n_rows, e.meta)

    def __contains__(self, key: Any) -> bool:
        with self._lock:
            return key in self._entries

    def entry_nbytes(self, key: Any) -> int:
        """Live page footprint of one entry (0 when absent)."""
        with self._lock:
            e = self._entries.get(key)
            return e.live_pages * self.page_bytes if e is not None else 0

    def entries_snapshot(self) -> List[Tuple[Any, int]]:
        """(key, page-footprint bytes) in LRU order, oldest first — the
        tier agent's eviction-candidate input."""
        with self._lock:
            return [(k, e.live_pages * self.page_bytes)
                    for k, e in self._entries.items()]

    # -- host boundary (test/bench parity with PlanarShardStore) -------------

    def admit(self, key: Any, rows: np.ndarray, w: int = 8,
              meta: Any = None, layout: str = "planes"):
        """Unpack packed [n, B] uint8 rows and keep them page-resident
        (PlanarShardStore.admit contract)."""
        if layout == "packedbit":
            from ceph_tpu.ops.gf2 import to_packedbit

            assert w == 8, "packed-bit residency is the w=8 byte layout"
            B = rows.shape[1]
            buf = np.ascontiguousarray(rows)
            if B % 32:
                buf = np.pad(buf, ((0, 0), (0, 32 - B % 32)))
            bits = to_packedbit(buf)
            self.put_planar(key, bits, w=w, n_rows=rows.shape[0],
                            meta=meta, trim=B)
        else:
            from ceph_tpu.ops.gf2 import to_planar

            bits = to_planar(np.ascontiguousarray(rows), w)
            self.put_planar(key, bits, w=w, n_rows=rows.shape[0],
                            meta=meta, trim=rows.shape[1])
        return bits

    def note_d2h(self) -> None:
        """Count ONE device->host materialization at a declared exit
        boundary (this module's read(); ecutil's ``_pack_rows``
        callers).  No-op on the host arm — nothing left the device."""
        if self.device_arm:
            self.d2h_gathers += 1
            self.perf.inc("d2h_gathers")

    def read(self, key: Any) -> Optional[np.ndarray]:
        """Pack the resident rows back to [n, B] uint8 host bytes; None
        when absent or partial.  On the device arm the gather feeds the
        jitted unpack on device and np.asarray here is the single d2h
        (the SLAB_IO_BOUNDARY exit)."""
        got = self.get_planar(key)
        if got is None:
            return None
        bits, w, n_rows, _meta = got
        with self._lock:
            e = self._entries.get(key)
            trim = e.trim if e is not None else None
        if w == 0:
            # raw whole-object entry (put_raw): no planar decode exists;
            # the single uint8 bit-row IS the bytes
            out = np.asarray(bits).view(np.uint8).reshape(1, -1)
            self.note_d2h()
            return out if trim is None else out[:, :trim]
        if np.dtype(bits.dtype) == np.uint32:
            from ceph_tpu.ops.gf2 import from_packedbit

            with self.perf.time_avg("pack_s"):
                out = np.asarray(from_packedbit(bits, n_rows))
        else:
            from ceph_tpu.ops.gf2 import from_planar

            with self.perf.time_avg("pack_s"):
                out = np.asarray(from_planar(bits, w, n_rows))
        self.note_d2h()
        return out if trim is None else out[:, :trim]

    # -- eviction ------------------------------------------------------------

    def drop(self, key: Any, force: bool = False) -> bool:
        """Remove `key` if resident; True when an entry was actually
        dropped.  A DIRTY entry refuses (flush-before-evict: writeback
        pages must never be the only copy of acked data) unless
        ``force`` — deletes and overwrite-failure cleanup force, because
        there the data itself is going away.  Dropping an absent key is
        a supported no-op (the agent/LRU race rule)."""
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                self._memo_discard(key)
                self._sync_gauges()
                return False
            if e.dirty and not force:
                self.perf.inc("evict_refused_dirty")
                return False
            freed = self._remove_entry(key)
            self.evictions += 1
            self._sync_gauges()
        self.perf.inc("evict")
        self.perf.inc("page_evictions", freed)
        return True

    def shed_parity(self, key: Any) -> int:
        """Partial eviction: free the CLEAN page suffix past the
        data-row boundary (the parity rows).  The data prefix keeps
        serving reads through gather_rows; get_planar/planar_rows see a
        partial resident and fall back.  Returns bytes freed (0 when no
        boundary was recorded, nothing to shed, or the suffix holds
        dirty pages)."""
        with self._lock:
            e = self._entries.get(key)
            if e is None or e.data_rows is None or e.data_rows >= e.rows:
                return 0
            row_words = e.cols * e.itemsize // 4
            boundary = -(-(e.data_rows * row_words) // self.page_words)
            freed = 0
            for i in range(boundary, len(e.pages)):
                if e.pages[i] is None or i in e.dirty:
                    continue
                self._free.append(e.pages[i])
                e.pages[i] = None
                e.live_pages -= 1
                freed += 1
            self._pages_used -= freed
            if freed:
                self._sync_gauges()
        if freed:
            self.perf.inc("parity_sheds")
            self.perf.inc("page_evictions", freed)
        return freed * self.page_bytes

    # -- dirty lifecycle (writeback) -----------------------------------------

    def is_dirty(self, key: Any) -> bool:
        with self._lock:
            e = self._entries.get(key)
            return bool(e is not None and e.dirty)

    def has_dirty(self) -> bool:
        return self._dirty_page_count > 0

    def peek_dirty(self, key: Any):
        """(dirty_info, generation token) or None.  The token pins the
        exact install the caller is about to flush."""
        with self._lock:
            e = self._entries.get(key)
            if e is None or not e.dirty:
                return None
            return (e.dirty_info, e.dirty_gen)

    def dirty_items(self) -> List[Tuple[Any, Any, int, float]]:
        """Snapshot of (key, dirty_info, generation, dirty_since),
        oldest-dirty first — the flush agent's input."""
        with self._lock:
            items = [(k, e.dirty_info, e.dirty_gen, e.dirty_since)
                     for k, e in self._entries.items() if e.dirty]
        items.sort(key=lambda t: t[3])
        return items

    def clear_dirty(self, key: Any, gen: int) -> bool:
        """Mark the entry clean after a successful flush — only when
        ``gen`` still names the install the caller flushed (an
        overwrite re-installed and bumped the generation: its dirt is
        NOT flushed, and clearing it would lose acked data)."""
        with self._lock:
            e = self._entries.get(key)
            if e is None or e.dirty_gen != gen or not e.dirty:
                return False
            self._dirty_page_count -= len(e.dirty)
            e.dirty.clear()
            e.dirty_info = None
            self._gen += 1
            e.dirty_gen = self._gen
            self._sync_gauges()
        return True

    # -- exit-boundary memo (page-granular accounting) -----------------------

    def _memo_charge(self, nbytes: int) -> int:
        return -(-nbytes // self.page_bytes) * self.page_bytes

    def _memo_discard(self, key: Any) -> None:
        """Lock held."""
        got = self._memo.pop(key, None)
        if got is not None:
            self.memo_bytes -= self._memo_charge(self._memo_raw.pop(key))

    def memo_get(self, key: Any, version: Any):
        with self._lock:
            if key not in self._entries:
                return None
            got = self._memo.get(key)
        if got is None or got[0] != version:
            return None
        return got[1]

    def memo_put(self, key: Any, version: Any, value: Any) -> None:
        """As PlanarShardStore.memo_put, but the cap accounting is in
        PAGE units against the pool's byte size — the memo gauge can
        never drift from the granularity actual residency is budgeted
        in."""
        charge = self._memo_charge(len(value))
        with self._lock:
            if key not in self._entries:
                return
            self._memo_discard(key)
            if self.memo_bytes + charge > self.capacity_bytes:
                self.perf.set("memo_bytes", self.memo_bytes)
                return
            self._memo[key] = (version, value)
            self._memo_raw[key] = len(value)
            self.memo_bytes += charge
            self.perf.set("memo_bytes", self.memo_bytes)

    # -- introspection -------------------------------------------------------

    def page_stats(self) -> Dict[str, int]:
        with self._lock:
            partial = sum(1 for e in self._entries.values()
                          if e.live_pages < len(e.pages))
            return {
                "page_bytes": self.page_bytes,
                "pages_total": self._pages_total,
                "pages_used": self._pages_used,
                "dirty_pages": self._dirty_page_count,
                "dirty_bytes": self._dirty_page_count * self.page_bytes,
                "dirty_entries": sum(1 for e in self._entries.values()
                                     if e.dirty),
                "partial_residents": partial,
                "frag_saved_bytes": max(0, self.frag_saved_signed),
                "monolithic_equiv_bytes": self._mono_bytes,
                "device_arm": int(self.device_arm),
                "device_slabs": self._device_slab_count(),
                "h2d_installs": self.h2d_installs,
                "device_installs": self.device_installs,
                "d2h_gathers": self.d2h_gathers,
            }

    def stats(self) -> Dict[str, int]:
        return {"resident_bytes": self.resident_bytes,
                "memo_bytes": self.memo_bytes,
                "entries": len(self._entries), "admits": self.admits,
                "hits": self.hits, "misses": self.misses,
                "evictions": self.evictions,
                "pages_total": self._pages_total,
                "pages_used": self._pages_used,
                "dirty_pages": self._dirty_page_count,
                "frag_saved_bytes": self.frag_saved_signed,
                "monolithic_equiv_bytes": self._mono_bytes,
                "device_arm": int(self.device_arm),
                "device_slabs": self._device_slab_count(),
                "h2d_installs": self.h2d_installs,
                "device_installs": self.device_installs,
                "d2h_gathers": self.d2h_gathers}
