"""Mon consensus: elections, Paxos-replicated state, persistent store.

Role-equivalent of the reference's mon consensus stack (reference
src/mon/Paxos.h:174, src/mon/Elector.cc, src/mon/ElectionLogic.cc,
src/mon/MonitorDBStore.h):

- :class:`MonitorDBStore` — each mon's local durable store.  The reference
  uses RocksDB through MonitorDBStore; here it is an atomically-rewritten
  pickle file (tiny state), with the same recovery contract: committed
  versions survive restart.
- :class:`ElectionLogic` — rank-based leader election: a candidate
  proposes with a monotonically increasing epoch; peers defer to the
  lowest-ranked live proposer; the winner declares victory with the
  acked quorum (the reference's CLASSIC strategy).
- :class:`Paxos` — the single consensus log all mon state rides
  (reference: one Paxos instance, PaxosService machines layered on it).
  Leader-driven: collect (on election) brings the quorum to the newest
  committed version, then each proposal is begin -> majority accept ->
  commit, fanned to peons.  Values are opaque bytes (the mon pickles its
  replicated state-machine delta).

Network send/receive is injected by the Monitor daemon; these classes hold
the protocol state so they can be unit-tested without sockets.
"""

from __future__ import annotations

import os
import pickle
import tempfile
import time
from typing import Any, Callable, Dict, List, Optional, Set, Tuple


class MonitorDBStore:
    """Durable committed-version store; file-backed when path given."""

    def __init__(self, path: Optional[str] = None, keep_versions: int = 500):
        self.path = path
        self.keep_versions = keep_versions
        self.committed: Dict[int, bytes] = {}
        self.last_committed = 0
        self.first_committed = 0
        self.meta: Dict[str, Any] = {}  # election epoch, monmap, ...
        if path and os.path.exists(path):
            self._load()

    def _load(self) -> None:
        with open(self.path, "rb") as f:
            blob = pickle.load(f)
        self.committed = blob["committed"]
        self.last_committed = blob["last_committed"]
        self.first_committed = blob["first_committed"]
        self.meta = blob.get("meta", {})

    def _persist(self) -> None:
        if not self.path:
            return
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(self.path) or ".")
        try:
            with os.fdopen(fd, "wb") as f:
                pickle.dump(
                    {
                        "committed": self.committed,
                        "last_committed": self.last_committed,
                        "first_committed": self.first_committed,
                        "meta": self.meta,
                    },
                    f,
                    protocol=5,
                )
            os.replace(tmp, self.path)  # atomic: torn writes can't corrupt
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def commit(self, version: int, value: bytes) -> None:
        if version <= self.last_committed:
            return
        self.committed[version] = value
        self.last_committed = version
        if not self.first_committed:
            self.first_committed = version
        # trim old versions (reference paxos_trim)
        while self.last_committed - self.first_committed >= self.keep_versions:
            self.committed.pop(self.first_committed, None)
            self.first_committed += 1
        self._persist()

    def set_meta(self, key: str, value: Any) -> None:
        self.meta[key] = value
        self._persist()

    def get(self, version: int) -> Optional[bytes]:
        return self.committed.get(version)

    def latest(self) -> Tuple[int, Optional[bytes]]:
        return self.last_committed, self.committed.get(self.last_committed)


class ElectionLogic:
    """Rank-based election state; the Monitor wires sends/timeouts."""

    # scores are QUANTIZED into buckets before comparison (tie -> lowest
    # rank): a mon must be meaningfully better connected to displace a
    # lower rank, or jittery measurements would flap leadership.
    # Quantization (unlike a pairwise margin) keeps the ordering
    # TRANSITIVE — pairwise margins let a chain of within-margin wins
    # hand victory to the worst-connected candidate.
    SCORE_BUCKET = 0.2

    def __init__(self, rank: int, n_mons: int):
        self.rank = rank
        self.n_mons = n_mons
        self.epoch = 1
        self.electing = False
        self.acked_by: Set[int] = set()
        self.leader: Optional[int] = None
        self.quorum: Set[int] = set()
        # this mon's own connectivity score (mean peer-reachability EMA,
        # reference ConnectionTracker); the Monitor refreshes it before
        # each election round
        self.score = 1.0

    @classmethod
    def _bucket(cls, score: float) -> int:
        return int(round(score / cls.SCORE_BUCKET))

    def _beats(self, their_score: float, their_rank: int) -> bool:
        """Does the remote candidate beat US (we should ack them)?"""
        if their_score >= 0:
            theirs, ours = self._bucket(their_score), self._bucket(self.score)
            if theirs != ours:
                return theirs > ours
        return their_rank < self.rank

    @property
    def majority(self) -> int:
        return self.n_mons // 2 + 1

    def start(self) -> int:
        """Begin (or restart) an election; returns the new election epoch."""
        self.electing = True
        self.leader = None
        self.quorum = set()
        self.acked_by = {self.rank}
        if self.epoch % 2 == 0:
            self.epoch += 1  # odd epoch = election in progress (reference)
        else:
            self.epoch += 2
        return self.epoch

    def receive_propose(self, from_rank: int, epoch: int,
                        from_score: float = -1.0) -> str:
        """Any propose pulls us into the election (reference: an election
        message bumps everyone into electing).  Returns 'ack' (defer to a
        better candidate), 'ignore', or 'counter' (we are the better
        candidate: propose ourselves).  "Better" is connectivity score
        first (a well-connected mon routes around partial network
        failure), rank as the tiebreak — the reference's CONNECTIVITY
        election strategy (ElectionLogic.cc, ConnectionTracker.h:80)."""
        if epoch > self.epoch:
            self.epoch = epoch
        if from_rank == self.rank:
            return "ignore"
        # entering election: any standing quorum/leadership is suspended
        # until a victory re-establishes it (so a rejoining mon can win a
        # seat even when a stable quorum existed)
        self.electing = True
        self.leader = None
        self.quorum = set()
        if self._beats(from_score, from_rank):
            return "ack"
        return "counter"

    def receive_ack(self, from_rank: int, epoch: int) -> bool:
        """Returns True when this ack completes a majority.  An ack carrying
        a NEWER epoch teaches a restarted candidate the cluster's epoch (its
        next proposal round uses it)."""
        if epoch > self.epoch:
            self.epoch = epoch
            return False
        if not self.electing or epoch != self.epoch:
            return False
        self.acked_by.add(from_rank)
        return len(self.acked_by) >= self.majority

    def declare_victory(self) -> Tuple[int, Set[int]]:
        self.electing = False
        self.leader = self.rank
        self.quorum = set(self.acked_by)
        if self.epoch % 2 == 1:
            self.epoch += 1  # even epoch = stable quorum
        return self.epoch, self.quorum

    def receive_victory(self, from_rank: int, epoch: int,
                        quorum: Set[int]) -> bool:
        if epoch < self.epoch:
            return False
        self.epoch = epoch
        self.electing = False
        self.leader = from_rank
        self.quorum = set(quorum)
        return True

    @property
    def is_leader(self) -> bool:
        return self.leader == self.rank and not self.electing

    @property
    def in_quorum(self) -> bool:
        return self.leader is not None and self.rank in self.quorum


class Paxos:
    """Leader-driven single-log Paxos over an injected transport.

    The Monitor provides ``send(rank, payload_dict)``; payloads come back
    through the ``handle_*`` methods.  Proposals are serialized: one
    in-flight proposal at a time (the reference's is_updating gate).
    """

    def __init__(self, store: MonitorDBStore, rank: int,
                 send: Callable[[int, Dict[str, Any]], Any]):
        self.store = store
        self.rank = rank
        self.send = send
        self.on_commit: Optional[Callable[[int, bytes], None]] = None
        # leader proposal state
        self.proposing: Optional[Tuple[int, bytes]] = None
        self.accepts: Set[int] = set()
        self.quorum: Set[int] = set()
        # pending (uncommitted) value seen by a peon
        self.pending: Optional[Tuple[int, bytes]] = None
        # epoch fencing (the reference's proposal-number machinery,
        # Paxos.h accepted_pn/last_pn): peons promise the election epoch
        # at collect/victory and reject begin/commit from lower epochs, so
        # a deposed leader that still believes it leads cannot commit a
        # divergent value against the same peons
        self.epoch = 0  # leader: the epoch current proposals carry
        self.promised_epoch = 0  # peon: floor for begin/commit acceptance
        self.nacked = False  # leader: a peer refused our epoch

    # -- collect phase (leader, after election) ------------------------------

    def collect_state(self) -> Dict[str, Any]:
        v, val = self.store.latest()
        return {"op": "last", "version": v, "value": val,
                "pending": self.pending}

    def absorb_last(self, last: Dict[str, Any]) -> None:
        """Leader folds a peon's state into its own (newest version wins;
        an uncommitted pending from a dead leader's round is re-committed —
        the reference's uncommitted-value recovery)."""
        v, val = last.get("version", 0), last.get("value")
        if v > self.store.last_committed and val is not None:
            self.store.commit(v, val)
            if self.on_commit:
                self.on_commit(v, val)
        pend = last.get("pending")
        if pend is not None:
            pv, pval = pend
            if pv == self.store.last_committed + 1:
                self.store.commit(pv, pval)
                if self.on_commit:
                    self.on_commit(pv, pval)

    # -- proposals (leader) --------------------------------------------------

    async def propose(self, value: bytes, quorum: Set[int],
                      epoch: Optional[int] = None) -> int:
        """Replicate one value; returns the committed version.  The caller
        (Monitor) awaits acceptance via handle_accept -> _check_commit."""
        assert self.proposing is None, "one in-flight proposal at a time"
        if epoch is not None:
            self.epoch = epoch
        self.nacked = False
        version = self.store.last_committed + 1
        self.proposing = (version, value)
        self.accepts = {self.rank}
        self.quorum = set(quorum)
        for peer in quorum:
            if peer != self.rank:
                await self.send(peer, {"op": "begin", "version": version,
                                       "value": value, "epoch": self.epoch})
        return version

    def handle_accept(self, from_rank: int, version: int,
                      epoch: Optional[int] = None) -> bool:
        """Returns True when the proposal just reached majority."""
        if self.proposing is None or self.proposing[0] != version:
            return False
        if epoch is not None and epoch != self.epoch:
            return False  # accept for some other leadership's round
        self.accepts.add(from_rank)
        need = len(self.quorum) // 2 + 1
        return len(self.accepts) >= need

    def handle_nack(self, epoch: int) -> bool:
        """A peer promised a newer epoch: we are deposed.  Abandon the
        in-flight proposal (the reference leader bootstraps on seeing a
        higher pn).  A nack at or below our CURRENT proposal epoch is a
        stale packet from an older round — a single delayed frame must not
        tear down a healthy re-elected leadership — and is ignored.  The
        floor includes promised_epoch: a leadership we already promised
        (e.g. the election we just won, before the first propose() stamps
        self.epoch) is not news and must not depose us either.
        Returns True when the nack actually deposed us."""
        if epoch <= max(self.epoch, self.promised_epoch):
            return False
        self.nacked = True
        self.promised_epoch = max(self.promised_epoch, epoch)
        self.proposing = None
        return True

    async def commit_current(self) -> Tuple[int, bytes]:
        version, value = self.proposing  # type: ignore[misc]
        self.proposing = None
        self.store.commit(version, value)
        if self.on_commit:
            self.on_commit(version, value)
        for peer in self.quorum:
            if peer != self.rank:
                await self.send(peer, {"op": "commit", "version": version,
                                       "value": value, "epoch": self.epoch})
        return version, value

    # -- peon side -----------------------------------------------------------

    def promise(self, epoch: int) -> bool:
        """Record the election epoch at collect/victory time; returns False
        for a stale (lower-epoch) overture."""
        if epoch < self.promised_epoch:
            return False
        self.promised_epoch = epoch
        # Promising a NEWER leadership while our own proposal is in flight
        # means we were deposed mid-round: abandon it, or the commit we
        # send after gathering the remaining accepts would carry the new
        # leader's epoch and land on its peons as a divergent value.
        if self.proposing is not None and epoch > self.epoch:
            self.proposing = None
            self.nacked = True
        return True

    async def handle_begin(self, from_rank: int, version: int,
                           value: bytes, epoch: Optional[int] = None) -> None:
        if epoch is not None and epoch < self.promised_epoch:
            # stale leader (healed partition / lost lease): refuse, teach
            await self.send(from_rank, {"op": "nack", "version": version,
                                        "epoch": self.promised_epoch})
            return
        if epoch is not None:
            # route through promise(): a begin from a NEWER leadership must
            # also abandon any proposal WE have in flight (collect/victory
            # frames can be lost; the begin may be the first we hear of it)
            self.promise(epoch)
        self.pending = (version, value)
        await self.send(from_rank, {"op": "accept", "version": version,
                                    "epoch": epoch if epoch is not None
                                    else self.promised_epoch})

    def handle_commit(self, version: int, value: bytes,
                      epoch: Optional[int] = None) -> None:
        if epoch is not None and epoch < self.promised_epoch:
            return  # a deposed leader's commit must not land
        if epoch is not None:
            self.promise(epoch)  # same deposition semantics as handle_begin
        if self.pending and self.pending[0] == version:
            self.pending = None
        if version > self.store.last_committed:
            self.store.commit(version, value)
            if self.on_commit:
                self.on_commit(version, value)
