"""PG log: the per-PG replicated operation log driving delta recovery.

Role-equivalent of the reference's PGLog (reference src/osd/PGLog.{h,cc}):
every PG mutation appends a log entry (version, object, op, prior_version,
reqid) on EVERY acting shard, atomically with the object write.  The log
is the source of three guarantees:

- **dup detection**: a client resend (same reqid) is recognized and not
  re-applied (reference pg log dup entries; our mon does the same for its
  own writes);
- **delta recovery**: after an interval change, peers diff logs — entries
  the authoritative log has past a peer's last_update become that peer's
  *missing set*, and only those objects move (PGLog::merge_log /
  calc_missing); a peer whose last_update predates the log tail cannot be
  caught up by log replay and falls back to BACKFILL (full scan);
- **divergence handling**: a shard holding entries NEWER than the
  authoritative head (it accepted writes the failed primary never
  committed cluster-wide) rolls them back (reference rollback machinery,
  ECBackend::rollback_append).

Versions are (epoch, seq) pairs ordered lexicographically, the reference's
eversion_t.  Persistence: entries ride the object store's omap under a
per-PG meta object, written in the SAME transaction as the shard data.
"""

from __future__ import annotations

import pickle
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

Version = Tuple[int, int]  # (epoch, seq) — eversion_t role

ZERO: Version = (0, 0)


def pack_eversion(v: Version) -> int:
    """eversion -> one epoch-major ordered int, the version stamped on
    shard metadata.  Shard 'newest' resolution (reads, recovery, backfill)
    thereby follows PG-log order, never wall clocks: a failover primary on
    a slow clock still outranks pre-failover writes because its epoch is
    higher (the reference orders by eversion_t everywhere, e.g.
    src/osd/osd_types.h eversion_t)."""
    return (v[0] << 32) | (v[1] & 0xFFFFFFFF)


@dataclass
class LogEntry:
    version: Version
    op: str  # "write" | "delete"
    oid: str
    prior_version: Version = ZERO
    reqid: str = ""
    object_version: int = 0  # the data version stamped on the shards

    def encode(self) -> bytes:
        return pickle.dumps(self.__dict__, protocol=5)

    @classmethod
    def decode(cls, blob: bytes) -> "LogEntry":
        e = cls.__new__(cls)
        e.__dict__.update(pickle.loads(blob))
        return e


@dataclass
class PGLog:
    """In-memory log window [tail, head] plus a reqid dup set."""

    entries: List[LogEntry] = field(default_factory=list)
    tail: Version = ZERO  # everything <= tail has been trimmed
    max_entries: int = 500  # osd_min_pg_log_entries role
    _dups: Dict[str, Version] = field(default_factory=dict)

    @property
    def head(self) -> Version:
        return self.entries[-1].version if self.entries else self.tail

    def next_version(self, epoch: int) -> Version:
        h = self.head
        return (epoch, h[1] + 1)

    def append(self, entry: LogEntry) -> List[str]:
        """Append; returns omap keys of trimmed entries (caller removes
        them in its transaction — reference pg log trim)."""
        assert entry.version > self.head, (entry.version, self.head)
        self.entries.append(entry)
        if entry.reqid:
            self._dups[entry.reqid] = entry.version
        return self._trim()

    def _trim(self) -> List[str]:
        trimmed: List[str] = []
        while len(self.entries) > self.max_entries:
            dropped = self.entries.pop(0)
            self.tail = dropped.version
            trimmed.append(self._okey(dropped.version))
        while len(self._dups) > 4 * self.max_entries:
            self._dups.pop(next(iter(self._dups)))
        return trimmed

    def has_reqid(self, reqid: str) -> bool:
        return bool(reqid) and reqid in self._dups

    def latest_entry(self, oid: str) -> Optional[LogEntry]:
        """The newest log entry touching `oid` within the window, or None
        when the object has no entry here (trimmed away, or never
        written) — callers must then fall back to shard queries.  This is
        the primary's authoritative per-object version source (reference
        pg_log_t objects index role)."""
        for e in reversed(self.entries):
            if e.oid == oid:
                return e
        return None

    def entries_after(self, version: Version) -> Optional[List[LogEntry]]:
        """Entries with version > `version`, or None if `version` predates
        the tail (log can't catch that peer up -> backfill)."""
        if version < self.tail:
            return None
        return [e for e in self.entries if e.version > version]

    # -- recovery computation ------------------------------------------------

    def calc_missing(self, since: Version) -> Optional[Dict[str, LogEntry]]:
        """Objects a peer at `since` is missing: latest entry per oid among
        entries after `since` (None -> backfill needed)."""
        delta = self.entries_after(since)
        if delta is None:
            return None
        missing: Dict[str, LogEntry] = {}
        for e in delta:
            missing[e.oid] = e
        return missing

    def divergent_against(self, auth_head: Version) -> List[LogEntry]:
        """Our entries newer than the authoritative head: to roll back."""
        return [e for e in self.entries if e.version > auth_head]

    def rewind_to(self, version: Version) -> None:
        """Drop entries newer than `version` (after their effects were
        rolled back)."""
        self.entries = [e for e in self.entries if e.version <= version]

    # -- persistence ---------------------------------------------------------

    OMAP_PREFIX = "log."

    @staticmethod
    def _okey(version: Version) -> str:
        return f"{PGLog.OMAP_PREFIX}{version[0]:012d}.{version[1]:012d}"

    def omap_entries(self, entry: LogEntry) -> Dict[str, bytes]:
        """The omap mutation persisting one append (goes into the same
        store transaction as the shard write)."""
        return {self._okey(entry.version): entry.encode(),
                "info": pickle.dumps({"tail": self.tail}, protocol=5)}

    @classmethod
    def load(cls, omap: Dict[str, bytes], max_entries: int = 500) -> "PGLog":
        log = cls(max_entries=max_entries)
        info = omap.get("info")
        if info is not None:
            log.tail = tuple(pickle.loads(info).get("tail", ZERO))
        entries = sorted(
            (k, v) for k, v in omap.items() if k.startswith(cls.OMAP_PREFIX)
        )
        for _, blob in entries:
            e = LogEntry.decode(blob)
            e.version = tuple(e.version)
            e.prior_version = tuple(e.prior_version)
            if e.version > log.tail:
                log.entries.append(e)
                if e.reqid:
                    log._dups[e.reqid] = e.version
        return log

