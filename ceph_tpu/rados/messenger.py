"""Async messenger: typed messages over length-prefixed TCP frames.

Role-equivalent of the reference's AsyncMessenger + ProtocolV2 stack
(reference src/msg/async/AsyncMessenger.h:73, ProtocolV2.cc): every daemon
creates one Messenger, registers a Dispatcher, and exchanges versioned typed
messages over ordered per-peer Connections; a config-driven fault injector
(ms_inject_socket_failures, reference src/common/options/global.yaml.in:1240)
can sever connections to exercise retry/recovery paths without code changes.

Transport is asyncio TCP on loopback (the standalone-test topology the
reference uses, qa/standalone/ceph-helpers.sh); frames are
[u32 length][u16 type][u32 version][payload].  Payloads are pickled dataclass
fields — an internal trusted-cluster format; the reference's cross-version
dencoder discipline is represented by the per-type version field checked on
decode.
"""

from __future__ import annotations

import asyncio
import pickle
import random
import struct
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Tuple

_HDR = struct.Struct("<IHI")

# -- message registry --------------------------------------------------------

_MSG_TYPES: Dict[int, type] = {}
_MSG_IDS: Dict[type, int] = {}


def message(type_id: int, version: int = 1):
    """Register a message dataclass with a wire type id + version."""

    def deco(cls):
        cls = dataclass(cls)
        cls.TYPE_ID = type_id
        cls.VERSION = version
        _MSG_TYPES[type_id] = cls
        _MSG_IDS[cls] = type_id
        return cls

    return deco


def encode_message(msg: Any) -> bytes:
    payload = pickle.dumps(msg.__dict__, protocol=5)
    return _HDR.pack(len(payload), msg.TYPE_ID, msg.VERSION) + payload


def decode_message(type_id: int, version: int, payload: bytes) -> Any:
    cls = _MSG_TYPES.get(type_id)
    if cls is None:
        raise ValueError(f"unknown message type {type_id}")
    if version > cls.VERSION:
        raise ValueError(
            f"{cls.__name__} wire version {version} > supported {cls.VERSION}"
        )
    obj = cls.__new__(cls)
    obj.__dict__.update(pickle.loads(payload))
    return obj


# -- connection / messenger --------------------------------------------------


class Connection:
    def __init__(self, messenger: "Messenger", reader, writer, peer: Tuple[str, int]):
        self.messenger = messenger
        self.reader = reader
        self.writer = writer
        self.peer = peer
        self.closed = False
        self._send_lock = asyncio.Lock()

    async def send(self, msg: Any) -> None:
        inj = self.messenger.conf.get("ms_inject_socket_failures", 0)
        if inj and random.randrange(inj) == 0:
            await self.close()
            raise ConnectionResetError("injected socket failure")
        delay = self.messenger.conf.get("ms_inject_delay_max", 0)
        if delay:
            await asyncio.sleep(random.uniform(0, delay))
        data = encode_message(msg)
        async with self._send_lock:
            if self.closed:
                raise ConnectionResetError("connection closed")
            self.writer.write(data)
            await self.writer.drain()

    async def read_message(self) -> Any:
        hdr = await self.reader.readexactly(_HDR.size)
        length, type_id, version = _HDR.unpack(hdr)
        payload = await self.reader.readexactly(length)
        return decode_message(type_id, version, payload)

    async def close(self) -> None:
        if not self.closed:
            self.closed = True
            self.writer.close()
            try:
                # bounded: wait_closed can block if the peer never reads
                await asyncio.wait_for(self.writer.wait_closed(), timeout=0.5)
            except Exception:
                pass


class Messenger:
    """One per daemon.  dispatcher(conn, msg) is awaited per message
    (fast-dispatch style: no intermediate queue)."""

    def __init__(self, name: str, conf: Optional[dict] = None):
        self.name = name
        self.conf = conf or {}
        self.dispatcher: Optional[Callable] = None
        self.server: Optional[asyncio.AbstractServer] = None
        self.addr: Optional[Tuple[str, int]] = None
        self._conns: Dict[Tuple[str, int], Connection] = {}
        self._tasks: set = set()

    async def bind(self, host: str = "127.0.0.1", port: int = 0) -> Tuple[str, int]:
        self.server = await asyncio.start_server(self._accept, host, port)
        self.addr = self.server.sockets[0].getsockname()[:2]
        return self.addr

    async def _accept(self, reader, writer) -> None:
        peer = writer.get_extra_info("peername")[:2]
        conn = Connection(self, reader, writer, peer)
        task = asyncio.current_task()
        self._tasks.add(task)
        try:
            await self._serve(conn)
        finally:
            self._tasks.discard(task)

    async def _serve(self, conn: Connection) -> None:
        try:
            while not conn.closed:
                msg = await conn.read_message()
                if self.dispatcher is not None:
                    await self.dispatcher(conn, msg)
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            await conn.close()

    async def connect(self, addr: Tuple[str, int]) -> Connection:
        """Get (or create) an ordered connection to a peer; a cached dead
        connection is replaced (lossless_peer reconnect semantics)."""
        addr = tuple(addr)
        conn = self._conns.get(addr)
        if conn is not None and not conn.closed:
            return conn
        reader, writer = await asyncio.open_connection(*addr)
        conn = Connection(self, reader, writer, addr)
        self._conns[addr] = conn
        # serve replies arriving on the outbound connection too
        task = asyncio.get_running_loop().create_task(self._serve(conn))
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)
        return conn

    async def send(self, addr: Tuple[str, int], msg: Any, retries: int = 3) -> None:
        last: Optional[Exception] = None
        for _ in range(retries + 1):
            try:
                conn = await self.connect(addr)
                await conn.send(msg)
                return
            except (ConnectionError, OSError) as e:
                last = e
                self._conns.pop(tuple(addr), None)
        raise last  # type: ignore[misc]

    async def shutdown(self) -> None:
        # cancel serve loops FIRST: in py3.12 Server.wait_closed() waits for
        # all connection handlers, so live inbound loops would deadlock it
        for t in list(self._tasks):
            t.cancel()
        for conn in list(self._conns.values()):
            await conn.close()
        if self.server is not None:
            self.server.close()
            try:
                await asyncio.wait_for(self.server.wait_closed(), timeout=1.0)
            except asyncio.TimeoutError:
                pass
