"""Async messenger: typed messages over an authenticated, crc-guarded,
replay-safe framed TCP protocol.

Role-equivalent of the reference's AsyncMessenger + ProtocolV2 stack
(reference src/msg/async/AsyncMessenger.h:73, ProtocolV2.cc, frames_v2.cc):
every daemon creates one Messenger, registers a Dispatcher, and exchanges
versioned typed messages over ordered per-peer Connections.  The v2-style
connection bring-up is banner -> hello (peer name/type, nonce, session
cookie, requested policy, optional HMAC auth over a shared secret — the
cephx role, src/auth/) -> session.  Data frames carry a crc32 (ms_crc_data
mode) and an optional zlib-compressed payload (compression_onwire.cc role,
ms_compress_min_size).

Policies mirror the reference's (Policy::lossy_client vs lossless_peer),
negotiated at handshake: on a lossless session BOTH sides keep one
long-lived Connection object per peer session — frames are sequenced,
acked, and kept queued until acked; after a transport drop the initiator
reconnects and each side replays its un-acked frames onto the new transport
(the server adopts the new socket into the existing session Connection, the
reference's session-reconnect + out_queue replay, ProtocolV2.cc
reuse_connection) — with receiver-side seq dedupe making dispatch
exactly-once in both directions, the OSD<->OSD guarantee PG consistency is
built on.  Lossy connections just fail and are replaced wholesale.

A config-driven fault injector (reference
src/common/options/global.yaml.in:1240) exercises the failure paths
without code changes: ms_inject_socket_failures severs connections,
ms_inject_delay_max delays sends, and ms_inject_dup_frames delivers
client-op-plane messages twice (two frames, two seqs — duplicates the
receiver's seq dedupe CANNOT filter, proving the application layer's
reqid/pop-once dedup instead).  A dispatch throttle
(ms_dispatch_throttle_bytes) applies receive-side backpressure.

Wire formats, by plane (see README "Wire-format threat model"):
- DATA plane (MOSDOp/MOSDOpReply/ECSub*/MPushShard): fixed binary field
  layouts (FLAG_FIXED; FIXED_FIELDS declared in types.py) — struct-speed
  and incapable of executing code on decode, like the reference's
  fixed-layout dencoder structs.  Bulk bytes ride the zero-copy blob
  lane with their own crc32c.
- CONTROL plane (maps, peering, paxos, config): pickled dataclass
  fields — an internal trusted-cluster format behind cephx-lite auth.
- COLOCATED daemons (ms_local_fastpath): no serialization at all —
  typed messages hand over by reference (Messenger local_connection
  role).
The reference's cross-version dencoder discipline is represented by the
per-type version field checked on decode (and exercised by
tools/dencoder + the wire corpus).

Cork/flush discipline (the corked wire data plane): every Connection owns
an OUTBOX.  ``send()`` frames the message and appends the segments to the
outbox; a single per-connection flusher task drains the outbox with ONE
``writelines`` + ONE ``drain()`` per flush window, so frames queued by
concurrent senders (a k+m stripe fan-out, a burst of sub-write replies)
coalesce into one scatter-gather write instead of paying a
lock/write/drain round-trip each (the reference's ProtocolV2 out_queue +
segment writev).  The flush window is self-clocking: while one window
drains, new frames pile into the next — no added latency for an isolated
send, automatic batching under load.  On plaintext TCP the flusher also
swaps the StreamWriter for a CorkedWriter that ``sendmsg``-writevs the
frame segments STRAIGHT FROM their owning buffers (encode outputs, store
blobs, BufferList pieces) — zero copies between codec and kernel.

Acks are PIGGYBACKED: dispatching a frame queues a cumulative ack
(highest contiguous seq) on the connection instead of writing a
standalone ACK_TYPE frame; the next flush carries one ack frame for the
whole window (acks are cumulative, so the latest seq covers every
earlier one).  An ack-only flush is still written promptly when no data
frames are outbound.  The rx side mirrors the batching: the serve loop
drains every frame ALREADY BUFFERED on the transport into one batch,
dispatches the batch (through ``group_dispatcher`` when the daemon
installs one — the whole-stripe group handoff seam), and acks once.

Lossless-replay interaction: a frame enters the unacked replay queue
BEFORE it enters the outbox, and close() fails the pending flush window
and clears the outbox — un-flushed frames replay from the unacked queue
onto the adopted transport in seq order, and the receiver's dedupe floor
makes any flush/replay overlap exactly-once.

Sharded multi-reactor wire plane (reactor.py + the lane layer here):

- **Reactor pool** (``ms_async_op_threads``): N reactor workers, each a
  thread with its own event loop owning a shard of sockets (reference
  AsyncMessenger worker pool).  Outbound data lanes are bound to workers
  by a stable hash of (peer, lane); inbound sockets shard across the
  workers' dup'd listening fds.  Socket work (framing, crc, sendmsg,
  recv memcpy — all GIL-releasing) runs on the owning reactor; dispatch
  hops back to the daemon's home loop, so daemon state stays
  single-loop.  Each reactor-owned connection charges a per-worker
  dispatch throttle (receive backpressure is per shard).
- **Multi-lane peer striping** (``ms_lanes_per_peer`` > 1, negotiated —
  an old peer that doesn't advertise ``lanes_ok`` gets one lane): a peer
  pair opens N parallel lanes, each a full Connection (own cork/outbox,
  own seq space, own unacked replay queue, own flusher).  Lane 0 is the
  CONTROL lane — pings, acks, maps, backoffs, health are never queued
  behind data.  Data-plane messages (LANE_STRIPE types) are striped
  round-robin across lanes 1..N-1, stamped with a connection-global
  ``gseq``; the receiving LaneGroup reassembles gseq order before
  dispatch, so per-(peer,type) ordering (in fact total data-plane
  order) and the reqid/dedup machinery above are preserved.  Messages
  with blobs >= ``ms_lane_stripe_min`` are FRAGMENTED: the blob splits
  into per-lane MLaneSegment frames sent concurrently and reassembled
  into one buffer on the receiver — one large transfer rides all lanes
  at once.  A dead lane pins and replays only ITS unacked frames
  (per-lane sessions); the remaining lanes keep draining, and the gseq
  reorder buffer absorbs the replayed hole.
- **Colocated ring transport** (``ms_colocated_ring``): the handshake
  hello carries a per-process token; when both ends share the process
  (vstart/test topology, bench loopback arm) the acceptor offers an
  in-process RingPipe pair in its fin and both sides swap the TCP
  session for a zero-serialization ring (BufferList views hand over by
  reference).  Any negotiation failure falls back to TCP transparently.
"""

from __future__ import annotations

import asyncio
import collections
import hashlib
import hmac
import itertools
import json
import os
import pickle
import random
import socket as socket_mod
import struct
import threading
import time
import traceback
import zlib
from dataclasses import dataclass, field
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

import numpy as np

from ceph_tpu.common.perf_counters import PerfCounters, PerfCountersBuilder
from ceph_tpu.common.throttle import Throttle
from ceph_tpu.utils import wirepath as _wirepath
from ceph_tpu.rados.reactor import (PROC_TOKEN, ReactorPool, RingConnection,
                                    ring_abandon, ring_claim, ring_offer)
from ceph_tpu.rados.reactor_proc import ShmConnEndpoint, delegate_socket
from ceph_tpu.rados.shm_ring import (FRAME_HDR as _SHM_FRAME_HDR,
                                     REC_EOF as _SHM_REC_EOF,
                                     REC_ERR as _SHM_REC_ERR,
                                     REC_FRAME as _SHM_REC_FRAME,
                                     RF_BLOB as _SHM_RF_BLOB,
                                     RF_FIXED as _SHM_RF_FIXED,
                                     RF_VERIFIED as _SHM_RF_VERIFIED)


def _build_wire_perf() -> PerfCounters:
    """The `wire` counter set — one per Messenger, added to the owning
    daemon's PerfCountersCollection so `perf dump` and the mgr prometheus
    exporter carry the wire-path breakdown the ROADMAP names as the
    reason the device-tier win is invisible over TCP.  COUNTER SCHEMA
    (name -> meaning -> kind):

      tx_msgs / rx_msgs    u64         messages sent / dispatched
      tx_bytes / rx_bytes  u64         frame bytes written / received on
                                       the socket (tx side counts EVERY
                                       write: messages, acks, session
                                       replays)
      tx_framing           longrunavg  encode + frame-build seconds per send
      tx_io                longrunavg  socket write + drain seconds per
                                       write (messages, acks, replays)
      rx_io                longrunavg  payload read seconds per frame
                                       (clock starts AFTER the header
                                       lands, so idle wait between
                                       messages never pollutes it)
      rx_framing           longrunavg  decode_message seconds per dispatch
      local_msgs           u64         colocated-fastpath handoffs (no
                                       framing or socket at all)
      tx_flushes           u64         outbox flush windows written (each is
                                       one writelines + one drain)
      tx_flush_frames      histogram   frames coalesced per flush window
      tx_flush_bytes       histogram   bytes per flush window
      tx_flush_data        u64         windows cut carrying data frames
      tx_flush_ack         u64         ack-only windows (no data pending)
      tx_acks              u64         ack frames written
      tx_acks_coalesced    u64         acks absorbed into a pending ack
                                       (would have been standalone frames)
      tx_crc_reused        u64         blob frames whose wire crc reused an
                                       app-level crc (no recompute pass)
      rx_batches           u64         multi-frame rx batches drained
      rx_batch_msgs        histogram   messages per rx dispatch batch
      wirepath_kind        u64 gauge   1 = native wirepath, 0 = python arm
      native_tx_calls      u64         released-GIL tx wirepath calls
      native_rx_calls      u64         released-GIL rx wirepath calls
      native_bytes         u64         bytes touched by native wirepath
                                       passes (counted once per pass)
      tx_<Type> / rx_<Type>        u64  per-message-type counts (dynamic)
      tx_bytes_<Type> / rx_bytes_<Type>  u64  per-type frame bytes

    framing vs io is the actionable split: framing seconds are Python
    encode cost a scatter-gather/zero-copy PR can remove; io seconds are
    the socket's.  With the corked outbox, tx_io is per FLUSH WINDOW (not
    per message): sum(tx_io)/tx_msgs is the per-message socket cost and
    drops as flush windows batch more frames."""
    b = PerfCountersBuilder("wire")
    b.add_u64_counter("tx_msgs", "messages sent")
    b.add_u64_counter("tx_bytes", "frame bytes sent")
    b.add_u64_counter("rx_msgs", "messages dispatched")
    b.add_u64_counter("rx_bytes", "frame bytes received")
    b.add_time_avg("tx_framing", "encode + frame-build seconds per send")
    b.add_time_avg("tx_io", "socket write + drain seconds per flush window")
    b.add_time_avg("rx_io", "payload read seconds per frame (post-header)")
    b.add_time_avg("rx_framing", "decode seconds per dispatched message")
    b.add_u64_counter("local_msgs", "colocated-fastpath handoffs")
    b.add_u64_counter("tx_flushes", "outbox flush windows written")
    b.add_histogram("tx_flush_frames", "frames coalesced per flush window")
    b.add_histogram("tx_flush_bytes", "bytes per flush window")
    b.add_u64_counter("tx_flush_data", "flush windows carrying data frames")
    b.add_u64_counter("tx_flush_ack", "ack-only flush windows")
    b.add_u64_counter("tx_acks", "ack frames written")
    b.add_u64_counter("tx_acks_coalesced",
                      "acks absorbed into a pending cumulative ack")
    b.add_u64_counter("tx_crc_reused",
                      "blob frames reusing an app-level crc on the wire")
    b.add_u64_counter("rx_batches", "multi-frame rx dispatch batches")
    b.add_histogram("rx_batch_msgs", "messages per rx dispatch batch")
    # multi-lane / reactor / ring plane (module docstring "Sharded
    # multi-reactor wire plane"); per-lane splits ride dynamic
    # tx_lane<k>_msgs / tx_lane<k>_bytes counters
    b.add_u64_counter("ring_msgs", "colocated ring handoffs (no framing, "
                                   "no socket, no serialization)")
    b.add_u64_counter("lane_rx_parked",
                      "striped frames parked awaiting a gseq gap")
    b.add_u64_counter("lane_frag_tx", "lane fragments sent (large blobs "
                                      "split across data lanes)")
    b.add_u64_counter("lane_frag_rx", "lane fragments reassembled")
    b.add_u64_counter("lane_frag_overflow",
                      "fragments refused by the reassembly memory cap")
    b.add_u64_counter("lane_revivals", "dead lanes redialed and replayed")
    # native wirepath (utils/wirepath.py): which arm ran and how much of
    # the per-byte hot loop it carried — wirepath_kind is the arm gauge
    # (1 = native, 0 = python; BENCH records the string alongside)
    b.add_u64("wirepath_kind", "wirepath arm: 1 = native, 0 = python")
    b.add_u64_counter("native_tx_calls",
                      "released-GIL wirepath calls on the tx side "
                      "(whole-window writev, batch blob crc)")
    b.add_u64_counter("native_rx_calls",
                      "released-GIL wirepath calls on the rx side "
                      "(burst crc verify, fused copy+crc, scatter)")
    b.add_u64_counter("native_bytes",
                      "bytes touched by native wirepath passes (each "
                      "pass counts: a byte crc-verified then scattered "
                      "counts once per pass)")
    # µs histograms of the socket-io longrunavgs: tail-latency
    # percentiles (p50/p99/p999) come out of the power-of-2 buckets, so
    # the BENCH record reports wire tx/rx TAILS, not just means
    b.add_histogram("tx_io_us", "socket write+drain µs per flush window")
    b.add_histogram("rx_io_us", "payload read µs per frame")
    # process-sharded reactor plane (ms_reactor_mode=process): the
    # byte-loop counters now live in the WORKER PROCESSES' counter
    # blocks; these proc_* aggregates are refreshed from shared memory
    # at dump time (perf.presample) so `perf dump`, /metrics and BENCH
    # see the whole plane, not just the parent's share.  Values are
    # ABSOLUTE since worker spawn (a perf reset does not zero a worker).
    b.add_u64("proc_workers", "live reactor worker processes")
    b.add_u64("proc_delegated_conns",
              "connections delegated to worker processes (absolute)")
    b.add_u64("proc_rx_frames",
              "frames parsed+verified in worker processes (absolute)")
    b.add_u64("proc_rx_bytes", "frame bytes received by workers (absolute)")
    b.add_u64("proc_tx_calls", "socket write passes by workers (absolute)")
    b.add_u64("proc_tx_bytes", "bytes written by workers (absolute)")
    b.add_u64("proc_native_rx_calls",
              "released-GIL rx wirepath calls in workers (absolute)")
    b.add_u64("proc_native_tx_calls",
              "released-GIL tx wirepath calls in workers (absolute)")
    b.add_u64("proc_native_bytes",
              "bytes touched by worker wirepath passes (absolute)")
    b.add_u64("proc_worker_respawns",
              "worker processes respawned after death (absolute)")
    return b.create_perf_counters()

BANNER = b"ceph_tpu msgr v2\n"
_HDR = struct.Struct("<IHHBIQ")  # len, type, version, flags, crc, seq

# blob-frame payload prefix: pickled length + blob checksum
_BLOB_PFX = struct.Struct("<II")

FLAG_COMPRESSED = 1
# FLAG_FIXED: the payload (or the header part of a blob frame) is the
# class's FIXED_FIELDS binary layout, not pickle — the data-plane
# framing discipline (reference fixed-layout dencoder encode for
# MOSDOp/ECSubWrite wire structs, src/osd/ECMsgTypes.h encode_payload):
# nothing on the hot path can execute code on decode, and field packing
# is struct-speed.  Control-plane types keep pickle (internal
# trusted-cluster format; see module docstring).
FLAG_FIXED = 4
# FLAG_BLOB: payload = [u32 plen][u32 blob_crc][pickled(plen)][blob].
# The large binary field of a message (MOSDOp.data, MECSubWrite.chunk, ...)
# rides OUT OF BAND from the pickle: the sender never copies it into a
# serialized buffer (scatter-gather writev via writer.writelines), the
# header crc covers only the small pickled part, and the blob's own
# hardware crc32c protects the bulk bytes — the zero-copy framing half of
# the reference's bufferlist-based wire path (src/msg/async/ProtocolV2.cc
# segments + crc sections role).
FLAG_BLOB = 2
# only bulk payloads are worth the second checksum + reattach bookkeeping
BLOB_MIN = 16 * 1024

ACK_TYPE = 0xFFF0  # control frame: payload is the acked seq (u64)

MAX_SESSIONS = 4096  # LRU cap on server-side peer sessions

# -- message registry --------------------------------------------------------

_MSG_TYPES: Dict[int, type] = {}
_MSG_IDS: Dict[type, int] = {}


def message(type_id: int, version: int = 1):
    """Register a message dataclass with a wire type id + version."""

    def deco(cls):
        existing = _MSG_TYPES.get(type_id)
        if existing is not None and existing.__name__ != cls.__name__:
            raise ValueError(
                f"wire type id {type_id} already taken by "
                f"{existing.__name__}; cannot register {cls.__name__}"
            )
        cls = dataclass(cls)
        cls.TYPE_ID = type_id
        cls.VERSION = version
        _MSG_TYPES[type_id] = cls
        _MSG_IDS[cls] = type_id
        return cls

    return deco


# -- lane negotiation / fragmentation wire types -----------------------------
# Messenger-internal data-plane types (fixed layouts; corpus + dencoder
# covered like every other wire type).  They live HERE, not types.py,
# because the lane layer itself produces and consumes them.


@message(71)
class MLaneHello:
    """First frame on every lane of a multi-lane peer session: binds the
    carrying connection to lane ``lane`` of lane-group ``group`` (the
    connection-negotiation fields of the wire plane).  Lane 0's hello
    CREATES the group on the acceptor; joining lanes attach to it.
    ``proc`` carries a short digest of the sender's process token for
    diagnostics only — colocation trust rides the handshake hello."""

    group: str = ""
    lane: int = 0
    n_lanes: int = 1
    proc: str = ""
    flags: int = 0

    FIXED_FIELDS = [("group", "s"), ("lane", "q"), ("n_lanes", "q"),
                    ("proc", "s"), ("flags", "Q")]


@message(72)
class MLaneSegment:
    """One fragment of a striped large message: blobs >=
    ``ms_lane_stripe_min`` split into per-data-lane segments sent
    concurrently; the receiver reassembles ``nfrags`` chunks into one
    contiguous buffer, decodes the original message from ``header``
    (fragment 0 carries it) and releases it into the gseq reorder at
    ``gseq``.  ``total`` is the full blob length, ``off`` this chunk's
    byte offset — explicit, so reassembly never depends on arrival
    order or even chunk sizing."""

    gseq: int = 0
    idx: int = 0
    nfrags: int = 1
    total: int = 0
    off: int = 0
    type_id: int = 0
    version: int = 1
    fixed: bool = False
    header: bytes = b""
    chunk: bytes = b""

    FIXED_FIELDS = [("gseq", "Q"), ("idx", "q"), ("nfrags", "q"),
                    ("total", "q"), ("off", "q"), ("type_id", "q"),
                    ("version", "q"), ("fixed", "?"), ("header", "y"),
                    ("chunk", "y")]
    BLOB_ATTR = "chunk"
    BLOB_VIEW_OK = True


# store-resident buffers may be memoryviews (ownership-transferred
# encode outputs); when one rides a pickled message field on the REAL
# wire, serialize it as its bytes — the local fastpath never serializes
import copyreg  # noqa: E402

copyreg.pickle(memoryview, lambda m: (bytes, (bytes(m),)))


def _norm_segments(segments):
    """Normalize buffers to non-empty 1-D byte memoryviews; returns
    (views, total_bytes).  Shared by BufferList and CorkedWriter so the
    cast/skip-empty rules cannot drift apart."""
    segs = []
    total = 0
    for s in segments:
        mv = s if isinstance(s, memoryview) else memoryview(s)
        if mv.ndim != 1 or mv.itemsize != 1:
            mv = mv.cast("B")
        if mv.nbytes:
            segs.append(mv)
            total += mv.nbytes
    return segs, total


class BufferList:
    """A blob made of multiple buffers (the reference's bufferlist,
    src/common/buffer.h): a message's bulk field may be handed over as a
    LIST of byte pieces — per-stripe chunk views, extent slices — and the
    corked send path writev's the pieces straight from their owning
    buffers.  No producer-side gather copy: the de-interleave a read
    reply used to pay (stripes -> one contiguous buffer -> frame) becomes
    a list of views the kernel gathers.  The frame crc chains across the
    pieces, so the bytes on the wire (and the receiver, which sees one
    contiguous blob land in its frame buffer) are identical to the
    concatenation.  Pickling one (control-plane ride-along, sub-threshold
    fallback) materializes to plain bytes."""

    __slots__ = ("segments", "nbytes")

    def __init__(self, segments=()):
        self.segments, self.nbytes = _norm_segments(segments)

    def __len__(self) -> int:
        return self.nbytes

    def tobytes(self) -> bytes:
        return b"".join(self.segments)

    def __bytes__(self) -> bytes:
        return self.tobytes()


# a BufferList that rides pickle (local-fastpath control copy, or a
# sub-threshold blob folded into the payload) lands as plain bytes
copyreg.pickle(BufferList, lambda bl: (bytes, (bl.tobytes(),)))


def as_bytes(data) -> bytes:
    """Materialize a message bulk field to bytes: blob-lane fields may be
    bytes, bytearray, memoryview, or BufferList depending on the path the
    message took (wire rx buffer, store view, scatter-gather reply)."""
    if isinstance(data, bytes):
        return data
    if isinstance(data, BufferList):
        return data.tobytes()
    return bytes(data)


# -- fixed binary field codec ------------------------------------------------
# Data-plane messages declare FIXED_FIELDS = [(name, kind)]: a flat,
# versioned-by-frame binary layout.  Kinds: q/Q/d/? scalars, s (u32-len
# utf8), y (u32-len bytes), Q* (u64 list), s* (str list), qq* (list of
# (i64, i64) pairs), addr ((host, port) or None).  A class may gate
# eligibility with FIXED_WHEN(msg) — e.g. MOSDOp falls back to pickle
# when a compound op vector is attached.

_FIX = {k: struct.Struct("<" + k) for k in ("q", "Q", "d", "?")}
_LEN32 = struct.Struct("<I")
_PAIR = struct.Struct("<qq")


def _pack_fixed(msg: Any, fields, blob_attr=None) -> bytes:
    parts = []
    for name, kind in fields:
        v = msg.__dict__.get(name)
        if name == blob_attr:
            v = b""  # rides the blob lane; reattached on decode
        st = _FIX.get(kind)
        if st is not None:
            parts.append(st.pack(v if kind != "?" else bool(v)))
        elif kind == "s":
            b = (v or "").encode()
            parts.append(_LEN32.pack(len(b)))
            parts.append(b)
        elif kind == "y":
            b = v if isinstance(v, (bytes, bytearray)) else \
                (b"" if v is None else bytes(v))
            parts.append(_LEN32.pack(len(b)))
            parts.append(b)
        elif kind == "Q*":
            v = v or ()
            parts.append(_LEN32.pack(len(v)))
            parts.append(struct.pack(f"<{len(v)}Q", *v))
        elif kind == "s*":
            v = v or ()
            parts.append(_LEN32.pack(len(v)))
            for s in v:
                b = s.encode()
                parts.append(_LEN32.pack(len(b)))
                parts.append(b)
        elif kind == "qq*":
            v = v or ()
            parts.append(_LEN32.pack(len(v)))
            for a, b in v:
                parts.append(_PAIR.pack(a, b))
        elif kind == "addr":
            if not v:
                parts.append(_LEN32.pack(0xFFFFFFFF))
            else:
                h = str(v[0]).encode()
                parts.append(_LEN32.pack(len(h)))
                parts.append(h)
                parts.append(_FIX["q"].pack(int(v[1])))
        else:  # pragma: no cover - schema bug
            raise ValueError(f"unknown fixed kind {kind!r}")
    return b"".join(parts)


def _default_copy(v):
    return list(v) if isinstance(v, list) else (
        dict(v) if isinstance(v, dict) else v)


def _unpack_fixed(cls, payload: bytes, blob: Any):
    obj = cls.__new__(cls)
    d = obj.__dict__
    # non-fixed fields keep their dataclass defaults (fresh containers)
    defaults = _FIXED_DEFAULTS.get(cls)
    if defaults is None:
        defaults = _FIXED_DEFAULTS[cls] = {
            k: v for k, v in cls().__dict__.items()}
    fixed_names = {n for n, _ in cls.FIXED_FIELDS}
    for k, v in defaults.items():
        if k not in fixed_names:
            d[k] = _default_copy(v)
    off = 0
    mv = memoryview(payload)
    for idx, (name, kind) in enumerate(cls.FIXED_FIELDS):
        if off >= len(payload):
            # truncated tail: the sender's FIXED_FIELDS list was SHORTER
            # — an old build predating trailing additions like the
            # trace-id pair.  Default the unsent remainder (the
            # fixed-layout analog of the reference's versioned-decode
            # "new fields default" rule); new fields MUST append.
            for tail_name, _ in cls.FIXED_FIELDS[idx:]:
                d[tail_name] = _default_copy(defaults[tail_name])
            break
        st = _FIX.get(kind)
        if st is not None:
            d[name] = st.unpack_from(payload, off)[0]
            off += st.size
        elif kind in ("s", "y"):
            (n,) = _LEN32.unpack_from(payload, off)
            off += 4
            raw = bytes(mv[off:off + n])
            off += n
            d[name] = raw.decode() if kind == "s" else raw
        elif kind == "Q*":
            (n,) = _LEN32.unpack_from(payload, off)
            off += 4
            d[name] = list(struct.unpack_from(f"<{n}Q", payload, off))
            off += 8 * n
        elif kind == "s*":
            (n,) = _LEN32.unpack_from(payload, off)
            off += 4
            out = []
            for _ in range(n):
                (sn,) = _LEN32.unpack_from(payload, off)
                off += 4
                out.append(bytes(mv[off:off + sn]).decode())
                off += sn
            d[name] = out
        elif kind == "qq*":
            (n,) = _LEN32.unpack_from(payload, off)
            off += 4
            out = []
            for _ in range(n):
                out.append(_PAIR.unpack_from(payload, off))
                off += _PAIR.size
            d[name] = out
        elif kind == "addr":
            (n,) = _LEN32.unpack_from(payload, off)
            off += 4
            if n == 0xFFFFFFFF:
                d[name] = None
            else:
                host = bytes(mv[off:off + n]).decode()
                off += n
                port = _FIX["q"].unpack_from(payload, off)[0]
                off += 8
                d[name] = (host, port)
    if blob is not None:
        d[getattr(cls, "BLOB_ATTR")] = blob
    return obj


_FIXED_DEFAULTS: Dict[type, Dict[str, Any]] = {}


def encode_payload(msg: Any) -> bytes:
    return pickle.dumps(msg.__dict__, protocol=5)


def encode_payload_parts(msg: Any):
    """(header, blob, fixed): when the message class declares BLOB_ATTR
    and the field is bulk bytes, it is stripped from the header part and
    returned separately so framing can scatter-gather it with zero
    copies.  Data-plane classes with FIXED_FIELDS get the fixed binary
    layout for the header part (fixed=True) instead of pickle."""
    cls = type(msg)
    attr = getattr(cls, "BLOB_ATTR", None)
    blob = None
    if attr is not None:
        b = msg.__dict__.get(attr)
        if isinstance(b, (bytes, bytearray, memoryview, BufferList)) \
                and len(b) >= BLOB_MIN:
            blob = b
    fields = getattr(cls, "FIXED_FIELDS", None)
    if fields is not None:
        when = getattr(cls, "FIXED_WHEN", None)
        if when is None or when(msg):
            return (_pack_fixed(msg, fields,
                                blob_attr=attr if blob is not None
                                else None),
                    blob, True)
    if blob is not None:
        d = dict(msg.__dict__)
        d[attr] = None  # reattached by decode_message
        return pickle.dumps(d, protocol=5), blob, False
    if attr is not None:
        b = msg.__dict__.get(attr)
        if isinstance(b, memoryview):
            # below the blob threshold the field rides the pickle,
            # which cannot serialize memoryviews natively fast
            d = dict(msg.__dict__)
            d[attr] = bytes(b)
            return pickle.dumps(d, protocol=5), None, False
    return pickle.dumps(msg.__dict__, protocol=5), None, False


def decode_message(type_id: int, version: int, payload: bytes,
                   blob: Any = None, fixed: bool = False) -> Any:
    cls = _MSG_TYPES.get(type_id)
    if cls is None:
        raise ValueError(f"unknown message type {type_id}")
    if version > cls.VERSION:
        raise ValueError(
            f"{cls.__name__} wire version {version} > supported {cls.VERSION}"
        )
    if fixed:
        if getattr(cls, "FIXED_FIELDS", None) is None:
            raise ValueError(f"{cls.__name__}: unexpected fixed frame")
        return _unpack_fixed(cls, payload, blob)
    obj = cls.__new__(cls)
    obj.__dict__.update(pickle.loads(payload))
    if blob is not None:
        setattr(obj, getattr(cls, "BLOB_ATTR"), blob)
    return obj


# frame/bulk checksum: the shared hardware-crc32c resolver.  The KIND in
# use rides the handshake hello: when the two ends resolved differently
# (one host's native build failed), the connection falls back to zlib for
# its frames instead of looping on BadFrame forever.
from ceph_tpu.utils.checksum import checksum, checksum_kind  # noqa: E402


class BadFrame(Exception):
    pass


# Everything a send/dial can legitimately raise when the PEER (not this
# process) is at fault: socket errors, handshake refusals/garbage, dial
# timeouts.  Daemons catching "send failed, treat as missing ack" catch
# THIS, not Exception — a TypeError in our own framing code must crash
# loudly, not melt into a silent degraded loop.  (ConnectionError and
# PermissionError are OSError subclasses and IncompleteReadError an
# EOFError subclass — listed anyway to document the intended surface.)
TRANSPORT_ERRORS = (ConnectionError, OSError, asyncio.TimeoutError,
                    asyncio.IncompleteReadError, EOFError, BadFrame,
                    PermissionError, json.JSONDecodeError)


# -- policies ----------------------------------------------------------------


@dataclass
class Policy:
    lossy: bool = True
    replay: bool = False  # keep unacked queue + replay on reconnect

    @classmethod
    def lossy_client(cls) -> "Policy":
        return cls(lossy=True, replay=False)

    @classmethod
    def lossless_peer(cls) -> "Policy":
        return cls(lossy=False, replay=True)


def _cget(conf, key: str, default: Any) -> Any:
    try:
        v = conf.get(key, default)
    except TypeError:
        v = conf.get(key) if key in conf else default
    return default if v is None else v


# -- local fast dispatch -----------------------------------------------------

# addr -> live Messenger in THIS process.  Colocated daemons' frames can
# skip the TCP stack entirely (ms_local_fastpath): the in-process
# equivalent of the reference's Messenger local_connection fast dispatch
# and the colocated-transport seam its pluggable NetworkStack keeps open
# (src/msg/async/Stack.h; DPDK/RDMA lanes plug in there the same way).
_LOCAL_REGISTRY: Dict[Tuple[str, int], "Messenger"] = {}


class LocalConnection:
    """In-process session with a colocated daemon: typed messages hand
    over BY REFERENCE through a receiver-side FIFO — no sockets,
    framing, checksums, or serialization.  Delivery matches a lossless
    wire session: per-connection order (one pump task), exactly-once
    (no transport to fail mid-frame), and dispatcher isolation
    (exceptions log, never propagate into the sender — the _serve
    discipline).  Shared contract with the reference's local delivery:
    a message is immutable once sent.

    Enabled per-messenger by ms_local_fastpath; vstart turns it on for
    plain clusters, while any wire-exercising configuration (auth,
    secure mode, fault injection) keeps real sockets so those paths
    stay covered."""

    def __init__(self, messenger: "Messenger", peer_messenger: "Messenger",
                 reverse: Optional["LocalConnection"] = None):
        self.messenger = messenger
        self.peer_messenger = peer_messenger
        self.peer = tuple(peer_messenger.addr or ("local", 0))
        self.peer_name = peer_messenger.name
        self.policy = Policy.lossless_peer()
        self.outbound = reverse is None
        # how the peer "authenticated": same-process construction IS the
        # trust statement (fastpath is off whenever auth is configured)
        self.auth_kind = "local"
        self.auth_entity_type = peer_messenger.entity_type
        self.closed = False
        # bounded: a flooding sender parks on put() exactly like a full
        # socket buffer parks drain()
        self._queue: asyncio.Queue = asyncio.Queue(maxsize=1024)
        self._pump: Optional[asyncio.Task] = None
        self.reverse = reverse if reverse is not None else \
            LocalConnection(peer_messenger, messenger, reverse=self)

    async def send(self, msg: Any) -> None:
        peer = self.peer_messenger
        if (self.closed or peer._shutdown
                or _LOCAL_REGISTRY.get(self.peer) is not peer):
            self.closed = True
            raise ConnectionError(f"local peer {self.peer_name} gone")
        cls = type(msg)
        fields = getattr(cls, "FIXED_FIELDS", None)
        when = getattr(cls, "FIXED_WHEN", None)
        if fields is None or (when is not None and not when(msg)):
            # CONTROL-plane (or exotic) payload: give the receiver its
            # own object graph, exactly as the pickled wire would.
            # By-reference handoff is only safe for the flat, immutable
            # data-plane set — a control payload like MMapReply carries
            # the mon's LIVE OSDMap, whose next in-place mutation would
            # otherwise tear every colocated daemon's shared copy.
            msg = pickle.loads(pickle.dumps(msg, protocol=5))
        await self.reverse._deliver(msg)
        self.messenger.perf.inc("local_msgs")

    async def _deliver(self, msg: Any) -> None:
        await self._queue.put(msg)
        if self._pump is None or self._pump.done():
            m = self.messenger
            self._pump = asyncio.get_running_loop().create_task(
                self._pump_loop())
            m._tasks.add(self._pump)
            self._pump.add_done_callback(m._tasks.discard)

    async def _pump_loop(self) -> None:
        while not self.closed and not self.messenger._shutdown:
            msg = await self._queue.get()
            disp = self.messenger.dispatcher
            if disp is None:
                continue
            try:
                await disp(self, msg)
            except (asyncio.CancelledError, GeneratorExit):
                raise
            except Exception:
                traceback.print_exc()

    async def close(self, gen: int = 0) -> None:
        self.closed = True
        if self._pump is not None:
            self._pump.cancel()


# -- connection --------------------------------------------------------------


class FrameReceiver(asyncio.BufferedProtocol):
    """Zero-copy receive path: installed over the connection's transport
    (transport.set_protocol) AFTER the handshake, replacing the
    StreamReader chain whose kernel-copy -> feed_data-extend ->
    readexactly-slice pipeline double-copies every byte.  BufferedProtocol
    hands the transport OUR buffer: while a readexactly() is pending, the
    destination frame buffer itself is exposed, so payload bytes land
    exactly once.  Write-side flow control keeps working by forwarding
    pause_writing/resume_writing to the original stream protocol (the
    StreamWriter's drain() still consults it)."""

    # small backlog cap: bytes that arrive before a readexactly() is
    # waiting land in _pending and must be COPIED out, so the transport
    # pauses early — the single-copy path is bytes landing directly in
    # the registered destination buffer.  The native wirepath inverts
    # the tradeoff (Connection._rx_drain_native verifies AND lands the
    # whole backlog below the GIL), so enable_fast_read sizes the
    # backlog UP when that arm is live: a burst of bulk frames must fit
    # complete frames in _pending for the batch drain to engage at all.
    _LIMIT = 128 << 10
    _NATIVE_LIMIT = 1 << 20
    _NATIVE_SCRATCH = 256 << 10

    def __init__(self, transport, stream_protocol, leftover: bytes = b"",
                 limit: Optional[int] = None, scratch: Optional[int] = None):
        self._transport = transport
        self._stream_protocol = stream_protocol
        self._pending = bytearray(leftover)
        self._off = 0  # consumed prefix of _pending (O(1) front-consume)
        self._dest = None  # memoryview being filled by get_buffer
        self._dest_pos = 0
        if limit is not None:
            self._LIMIT = limit  # instance override of the class cap
        self._scratch = bytearray(scratch or (64 * 1024))
        self._scratch_view = memoryview(self._scratch)
        self._waiter: Optional[asyncio.Future] = None
        self._eof = False
        self._exc: Optional[BaseException] = None
        self._read_paused = False
        self._via_scratch = True  # last get_buffer handed out scratch
        # the connection's CorkedWriter, when one took over the tx side:
        # connection_lost must fail its drain waiters too
        self.corked = None

    # -- protocol side -------------------------------------------------------

    def get_buffer(self, sizehint: int):
        if self._dest is not None and self._dest_pos < len(self._dest):
            remaining = len(self._dest) - self._dest_pos
            if remaining >= len(self._scratch):
                # bulk destination (blob body): single-copy direct fill
                self._via_scratch = False
                return self._dest[self._dest_pos:]
            # SMALL destination (frame header, short payload): read
            # GREEDILY through scratch so one recv drains everything the
            # kernel has — the surplus (trailing frames of a burst)
            # lands in _pending, which is what the serve loop's rx
            # batching predicate looks at.  A per-dest-sized recv here
            # would hand frames over one at a time (two syscalls per
            # tiny frame) and batching would never see a second frame.
            self._via_scratch = True
            return self._scratch_view
        self._via_scratch = True
        return self._scratch_view

    def buffer_updated(self, nbytes: int) -> None:
        if self._dest is not None and self._dest_pos < len(self._dest):
            if not self._via_scratch:
                self._dest_pos += nbytes
                # wake the reader only when its buffer is COMPLETE: a
                # wake per network chunk would round-trip the event loop
                # hundreds of times per blob, each competing with every
                # other ready callback in a busy daemon
                if self._dest_pos >= len(self._dest):
                    self._wake()
                return
            # greedy scratch read: split between the waiting dest and
            # the pending backlog
            remaining = len(self._dest) - self._dest_pos
            take = min(nbytes, remaining)
            self._dest[self._dest_pos:self._dest_pos + take] = \
                self._scratch_view[:take]
            self._dest_pos += take
            if nbytes > take:
                self._pending += self._scratch_view[take:nbytes]
                self._check_limit()
            if self._dest_pos >= len(self._dest):
                self._wake()
        else:
            self._pending += self._scratch_view[:nbytes]
            self._check_limit()
            self._wake()

    def _check_limit(self) -> None:
        if len(self._pending) - self._off > self._LIMIT \
                and not self._read_paused:
            self._read_paused = True
            try:
                self._transport.pause_reading()
            except Exception:
                pass

    def eof_received(self):
        self._eof = True
        self._wake()
        return False

    def connection_lost(self, exc) -> None:
        self._eof = True
        self._exc = exc
        self._wake()
        if self.corked is not None:
            self.corked._on_lost(exc)
        # the StreamWriter still drains through the ORIGINAL stream
        # protocol: without this forward, a drain() parked on a paused
        # writer never learns the connection died and waits forever —
        # holding the connection send lock and wedging every reconnect
        try:
            self._stream_protocol.connection_lost(exc)
        except Exception:
            pass

    def pause_writing(self) -> None:
        self._stream_protocol.pause_writing()

    def resume_writing(self) -> None:
        self._stream_protocol.resume_writing()

    def _wake(self) -> None:
        w = self._waiter
        if w is not None and not w.done():
            w.set_result(None)

    # -- reader side ---------------------------------------------------------

    async def readexactly(self, n: int, uninit: bool = False, into=None):
        """Read n bytes.  With ``uninit=True`` the destination is an
        UNINITIALIZED buffer (np.empty) returned as a memoryview:
        bytearray(n) memsets n zero bytes the socket is about to
        overwrite, a full extra pass over the data volume on blob
        frames.  Only blob fields whose consumers are buffer-safe
        (BLOB_VIEW_OK types: store/decode lanes) opt in — everything
        else keeps bytearray semantics (concat, decode, mutation).
        With ``into=`` the bytes land DIRECTLY in the caller's buffer
        (the lane-fragment reassembly seam: a striped blob's segments
        fill their slice of the assembly buffer with zero extra
        passes); the buffer is returned."""
        pend = self._pending
        avail = len(pend) - self._off
        if into is not None:
            buf = into if isinstance(into, memoryview) \
                else memoryview(into)
            if buf.ndim != 1 or buf.itemsize != 1:
                buf = buf.cast("B")
            mv = buf
            if avail >= n:
                mv[:n] = pend[self._off:self._off + n]
                self._consume(n)
                return buf
        elif avail >= n:
            out = bytes(pend[self._off:self._off + n])
            self._consume(n)
            return out
        elif uninit:
            buf = memoryview(np.empty(n, dtype=np.uint8)).cast("B")
            mv = buf
        else:
            buf = bytearray(n)
            mv = memoryview(buf)
        pos = avail
        if pos:
            mv[:pos] = pend[self._off:]
            self._off = 0
            pend.clear()
            self._maybe_resume()
        self._dest = mv
        self._dest_pos = pos
        try:
            while self._dest_pos < n:
                if self._eof:
                    if self._exc is not None and not isinstance(
                            self._exc, (ConnectionError, OSError)):
                        raise self._exc
                    raise asyncio.IncompleteReadError(
                        bytes(mv[:self._dest_pos]), n)
                self._waiter = asyncio.get_running_loop().create_future()
                try:
                    await self._waiter
                finally:
                    self._waiter = None
        finally:
            self._dest = None
        return buf

    def _consume(self, n: int) -> None:
        """Advance the consumed-prefix pointer; compact only when the
        dead prefix dominates (amortized O(1) — a del-from-front per
        read is an O(len) memmove that dominated profiles)."""
        self._off += n
        pend = self._pending
        if self._off == len(pend):
            self._off = 0
            pend.clear()
        elif self._off > 1 << 16 and self._off * 2 > len(pend):
            del pend[:self._off]
            self._off = 0
        self._maybe_resume()

    def _maybe_resume(self) -> None:
        if self._read_paused \
                and len(self._pending) - self._off < self._LIMIT // 2:
            self._read_paused = False
            try:
                self._transport.resume_reading()
            except Exception:
                pass


class CorkedWriter:
    """Zero-copy scatter-gather tx path: once the handshake is done (and
    the transport's own write buffer is empty), the connection's flusher
    swaps the StreamWriter for this — writes go STRAIGHT from the frame
    segments to ``socket.sendmsg`` (writev), so frame bytes are never
    joined or copied into a transport buffer.  The asyncio transport
    keeps owning the rx side (FrameReceiver) and the fd's lifetime; this
    class only owns which bytes leave.

    Congestion handling: segments queue in a deque; a full socket
    registers an add_writer callback that resumes sendmsg as the kernel
    drains.  ``drain()`` parks senders until the backlog is fully
    written: queued segments are VIEWS of live caller buffers (encode
    outputs, store blobs), and a drain that returned with segments still
    queued would let the owner mutate bytes before the kernel reads
    them.  Zero-copy therefore trades the overlap a buffered writer has
    — the copies it saves are the whole point.

    Failure: a send error (or the transport's connection_lost, forwarded
    by FrameReceiver) fails queued segments and drain waiters with the
    transport error — the same surface StreamWriter.drain() has."""

    IOV_MAX = 512  # segments per sendmsg call (conservative vs UIO_MAXIOV)

    def __init__(self, transport, sock, stream_writer, wp=None, perf=None):
        self._transport = transport
        self._sock = sock
        self._sw = stream_writer  # close/wait_closed/extra-info delegate
        # native wirepath arm: one released-GIL writev call drains the
        # whole backlog (partial writes, EINTR, IOV batching loop in C)
        # instead of the Python sendmsg walk below; perf counts the arm
        self._wp = wp
        self._perf = perf
        loop = asyncio.get_running_loop()
        self._loop = loop
        # the PRIVATE writer registration transports themselves use: the
        # public add_writer refuses fds owned by a transport (ours is —
        # the transport keeps the rx side).  _maybe_cork gates on these
        # existing, so an event loop without them just never corks.
        self._add_writer = loop._add_writer
        self._remove_writer = loop._remove_writer
        self._fd = sock.fileno()
        self._segs: Deque = collections.deque()
        self._buffered = 0
        self._writer_on = False  # add_writer registered
        self._waiters: list = []
        self._exc: Optional[BaseException] = None

    # -- StreamWriter surface -------------------------------------------------

    def write(self, data) -> None:
        self.writelines([data])

    def writelines(self, segments) -> None:
        if self._exc is not None:
            return  # error surfaces at drain(), like StreamWriter
        segs, total = _norm_segments(segments)
        self._segs.extend(segs)
        self._buffered += total
        if not self._writer_on:
            self._do_send()

    async def drain(self) -> None:
        while self._exc is None and self._buffered > 0:
            fut = self._loop.create_future()
            self._waiters.append(fut)
            await fut
        if self._exc is not None:
            exc = self._exc
            raise exc if isinstance(exc, Exception) \
                else ConnectionResetError("connection lost")

    def close(self) -> None:
        # best-effort final flush, then the transport closes the fd; any
        # still-unsent segments are dropped (lossless replay re-delivers)
        if self._exc is None and self._segs and not self._writer_on:
            self._do_send()
        self._detach()
        self._sw.close()

    async def wait_closed(self) -> None:
        await self._sw.wait_closed()

    def get_extra_info(self, *a, **kw):
        return self._sw.get_extra_info(*a, **kw)

    @property
    def transport(self):
        return self._transport

    # -- socket side ----------------------------------------------------------

    def _do_send(self) -> None:
        try:
            if self._wp is not None and self._segs:
                # ONE foreign call writes the whole backlog with the
                # GIL released — wirepy_writev loops partial writes /
                # EINTR / IOV_MAX internally and returns only on
                # completion or EAGAIN (the PyDLL shim parses the
                # segment list itself, so the Python side pays a bare
                # list() per call)
                written = self._wp.wirepy_writev(self._fd,
                                                 list(self._segs))
                if self._perf is not None:
                    self._perf.inc("native_tx_calls")
                    if written:
                        self._perf.inc("native_bytes", written)
                if written:
                    self._advance(written)
                if self._segs:
                    raise BlockingIOError  # kernel buffer full
            while self._segs:
                if len(self._segs) > self.IOV_MAX:
                    batch = list(itertools.islice(self._segs, self.IOV_MAX))
                else:
                    batch = list(self._segs)
                sent = self._sock.sendmsg(batch)
                self._advance(sent)
        except (BlockingIOError, InterruptedError):
            if not self._writer_on:
                self._writer_on = True
                self._add_writer(self._fd, self._do_send)
            return
        except OSError as e:
            self._on_lost(e)
            return
        if self._writer_on:
            self._writer_on = False
            try:
                self._remove_writer(self._fd)
            except Exception:
                pass
        self._wake()

    def _advance(self, n: int) -> None:
        self._buffered -= n
        while n and self._segs:
            head = self._segs[0]
            if n >= head.nbytes:
                n -= head.nbytes
                self._segs.popleft()
            else:
                self._segs[0] = head[n:]
                n = 0

    def _wake(self) -> None:
        if self._buffered == 0 or self._exc is not None:
            waiters, self._waiters = self._waiters, []
            for w in waiters:
                if not w.done():
                    w.set_result(None)

    def _detach(self) -> None:
        if self._writer_on:
            self._writer_on = False
            try:
                self._remove_writer(self._fd)
            except Exception:
                pass

    def _on_lost(self, exc) -> None:
        if self._exc is None:
            self._exc = exc if exc is not None else \
                ConnectionResetError("connection lost")
        self._detach()
        self._segs.clear()
        self._buffered = 0
        self._wake()


class Connection:
    """One ordered session with a peer.  For lossless sessions this object
    outlives TCP transports: seqs, the unacked queue, and the dedupe floor
    persist while transports come and go (transport_gen fences stale serve
    loops)."""

    def __init__(self, messenger: "Messenger", reader, writer,
                 peer: Tuple[str, int], policy: Policy,
                 peer_name: str = "", outbound: bool = False):
        self.messenger = messenger
        self.reader = reader
        self.writer = writer
        self.peer = peer
        self.peer_name = peer_name
        self.policy = policy
        self.outbound = outbound
        # how the peer authenticated ("ticket" / "secret" / "none") — set
        # by the acceptor after _handshake_in; outbound conns keep "none"
        self.auth_kind = "none"
        self.auth_entity_type = ""
        self.closed = False
        self.transport_gen = 0
        self.out_seq = 0
        self.in_seq = 0  # highest data seq dispatched (dedupe floor)
        # multi-reactor plane: the event loop owning this connection's
        # transport (all of its coroutine work runs there; cross-loop
        # senders hop via Messenger._conn_send), the reactor worker when
        # one owns the shard, and the lane-group membership when this
        # connection is one lane of a striped peer session
        try:
            self.loop: Optional[asyncio.AbstractEventLoop] = \
                asyncio.get_running_loop()
        except RuntimeError:
            self.loop = None
        self.reactor = None  # ReactorWorker owning this socket's shard
        # process mode: the reactor worker PROCESS this connection's
        # socket was delegated to (reader/writer are ShmConnEndpoints)
        self.shm_worker = None
        self.lane_group: Optional["LaneGroup"] = None
        self.lane_idx = 0
        # dispatch throttle for THIS connection's loop: the home loop
        # shares the messenger-wide throttle; each reactor worker gets
        # its own (receive backpressure is per shard — asyncio futures
        # inside Throttle are loop-bound)
        self.throttle = messenger._throttle_here()
        # per-connection session id: acceptors key replay sessions on it, so
        # a REPLACED connection never collides with its predecessor's seqs
        self.session_id = random.randbytes(8).hex()
        self.unacked: Deque[Tuple[int, bytes]] = collections.deque()
        from ceph_tpu.common.lockdep import make_async_mutex

        self._send_lock = make_async_mutex("conn-send")
        # corked outbox (module docstring "Cork/flush discipline"):
        # framed segments awaiting the next flush window, the shared
        # future senders in that window await, and the single flusher
        # task that drains windows with one writelines+drain each
        self._outbox: list = []
        self._outbox_frames = 0
        self._outbox_bytes = 0
        self._ack_pending = -1  # highest seq owed an ack; -1 = none
        self._flush_fut: Optional[asyncio.Future] = None
        self._flusher: Optional[asyncio.Task] = None
        self._corked_ok = bool(_cget(messenger.conf, "ms_corked_writev",
                                     True))
        # crc/compression resolved once per connection (v2 negotiates at
        # handshake time; avoids typed-config parsing on the hot path)
        conf = messenger.conf
        self.crc_enabled = bool(_cget(conf, "ms_crc_data", True))
        self.compress_min = int(_cget(conf, "ms_compress_min_size", 0) or 0)
        # frame checksum for THIS connection: crc32c when both ends run
        # the native build (negotiated via the hello's "ckind"), zlib
        # otherwise — a silent per-host resolver difference must degrade,
        # not deadlock (set by the handshake; default local resolver)
        self.crc_fn = checksum
        # native wirepath arm (messenger-resolved): rx drains consult it
        # together with crc_fn — a zlib-negotiated connection keeps the
        # python arm so frame bytes stay identical either way
        self.wp = messenger.wirepath
        # frames pre-verified + pre-scattered by _rx_drain_native,
        # awaiting read_frame pops (each entry is read_frame's tuple);
        # _rx_error raises once the stash drains (a bad frame mid-burst
        # fails the connection AFTER its valid predecessors dispatch)
        self._rx_stash: Deque = collections.deque()
        self._rx_error: Optional[BaseException] = None

    def enable_fast_read(self) -> None:
        """Swap the StreamReader for the zero-copy FrameReceiver when the
        transport allows it (plaintext TCP; not already swapped).  Called
        at serve-loop start — the handshake has fully drained its reads,
        and any bytes the stream already buffered carry over."""
        r = self.reader
        if not isinstance(r, asyncio.StreamReader):
            return  # SecureStream (AES-GCM) or already a FrameReceiver
        try:
            transport = r._transport  # the stream pair shares it
            if transport is None:
                return
            proto = transport.get_protocol()
            leftover = bytes(r._buffer)
            r._buffer.clear()
            if self.wp is not None and (self.crc_fn is checksum
                                        or not self.crc_enabled):
                # native rx drain (same predicate read_frame gates the
                # drain on — a zlib-negotiated connection stays on the
                # python arm and must keep the small backlog): complete
                # frames must BUFFER for the burst verify+scatter to
                # batch, and the backlog-copy penalty the small default
                # guards against runs below the GIL on this arm
                receiver = FrameReceiver(
                    transport, proto, leftover,
                    limit=FrameReceiver._NATIVE_LIMIT,
                    scratch=FrameReceiver._NATIVE_SCRATCH)
            else:
                receiver = FrameReceiver(transport, proto, leftover)
            if r.at_eof():
                receiver._eof = True  # FIN landed before the swap
            transport.set_protocol(receiver)
            # the StreamReader may have left the transport paused (its
            # own flow control); the receiver starts unpaused, so resume
            # or reads would hang forever once the leftover drains
            try:
                transport.resume_reading()
            except Exception:
                pass
        except Exception:
            return
        self.reader = receiver

    # -- frame IO ------------------------------------------------------------

    def _frame(self, type_id: int, version: int, payload: bytes, seq: int,
               flags: int = 0) -> bytes:
        if self.compress_min and len(payload) >= self.compress_min:
            compressed = zlib.compress(payload, 1)
            if len(compressed) < len(payload):
                payload = compressed
                flags |= FLAG_COMPRESSED
        crc = self.crc_fn(payload) if self.crc_enabled else 0
        return _HDR.pack(len(payload), type_id, version, flags, crc, seq) + payload

    def _frame_segments(self, type_id: int, version: int, pickled: bytes,
                        blob, seq: int, flags: int = 0,
                        blob_crc: Optional[int] = None):
        """Scatter-gather frame for a blob message: the bulk bytes are
        never concatenated into a serialized buffer — the transport
        writev's [hdr, prefix, pickled, blob...] as-is (a BufferList blob
        contributes each piece unjoined).  The header crc covers
        prefix+pickled (small); the blob carries its own crc32c —
        ``blob_crc`` passes a crc the sender already holds over exactly
        these bytes (MECSubWrite.chunk_crc, a stored shard's meta crc) so
        the wire pass is skipped, the reference's bufferlist cached-crc
        discipline.  Blob frames skip on-wire compression (bulk data is
        usually incompressible shard bytes; the pickled part is tiny)."""
        if isinstance(blob, BufferList):
            segs = blob.segments
            blob_len = blob.nbytes
        else:
            segs = [blob]
            blob_len = len(blob)
        if blob_crc is None:
            if not self.crc_enabled:
                blob_crc = 0
            elif self.wp is not None and len(segs) > 1 \
                    and self.crc_fn is checksum:
                # multi-piece BufferList: ONE released-GIL call chains
                # the crc across every piece (was one ctypes round-trip
                # per piece)
                blob_crc = self.wp.wirepy_crc_chain(segs)
                self.messenger.perf.inc("native_tx_calls")
                self.messenger.perf.inc("native_bytes", blob_len)
            else:
                blob_crc = 0
                for s in segs:
                    blob_crc = self.crc_fn(s, blob_crc)
        else:
            self.messenger.perf.inc("tx_crc_reused")
        prefix = _BLOB_PFX.pack(len(pickled), blob_crc)
        crc = (self.crc_fn(pickled, self.crc_fn(prefix))
               if self.crc_enabled else 0)
        hdr = _HDR.pack(_BLOB_PFX.size + len(pickled) + blob_len,
                        type_id, version, FLAG_BLOB | flags, crc, seq)
        return [hdr, prefix, pickled, *segs]

    # -- corked outbox (tx coalescing) ---------------------------------------

    def _seg_len(self, s) -> int:
        return s.nbytes if isinstance(s, memoryview) else len(s)

    async def _enqueue(self, data) -> None:
        """Append one framed message to the outbox and await the flush
        window that carries it.  Concurrent senders in the same window
        share ONE writelines + ONE drain; a transport failure fails the
        whole window (each sender sees ConnectionResetError)."""
        if self.closed:
            raise ConnectionResetError("connection closed")
        segs = data if isinstance(data, list) else [data]
        self._outbox.extend(segs)
        self._outbox_frames += 1
        self._outbox_bytes += sum(self._seg_len(s) for s in segs)
        fut = self._flush_fut
        if fut is None:
            fut = self._flush_fut = \
                asyncio.get_running_loop().create_future()
        self._kick_flusher()
        await fut

    def queue_ack(self, seq: int) -> None:
        """Queue a cumulative ack for ``seq`` (acks are cumulative: the
        receiver pops every unacked frame <= seq, so only the highest
        pending seq ever needs a frame).  The ack piggybacks on the next
        flush window — one ack frame per window instead of one per
        dispatched message."""
        if self.closed:
            return
        if self._ack_pending >= 0:
            self.messenger.perf.inc("tx_acks_coalesced")
        self._ack_pending = max(self._ack_pending, seq)
        self._kick_flusher()

    def _kick_flusher(self) -> None:
        if self._flusher is None or self._flusher.done():
            m = self.messenger
            self._flusher = asyncio.get_running_loop().create_task(
                self._flush_loop())
            m._tasks.add(self._flusher)
            self._flusher.add_done_callback(m._tasks.discard)

    def _ack_frame(self) -> bytes:
        payload = struct.pack("<Q", self._ack_pending)
        self._ack_pending = -1
        return _HDR.pack(8, ACK_TYPE, 1, 0, self.crc_fn(payload), 0) + payload

    async def _flush_loop(self) -> None:
        """The per-connection flusher: drains flush windows until the
        outbox and pending ack are empty.  tx accounting lives HERE so
        every socket write — messages, acks — lands in tx_io/tx_bytes;
        per-message framing cost and per-type counts are send()'s
        (_note_tx).  The tx_io timer starts INSIDE the lock: queueing
        behind an adopt_transport replay is not socket time."""
        perf = self.messenger.perf
        try:
            while (self._outbox or self._ack_pending >= 0) \
                    and not self.closed:
                async with self._send_lock:
                    if self.closed:
                        break
                    self._maybe_cork()
                    segs = self._outbox
                    self._outbox = []
                    frames = self._outbox_frames
                    self._outbox_frames = 0
                    nbytes = self._outbox_bytes
                    self._outbox_bytes = 0
                    fut, self._flush_fut = self._flush_fut, None
                    had_data = bool(segs)
                    if self._ack_pending >= 0:
                        ack = self._ack_frame()
                        segs.append(ack)
                        frames += 1
                        nbytes += len(ack)
                        perf.inc("tx_acks")
                    if not segs:
                        break
                    perf.inc("tx_flush_data" if had_data else "tx_flush_ack")
                    perf.inc("tx_flushes")
                    perf.hinc("tx_flush_frames", frames)
                    perf.hinc("tx_flush_bytes", nbytes)
                    gen = self.transport_gen
                    t_io = time.monotonic()
                    try:
                        with perf.time_avg("tx_io"):
                            self.writer.writelines(segs)
                            await self.writer.drain()
                    except (ConnectionError, OSError,
                            asyncio.TimeoutError) as e:
                        if fut is not None and not fut.done():
                            fut.set_exception(ConnectionResetError(
                                f"flush failed: {e}"))
                            fut.exception()  # mark retrieved (no-waiter GC)
                        # gen-fenced: a no-op here means adopt_transport
                        # replaced the transport under us — loop again and
                        # retry the remaining windows on the new writer
                        # (a genuine close ends the loop via its condition)
                        await self.close(gen)
                        continue
                    except asyncio.CancelledError:
                        raise
                    except BaseException as e:
                        # a framing/writer BUG must crash loudly — but
                        # never by leaving the window's senders parked on
                        # a future nobody will resolve
                        if fut is not None and not fut.done():
                            fut.set_exception(
                                ConnectionResetError(f"flush failed: {e}"))
                            fut.exception()
                        await self.close(gen)
                        raise
                    perf.inc("tx_bytes", nbytes)
                    perf.hinc("tx_io_us",
                              (time.monotonic() - t_io) * 1e6)
                    if fut is not None and not fut.done():
                        fut.set_result(None)
        finally:
            if self.closed:
                self._fail_pending(ConnectionResetError("connection closed"))

    def _pin_replay_queue(self) -> None:
        """Materialize view segments of queued unacked frames to bytes.
        Runs at transport death: from here the frames may sit queued for
        a whole reconnect window (or forever, for a gone peer), and a
        queued VIEW would pin its whole backing buffer (e.g. the k-row
        encode matrix behind one shard's 1/k-sized view) for that long.
        While the transport is healthy the queue turns over within an
        RTT, so the hot path never pays this copy."""
        for i, (seq, data) in enumerate(self.unacked):
            if isinstance(data, list) \
                    and any(not isinstance(s, bytes) for s in data):
                self.unacked[i] = (seq, [
                    s if isinstance(s, bytes) else bytes(s) for s in data])

    def _fail_pending(self, exc: Exception) -> None:
        """Fail the pending flush window (senders awaiting it see the
        transport error) and drop un-flushed segments: lossless frames
        live in the unacked queue and replay on the adopted transport;
        un-flushed acks are re-queued by the dedupe path when the peer
        replays."""
        fut, self._flush_fut = self._flush_fut, None
        self._outbox = []
        self._outbox_frames = 0
        self._outbox_bytes = 0
        self._ack_pending = -1
        if fut is not None and not fut.done():
            fut.set_exception(exc)
            fut.exception()  # mark retrieved: ok if every sender left

    def _maybe_cork(self) -> None:
        """Swap the StreamWriter for the zero-copy CorkedWriter when the
        transport allows it (plaintext TCP, nothing buffered in the
        transport, sendmsg available).  Called under the send lock at
        flush time — lazily, so it naturally re-engages after an
        adopt_transport handed us a fresh StreamWriter."""
        if not self._corked_ok:
            return
        w = self.writer
        if not isinstance(w, asyncio.StreamWriter):
            return  # SecureStream (AES-GCM) or already corked
        try:
            transport = w.transport
            if (transport is None or transport.is_closing()
                    or transport.get_write_buffer_size() != 0):
                return
            sock = transport.get_extra_info("socket")
            # unwrap asyncio's TransportSocket: its sendmsg() warns (and
            # is slated for removal); the raw socket is the real surface
            sock = getattr(sock, "_sock", sock)
            if sock is None or not hasattr(sock, "sendmsg"):
                return
            loop = asyncio.get_running_loop()
            if not hasattr(loop, "_add_writer"):
                return  # non-selector loop: keep the stream writer
            corked = CorkedWriter(transport, sock, w,
                                  wp=self.messenger.wirepath,
                                  perf=self.messenger.perf)
            proto = transport.get_protocol()
            if isinstance(proto, FrameReceiver):
                proto.corked = corked  # connection_lost fails its waiters
        except Exception:
            return
        self.writer = corked

    async def send(self, msg: Any) -> None:
        conf = self.messenger.conf
        inj = _cget(conf, "ms_inject_socket_failures", 0)
        injected = bool(inj) and random.randrange(inj) == 0
        if injected and not self.policy.replay:
            await self.close()
            raise ConnectionResetError("injected socket failure")
        delay = _cget(conf, "ms_inject_delay_max", 0)
        if delay:
            await asyncio.sleep(random.uniform(0, delay))
        # ms_inject_dup_frames: deliver this message TWICE (two frames,
        # two seqs — a genuine at-least-once delivery the receiver's seq
        # dedupe cannot filter), exercising the APPLICATION layer's
        # duplicate absorption.  Scoped to the client-op plane, which is
        # the layer contracted to absorb duplicates: MOSDOp dups dedupe
        # against the PG log's reqid set, MOSDOpReply dups against the
        # client's pop-once reply futures.  Other planes (sub-write
        # replies, peering gathers) count messages and are entitled to
        # the session's exactly-once delivery.
        dup_inj = _cget(conf, "ms_inject_dup_frames", 0)
        duplicate = (bool(dup_inj)
                     and type(msg).__name__ in ("MOSDOp", "MOSDOpReply")
                     and random.randrange(dup_inj) == 0)
        self.out_seq += 1
        seq = self.out_seq
        t_frame = time.monotonic()
        pickled, blob, fixed = encode_payload_parts(msg)
        flags = FLAG_FIXED if fixed else 0
        if blob is not None:
            # cached-crc reuse: a message that already carries a crc of
            # EXACTLY its blob bytes (BLOB_CRC_ATTR) skips the wire crc
            # pass — only when this connection's negotiated checksum is
            # the shared resolver the app-level crc was computed with
            pre_crc = None
            crc_attr = getattr(type(msg), "BLOB_CRC_ATTR", None)
            if crc_attr is not None and self.crc_enabled \
                    and self.crc_fn is checksum:
                v = msg.__dict__.get(crc_attr) or 0
                if v:
                    pre_crc = v & 0xFFFFFFFF
            data = self._frame_segments(msg.TYPE_ID, msg.VERSION, pickled,
                                        blob, seq, flags, blob_crc=pre_crc)
        else:
            pre_crc = None
            data = self._frame(msg.TYPE_ID, msg.VERSION, pickled, seq,
                               flags)
        self.messenger._note_tx(type(msg).__name__,
                                sum(self._seg_len(p) for p in data)
                                if isinstance(data, list) else len(data),
                                time.monotonic() - t_frame)
        if self.policy.replay:
            # lossless send never fails: the frame joins the session queue
            # and reconnect+replay delivers it exactly once (reference
            # lossless_peer out_queue semantics).  Blob VIEWS stay views
            # here — on a healthy session the ack pops the frame within
            # an RTT, so the pin on the backing buffer is transient; the
            # frames only materialize to bytes when the transport DIES
            # (close() -> _pin_replay_queue), which is when a frame can
            # actually sit queued long enough for pinning to matter.
            self.unacked.append((seq, data))
            if injected:
                # injected transport failure: frame stays queued, session
                # survives, reconnect+replay delivers
                await self.close()
                return
            try:
                await self._enqueue(data)
            except (ConnectionError, OSError):
                await self.close()
        else:
            await self._enqueue(data)
        if duplicate and not self.closed:
            # the duplicate frame is best-effort: the knob exists to
            # exercise dedup, and a transport error here already has the
            # original frame's failure handling covering the message
            self.out_seq += 1
            dseq = self.out_seq
            if blob is not None:
                ddata = self._frame_segments(
                    msg.TYPE_ID, msg.VERSION, pickled, blob, dseq, flags,
                    blob_crc=pre_crc)
            else:
                ddata = self._frame(msg.TYPE_ID, msg.VERSION, pickled,
                                    dseq, flags)
            if self.policy.replay:
                self.unacked.append((dseq, ddata))
            try:
                await self._enqueue(ddata)
            except (ConnectionError, OSError):
                pass

    async def send_ack(self, seq: int) -> None:
        """Compat shim: queue a cumulative ack (piggybacked on the next
        flush window; see queue_ack)."""
        self.queue_ack(seq)

    def handle_ack(self, seq: int) -> None:
        while self.unacked and self.unacked[0][0] <= seq:
            self.unacked.popleft()

    def buffered_frame_len(self) -> Optional[int]:
        """Payload length of the next COMPLETE frame in hand: a frame
        pre-verified into the rx stash by the native drain first, else
        whatever is fully buffered on the reader — the serve loop's rx
        batching predicate (batch only what needs no network wait).
        Delegated connections peek the shm ring instead: a fully
        buffered record needs no worker round-trip."""
        if self._rx_stash:
            return self._rx_stash[0][4]
        if isinstance(self.reader, ShmConnEndpoint):
            n = self.reader.complete_record_len()
            if n is None:
                return None
            return max(0, n - _SHM_FRAME_HDR.size)
        return Messenger._buffered_frame_len(self.reader)

    def _rx_drain_native(self) -> None:
        """Native rx burst: parse every COMPLETE frame already buffered
        in the FrameReceiver backlog, verify ALL their crc sections in
        ONE released-GIL call (wirepy_verify_regions — the geometry
        rides plain int lists, walked in C), land every verified
        frame's blob bytes with ONE more released-GIL scatter call
        (wirepy_scatter_from) — lane fragments straight into their
        slice of the group assembly buffer (frag_view) — and stash
        read_frame-ready tuples.  The python arm pays 2-4 awaits plus
        1-2 ctypes crc round-trips plus an interpreter copy per frame;
        this pays two foreign calls per BURST, and the GIL is released
        while the burst's bytes are checksummed and moved.

        A crc-failing frame mid-burst stashes its valid predecessors,
        consumes through the bad frame, and parks the BadFrame in
        _rx_error — read_frame raises it once the stash drains, exactly
        the slow path's fail-after-the-good-frames order."""
        r = self.reader
        pend = r._pending
        base = r._off
        end = len(pend)
        if end - base < _HDR.size or self._rx_error is not None:
            return
        crc_on = self.crc_enabled
        t0 = time.monotonic()
        voffs: list = []    # crc regions: offsets/lengths INTO pend
        vlens: list = []
        vwants: list = []
        expect: list = []   # (frame_index, is_blob) per crc region
        frames: list = []   # [type_id, version, seq, payload, length,
        #                      blob, fixed, verified, flags, src_off]
        pos = base
        error: Optional[BaseException] = None
        error_end = pos
        # one export for the whole drain: bytes(mv[a:b]) is a single
        # copy, where bytes(pend[a:b]) would copy twice (bytearray
        # slice, then bytes).  Released before _consume — a live export
        # blocks the bytearray resize.
        mv = memoryview(pend)
        try:
            while end - pos >= _HDR.size:
                length, type_id, version, flags, crc, seq = \
                    _HDR.unpack_from(pend, pos)
                if end - pos - _HDR.size < length:
                    break
                fstart = pos + _HDR.size
                fend = fstart + length
                blob = None
                verified = False
                src_off = -1
                if flags & FLAG_BLOB:
                    if _BLOB_PFX.size > length:
                        error = BadFrame(f"bad blob prefix on type {type_id}")
                        error_end = fend
                        break
                    plen, blob_crc = _BLOB_PFX.unpack_from(pend, fstart)
                    if _BLOB_PFX.size + plen > length:
                        # a corrupt plen would desync the stream — reject
                        # (the slow path refuses before any read; either
                        # way the frame is consumed and the session dies)
                        error = BadFrame(f"bad blob prefix on type {type_id}")
                        error_end = fend
                        break
                    hdr_end = fstart + _BLOB_PFX.size + plen
                    payload = bytes(mv[fstart + _BLOB_PFX.size:hdr_end])
                    blob_len = length - _BLOB_PFX.size - plen
                    if crc and crc_on:
                        # one region covers prefix+pickled: crc32c over the
                        # contiguous span == the chained tx-side crc
                        voffs.append(fstart)
                        vlens.append(hdr_end - fstart)
                        vwants.append(crc)
                        expect.append((len(frames), False))
                    cls = _MSG_TYPES.get(type_id)
                    dest = None
                    if cls is MLaneSegment and self.lane_group is not None \
                            and (flags & FLAG_FIXED) and blob_len \
                            and not (seq and seq <= self.in_seq):
                        # the in_seq guard: see the slow path — a replayed
                        # duplicate must not re-open reassembly state
                        try:
                            seg = _unpack_fixed(cls, payload, None)
                            dest = self.lane_group.frag_view(seg, blob_len)
                        except Exception:
                            dest = None
                    if dest is not None:
                        blob = dest
                    elif getattr(cls, "BLOB_VIEW_OK", False):
                        blob = memoryview(
                            np.empty(blob_len, dtype=np.uint8)).cast("B")
                    else:
                        blob = bytearray(blob_len)
                    src_off = hdr_end
                    if blob_crc and crc_on:
                        voffs.append(hdr_end)
                        vlens.append(blob_len)
                        vwants.append(blob_crc)
                        expect.append((len(frames), True))
                        verified = True
                else:
                    payload = bytes(mv[fstart:fend])
                    if crc and crc_on:
                        voffs.append(fstart)
                        vlens.append(length)
                        vwants.append(crc)
                        expect.append((len(frames), False))
                frames.append([type_id, version, seq, payload, length, blob,
                               bool(flags & FLAG_FIXED), verified, flags,
                               src_off])
                pos = fend
            if not frames and error is None:
                return
            perf = self.messenger.perf
            bad_idx = len(frames)
            if voffs:
                bad_region = self.wp.wirepy_verify_regions(
                    pend, voffs, vlens, vwants)
                perf.inc("native_rx_calls")
                perf.inc("native_bytes", sum(vlens))
                if bad_region >= 0:
                    fidx, is_blob = expect[bad_region]
                    if fidx < bad_idx:
                        bad_idx = fidx
                        error = BadFrame(
                            ("blob crc mismatch on type {}" if is_blob
                             else "crc mismatch on frame type {}").format(
                                frames[fidx][0]))
                        error_end = base + sum(
                            _HDR.size + f[4] for f in frames[:fidx + 1])
            consumed = pos - base
            soffs: list = []
            dsts: list = []
            for f in frames[:bad_idx]:
                if f[9] >= 0:
                    # verified-then-copied: a crc-refused frame never lands
                    # a byte (the slow path lands then kills; the failure
                    # surface — BadFrame, session death — is identical, the
                    # assembly buffer just stays cleaner)
                    soffs.append(f[9])
                    dsts.append(f[5])
                flags = f[8]
                payload = f[3]
                if flags & FLAG_COMPRESSED and not (flags & FLAG_BLOB):
                    payload = zlib.decompress(payload)
                self._rx_stash.append((f[0], f[1], f[2], payload, f[4],
                                       f[5], f[6], f[7]))
            if soffs:
                copied = self.wp.wirepy_scatter_from(pend, soffs, dsts)
                perf.inc("native_rx_calls")
                perf.inc("native_bytes", copied)
            if error is not None:
                self._rx_error = error
                consumed = error_end - base
        finally:
            mv.release()
        r._consume(consumed)
        rx_dt = time.monotonic() - t0
        perf.tinc("rx_io", rx_dt)
        perf.hinc("rx_io_us", rx_dt * 1e6)

    async def read_frame(self) -> Tuple[int, int, int, bytes, int, Any,
                                        bool, bool]:
        """Returns (type_id, version, seq, payload, cost, blob, fixed,
        blob_verified).  The dispatch throttle is charged `cost` bytes
        BEFORE the payload is read (receive-side backpressure, reference
        DispatchQueue throttle); the caller must put() cost back when
        done with the payload.  Blob frames (FLAG_BLOB) return the bulk
        bytes separately, checked against their own crc32c —
        ``blob_verified`` says that check actually ran (crc enabled and
        present), so handlers holding an app-level crc of the same bytes
        (MECSubWrite.chunk_crc) can skip their own verify pass."""
        stash = self._rx_stash
        if not stash and self.wp is not None \
                and isinstance(self.reader, FrameReceiver) \
                and (self.crc_fn is checksum or not self.crc_enabled):
            # native burst drain: every fully-buffered frame verifies in
            # one released-GIL call and lands pre-scattered in the stash
            self._rx_drain_native()
        if stash:
            (type_id, version, seq, payload, cost, blob, fixed,
             verified) = stash.popleft()
            await self.throttle.get(cost)
            self.messenger.perf.inc("rx_bytes", _HDR.size + cost)
            return (type_id, version, seq, payload, cost, blob, fixed,
                    verified)
        if self._rx_error is not None:
            err, self._rx_error = self._rx_error, None
            raise err
        if isinstance(self.reader, ShmConnEndpoint):
            return await self._read_frame_shm()
        hdr = await self.reader.readexactly(_HDR.size)
        length, type_id, version, flags, crc, seq = _HDR.unpack(hdr)
        cost = length
        await self.throttle.get(cost)
        # rx_io clock starts AFTER the header lands: the header read is
        # where idle between-message waiting parks, and folding that into
        # the per-frame number would drown the transfer cost it measures
        t_io = time.monotonic()
        blob_verified = False
        try:
            blob = None
            if flags & FLAG_BLOB:
                # the blob reads into ITS OWN buffer (FrameReceiver lands
                # bytes there directly — no giant payload slice)
                head = await self.reader.readexactly(_BLOB_PFX.size)
                plen, blob_crc = _BLOB_PFX.unpack_from(head)
                if _BLOB_PFX.size + plen > length:
                    # a corrupt plen would drive the blob read negative
                    # and desync the stream — reject before any read
                    raise BadFrame(f"bad blob prefix on type {type_id}")
                pickled = await self.reader.readexactly(plen)
                blob_len = length - _BLOB_PFX.size - plen
                cls = _MSG_TYPES.get(type_id)
                if getattr(cls, "BLOB_VIEW_OK", False) \
                        and isinstance(self.reader, FrameReceiver):
                    # lane-fragment reassembly seam: a striped segment's
                    # chunk lands DIRECTLY in its slice of the group's
                    # assembly buffer — no per-fragment staging buffer,
                    # no gather copy at reassembly time
                    dest = None
                    if cls is MLaneSegment and self.lane_group is not None \
                            and (flags & FLAG_FIXED) and blob_len \
                            and not (seq and seq <= self.in_seq):
                        # the in_seq guard keeps a REPLAYED duplicate
                        # (acked but re-sent across a lane revival) from
                        # re-creating reassembly state the serve loop is
                        # about to drop — that would leak one assembly
                        # buffer per replayed fragment
                        try:
                            seg = _unpack_fixed(cls, bytes(pickled), None)
                            dest = self.lane_group.frag_view(
                                seg, blob_len)
                        except Exception:
                            dest = None
                    if dest is not None:
                        blob = await self.reader.readexactly(blob_len,
                                                             into=dest)
                    else:
                        # store/decode-lane blob: land in an
                        # uninitialized buffer (no memset pass over the
                        # data volume)
                        blob = await self.reader.readexactly(blob_len,
                                                             uninit=True)
                else:
                    blob = await self.reader.readexactly(blob_len)
                if crc and self.crc_enabled \
                        and self.crc_fn(pickled, self.crc_fn(head)) != crc:
                    raise BadFrame(f"crc mismatch on frame type {type_id}")
                if blob_crc and self.crc_enabled:
                    if self.crc_fn(blob) != blob_crc:
                        raise BadFrame(f"blob crc mismatch on type {type_id}")
                    blob_verified = True
                payload = pickled
            else:
                payload = await self.reader.readexactly(length)
                if crc and self.crc_enabled \
                        and self.crc_fn(payload) != crc:
                    raise BadFrame(f"crc mismatch on frame type {type_id}")
                if flags & FLAG_COMPRESSED:
                    payload = zlib.decompress(payload)
        except BaseException:
            self.throttle.put(cost)
            raise
        perf = self.messenger.perf
        rx_dt = time.monotonic() - t_io
        perf.tinc("rx_io", rx_dt)
        perf.hinc("rx_io_us", rx_dt * 1e6)
        perf.inc("rx_bytes", _HDR.size + length)
        return (type_id, version, seq, payload, cost, blob,
                bool(flags & FLAG_FIXED), blob_verified)

    async def _read_frame_shm(self) -> Tuple[int, int, int, bytes, int,
                                             Any, bool, bool]:
        """Delegated-connection read_frame: the worker process already
        parsed, crc-verified (its own wirepath arm) and decompressed the
        frame; this side consumes the record from the shm ring.  Same
        contract as read_frame: throttle charged before the payload is
        copied out (and RETURNED on every error path — the r13 cost
        discipline extended to the process plane), lane fragments land
        straight in their slice of the group assembly buffer, EOF and
        crc failure surface exactly like the socket path's."""
        ep = self.reader
        kind, length = await ep.read_record_hdr()
        if kind == _SHM_REC_EOF:
            raise ConnectionResetError("delegated transport eof")
        if kind == _SHM_REC_ERR:
            raise BadFrame(
                (await ep.read_exact(length)).decode("utf-8", "replace"))
        if kind != _SHM_REC_FRAME:
            raise BadFrame(f"unknown shm record kind {kind}")
        fh = await ep.read_exact(_SHM_FRAME_HDR.size)
        type_id, version, rflags, seq, plen, blen = _SHM_FRAME_HDR.unpack(fh)
        cost = plen + blen
        await self.throttle.get(cost)
        t_io = time.monotonic()
        try:
            payload = await ep.read_exact(plen)
            blob = None
            if rflags & _SHM_RF_BLOB:
                cls = _MSG_TYPES.get(type_id)
                dest = None
                if cls is MLaneSegment and self.lane_group is not None \
                        and (rflags & _SHM_RF_FIXED) and blen \
                        and not (seq and seq <= self.in_seq):
                    # zero-copy reassembly across the process seam: the
                    # fragment's chunk reads shm -> its assembly slice
                    # (in_seq guard as in the socket paths — a replayed
                    # duplicate must not re-open reassembly state)
                    try:
                        seg = _unpack_fixed(cls, payload, None)
                        dest = self.lane_group.frag_view(seg, blen)
                    except Exception:
                        dest = None
                if dest is not None:
                    await ep.read_into(dest, blen)
                    blob = dest
                elif getattr(cls, "BLOB_VIEW_OK", False):
                    # rx -> install staging: page-aligned so a
                    # writeback install's h2d reads an aligned source
                    # (pinnable where pinned DMA exists) — the ring
                    # views native-gather straight into it, zero
                    # parent-side per-byte passes after the kernel
                    from ceph_tpu.rados.pagestore import install_staging

                    blob = install_staging(blen)
                    await ep.read_into(blob, blen)
                else:
                    blob = bytearray(blen)
                    await ep.read_into(blob, blen)
        except BaseException:
            self.throttle.put(cost)
            raise
        perf = self.messenger.perf
        rx_dt = time.monotonic() - t_io
        perf.tinc("rx_io", rx_dt)
        perf.hinc("rx_io_us", rx_dt * 1e6)
        perf.inc("rx_bytes", _HDR.size + cost)
        return (type_id, version, seq, payload, cost, blob,
                bool(rflags & _SHM_RF_FIXED),
                bool(rflags & _SHM_RF_VERIFIED))

    async def adopt_transport(self, reader, writer) -> None:
        """Adopt a fresh transport into this session and replay unacked
        frames (both directions of the reference's session reconnect:
        the initiator replays requests, the acceptor replays replies)."""
        old_writer = self.writer
        async with self._send_lock:
            self.reader = reader
            self.writer = writer
            self.closed = False
            self.transport_gen += 1
            # pre-verified frames from the DEAD transport: never
            # dispatched, never acked — the peer replays them on this
            # transport, and the in_seq dedupe floor keeps it exactly-once
            self._rx_stash.clear()
            self._rx_error = None
            try:
                old_writer.close()
            except Exception:
                pass
            replayed = 0
            with self.messenger.perf.time_avg("tx_io"):
                for _, data in list(self.unacked):
                    if isinstance(data, list):
                        self.writer.writelines(data)
                        replayed += sum(len(p) for p in data)
                    else:
                        self.writer.write(data)
                        replayed += len(data)
                await self.writer.drain()
            if replayed:
                self.messenger.perf.inc("tx_bytes", replayed)

    async def close(self, gen: Optional[int] = None) -> None:
        """Close the current transport.  With gen, only close if the
        transport hasn't been replaced since the caller observed it."""
        if gen is not None and gen != self.transport_gen:
            return
        if not self.closed:
            self.closed = True
            # senders parked on the pending flush window see the error
            # now; their frames replay from the unacked queue (lossless)
            self._fail_pending(ConnectionResetError("connection closed"))
            self._pin_replay_queue()
            self.writer.close()
            try:
                # bounded: wait_closed can block if the peer never reads
                await asyncio.wait_for(self.writer.wait_closed(), timeout=0.5)
            except Exception:
                pass


# -- multi-lane peer sessions ------------------------------------------------


class LaneGroup:
    """A striped peer session: N lane Connections plus the cross-lane
    sequencing/reassembly seam (module docstring "Sharded multi-reactor
    wire plane").  Duck-types the Connection surface daemons touch
    (send / close / peer / peer_name / auth metadata), so handlers reply
    through the group and replies stripe too.

    TX: LANE_STRIPE messages get the next connection-global ``gseq`` and
    round-robin across lanes 1..N-1 (lane 0 is control-only); blobs >=
    ``frag_min`` split into MLaneSegment fragments sent over ALL data
    lanes concurrently.  RX: every lane's serve loop pushes decoded
    messages here; gseq order is restored (holes park, a dead lane's
    replay fills them), fragments reassemble, and a single pump task on
    the messenger's home loop dispatches in order — one serialization
    point, so the ordering guarantee holds even when lanes live on
    different reactor threads.

    Throttle note: frames PARKED for a gap or a partial reassembly
    release their dispatch-throttle cost at park time (a dead lane may
    hold a gap open for seconds; holding budget hostage would stall the
    shard's other sessions) — parked memory is instead bounded by
    PARK_CAP, past which the reorderer force-drains in gseq order."""

    PARK_CAP = 8192  # parked frames before the reorderer force-drains
    # reassembly memory caps: fragment geometry is PEER-CLAIMED and read
    # before the frame crc can reject it, so the allocation it drives
    # must be bounded independently of the dispatch throttle (which only
    # accounts wire bytes).  Overflowing assemblies are refused and
    # counted (lane_frag_overflow); upper-layer resend recovers.
    FRAG_MAX_ASSEMBLIES = 64
    FRAG_MAX_BYTES = 256 << 20

    def __init__(self, messenger: "Messenger", addr: Tuple[str, int],
                 group_id: str, n_lanes: int, outbound: bool,
                 policy: Policy):
        self.messenger = messenger
        self.peer = tuple(addr)
        self.group_id = group_id
        self.n_lanes = max(2, int(n_lanes))
        self.outbound = outbound
        self.policy = policy
        self.lanes: List[Optional[Connection]] = [None] * self.n_lanes
        self.closed = False
        self.frag_min = int(_cget(messenger.conf, "ms_lane_stripe_min",
                                  1 << 20) or 0)
        self._tx_gseq = 0
        self._rr = 0
        # rx reorder + reassembly state, guarded for cross-reactor lanes
        self._lock = threading.Lock()
        self._rx_next = 1
        self._parked: Dict[int, Tuple[Any, Any]] = {}  # gseq -> (conn, msg)
        # gseq -> [seen, chunks, hdr, all_verified, buf, confirmed_ranges]
        self._frags: Dict[int, list] = {}
        self._frag_bytes = 0  # aggregate assembly-buffer bytes live
        self._fifo: Deque = collections.deque()  # (conn, msg, cost)
        self._pump_task: Optional[asyncio.Task] = None
        self._wake: Optional[asyncio.Event] = None
        self._reviving: set = set()

    # -- Connection surface ---------------------------------------------------

    def _lane0(self) -> Optional[Connection]:
        return self.lanes[0]

    @property
    def peer_name(self) -> str:
        c = self._lane0()
        return c.peer_name if c is not None else ""

    @property
    def auth_kind(self) -> str:
        c = self._lane0()
        return c.auth_kind if c is not None else "none"

    @property
    def auth_entity_type(self) -> str:
        c = self._lane0()
        return c.auth_entity_type if c is not None else ""

    def _lane(self, idx: int) -> Connection:
        conn = self.lanes[idx]
        if conn is None:
            conn = self.lanes[0]
        if conn is None:
            raise ConnectionResetError("lane group has no lanes")
        return conn

    @property
    def n_data_lanes(self) -> int:
        return self.n_lanes - 1

    async def send(self, msg: Any) -> None:
        if self.closed:
            raise ConnectionResetError("lane group closed")
        cls = type(msg)
        if not getattr(cls, "LANE_STRIPE", False):
            # control plane: lane 0, no gseq — never queued behind data
            await self.messenger._conn_send(self._lane(0), msg)
            return
        self._tx_gseq += 1
        gseq = self._tx_gseq
        msg.gseq = gseq
        blob_attr = getattr(cls, "BLOB_ATTR", None)
        blob = msg.__dict__.get(blob_attr) if blob_attr else None
        blob_len = len(blob) if blob is not None else 0
        if (self.frag_min and blob_len >= self.frag_min
                and self.n_data_lanes > 1):
            if await self._send_fragmented(msg, gseq):
                return
        idx = 1 + (gseq - 1) % self.n_data_lanes
        self._note_lane_tx(idx, blob_len)
        await self.messenger._conn_send(self._lane(idx), msg)

    def _note_lane_tx(self, idx: int, nbytes: int) -> None:
        p = self.messenger.perf
        p.ensure(f"tx_lane{idx}_msgs", desc=f"messages striped to lane {idx}")
        p.ensure(f"tx_lane{idx}_bytes", desc=f"blob bytes striped to lane {idx}")
        p.inc(f"tx_lane{idx}_msgs")
        p.inc(f"tx_lane{idx}_bytes", nbytes)

    async def _send_fragmented(self, msg: Any, gseq: int) -> bool:
        """Split a large blob across all data lanes as MLaneSegment
        frames sent concurrently; returns False when the message isn't
        actually blob-framed (caller falls back to whole-message)."""
        header, blob, fixed = encode_payload_parts(msg)
        if blob is None:
            return False
        if isinstance(blob, BufferList):
            segs, total = blob.segments, blob.nbytes
        else:
            segs, total = _norm_segments([blob])
        n = self.n_data_lanes
        base, extra = divmod(total, n)
        # walk the segment list once, carving n contiguous byte ranges
        sends = []
        seg_i, seg_off = 0, 0
        off = 0
        for i in range(n):
            want = base + (1 if i < extra else 0)
            pieces = []
            while want and seg_i < len(segs):
                seg = segs[seg_i]
                take = min(want, seg.nbytes - seg_off)
                pieces.append(seg[seg_off:seg_off + take])
                want -= take
                seg_off += take
                if seg_off >= seg.nbytes:
                    seg_i += 1
                    seg_off = 0
            chunk: Any = pieces[0] if len(pieces) == 1 else BufferList(pieces)
            frag = MLaneSegment(gseq=gseq, idx=i, nfrags=n, total=total,
                                off=off,
                                type_id=type(msg).TYPE_ID,
                                version=type(msg).VERSION,
                                fixed=bool(fixed),
                                header=header if i == 0 else b"",
                                chunk=chunk)
            lane_idx = 1 + (gseq + i - 1) % n
            self._note_lane_tx(lane_idx, len(chunk))
            sends.append(self.messenger._conn_send(
                self._lane(lane_idx), frag))
            off += len(chunk)
        self.messenger.perf.inc("lane_frag_tx", n)
        results = await asyncio.gather(*sends, return_exceptions=True)
        for r in results:
            if isinstance(r, BaseException):
                raise r
        return True

    # -- rx: reassembly + ordered dispatch ------------------------------------

    def rx_push(self, conn: Connection, msg: Any, cost: int) -> None:
        """Called by each lane's serve loop with a decoded message.
        Restores gseq order (parking holes), reassembles fragments, and
        feeds the ready run to the single dispatch pump.  Cost transfers
        with READY messages (released after dispatch); parked frames
        release theirs immediately (see class docstring)."""
        with self._lock:
            ready = self._ingest(conn, msg)
            first = True
            for c, m in ready:
                # THIS arrival's cost rides the first ready entry
                # (parked entries released theirs at park time; a
                # reassembled message inherits its completing
                # fragment's) — pump returns it, to the ARRIVAL's shard
                # throttle, after dispatch
                self._fifo.append((c, m, cost if first else 0, conn))
                first = False
        if not ready and cost:
            self.messenger._throttle_put(conn, cost)
        if ready:
            self._kick_pump()

    def _ingest(self, conn: Connection, msg: Any):
        """Under _lock: returns the in-order run of (conn, msg) this
        arrival unlocks ([] when it parked)."""
        if type(msg).__name__ == "MLaneSegment":
            msg = self._ingest_fragment(conn, msg)
            if msg is None:
                return []
        g = getattr(msg, "gseq", 0) or 0
        if g == 0 or g < self._rx_next:
            # control-plane (no gseq) dispatches immediately; g <
            # expected is a cross-lane duplicate (dup injection, replay
            # overlap) the application layer's reqid dedupe absorbs
            return [(conn, msg)]
        if g > self._rx_next:
            self._parked[g] = (conn, msg)
            self.messenger.perf.inc("lane_rx_parked")
            if len(self._parked) > self.PARK_CAP:
                # liveness backstop: force-drain in gseq order rather
                # than grow without bound (a hole this old means the
                # owning lane session is gone for good)
                self.messenger.dout(
                    1, f"lane group {self.group_id[:8]}: PARK_CAP "
                       f"({self.PARK_CAP}) exceeded at gseq hole "
                       f"{self._rx_next}; force-draining reorder buffer")
                keys = sorted(self._parked)
                out = [self._parked.pop(k) for k in keys]
                self._rx_next = keys[-1] + 1
                return out
            return []
        out = [(conn, msg)]
        self._rx_next += 1
        while self._rx_next in self._parked:
            out.append(self._parked.pop(self._rx_next))
            self._rx_next += 1
        return out

    def frag_view(self, seg: Any, blob_len: int):
        """Reassembly destination for one inbound MLaneSegment: the
        [off, off+blob_len) slice of gseq's assembly buffer, so the
        frame reader lands the bytes in place (zero-copy reassembly).
        None when the segment's geometry doesn't fit (corrupt/hostile
        frame: the caller falls back to a private buffer and the normal
        bounds-checked ingest)."""
        if (seg.total <= 0 or seg.total > (1 << 31) or seg.off < 0
                or seg.off + blob_len > seg.total
                or not (0 <= seg.idx < seg.nfrags <= 4096)):
            # implausible geometry (corrupt/hostile frame): refuse the
            # assembly allocation before the crc check can reject it
            return None
        with self._lock:
            st = self._frag_state(seg.gseq, seg.nfrags, seg.total)
            if st is None:
                return None
            if self._range_conflict(st, seg.idx, seg.off, blob_len):
                # overlaps a CONFIRMED fragment (or re-claims a consumed
                # idx): land in a private buffer instead — the crc check
                # will kill the corrupt frame without stomping verified
                # bytes, and a mere duplicate is dropped by _ingest
                return None
            return memoryview(st[4]).cast("B")[seg.off:seg.off + blob_len]

    def _frag_state(self, gseq: int, nfrags: int, total: int):
        """Under _lock: the reassembly entry for gseq, created if absent
        and the caps allow; None when refused (stale gseq, geometry
        mismatch, or the FRAG_MAX_* memory bounds)."""
        st = self._frags.get(gseq)
        if st is not None:
            return st if len(st[4]) == total else None
        if 0 < gseq < self._rx_next:
            # gseq already dispatched: a stale duplicate must not
            # re-open a completed (deleted) assembly
            return None
        if (len(self._frags) >= self.FRAG_MAX_ASSEMBLIES
                or self._frag_bytes + total > self.FRAG_MAX_BYTES):
            self.messenger.perf.inc("lane_frag_overflow")
            return None
        st = self._frags[gseq] = [0, [None] * nfrags, b"", True,
                                  np.empty(total, dtype=np.uint8), {}]
        self._frag_bytes += total
        return st

    @staticmethod
    def _range_conflict(st, idx: int, off: int, length: int) -> bool:
        """True when [off, off+length) overlaps a CONFIRMED fragment's
        bytes (or idx itself is already confirmed) — the guard that
        keeps a corrupt-geometry frame, whose blob lands BEFORE its crc
        is checked, from stomping verified regions of the assembly."""
        ranges = st[5]
        if idx in ranges:
            return True
        end = off + length
        for o, ln in ranges.values():
            if off < o + ln and o < end:
                return True
        return False

    def _frag_drop(self, gseq: int) -> None:
        st = self._frags.pop(gseq, None)
        if st is not None:
            self._frag_bytes -= len(st[4])

    def _ingest_fragment(self, conn: Connection, frag: Any):
        """Collect one MLaneSegment; returns the reassembled original
        message when complete, else None."""
        if frag.total <= 0 or frag.total > (1 << 31) \
                or not (0 < frag.nfrags <= 4096):
            return None
        st = self._frag_state(frag.gseq, frag.nfrags, frag.total)
        if st is None:
            return None
        seen, chunks, _hdr, ok, buf, ranges = st
        if 0 <= frag.idx < len(chunks) and chunks[frag.idx] is None:
            chunk = frag.chunk
            in_place = (isinstance(chunk, memoryview)
                        and chunk.obj is buf)
            nbytes = len(chunk)
            if not in_place:
                if frag.off < 0 or frag.off + nbytes > len(buf) \
                        or self._range_conflict(st, frag.idx, frag.off,
                                                nbytes):
                    # corrupt geometry: drop the fragment WITHOUT
                    # consuming its slot — a valid retransmission of
                    # this index must still be able to land
                    return None
                mv = chunk if isinstance(chunk, memoryview) \
                    else memoryview(as_bytes(chunk)
                                    if isinstance(chunk, BufferList)
                                    else chunk)
                if mv.ndim != 1 or mv.itemsize != 1:
                    mv = mv.cast("B")
                # single-fragment landing: the bounds/overlap guard above
                # already enforced everything the C-side guard would, and
                # one slice-assign is cheaper than a ctypes segment-list
                # round-trip (batched fragments ride the native drain)
                view = memoryview(buf).cast("B")
                view[frag.off:frag.off + mv.nbytes] = mv
            chunks[frag.idx] = True
            ranges[frag.idx] = (frag.off, nbytes)
            st[0] = seen = seen + 1
            if frag.header:
                st[2] = frag.header
            if not getattr(frag, "_wire_verified", False):
                st[3] = False
        if seen < len(chunks):
            return None
        self._frag_drop(frag.gseq)
        self.messenger.perf.inc("lane_frag_rx", len(chunks))
        msg = decode_message(frag.type_id, frag.version,
                             bytes(st[2]) if isinstance(st[2], (bytearray,
                                                                memoryview))
                             else st[2],
                             memoryview(st[4]).cast("B"), bool(frag.fixed))
        if st[3]:
            msg._wire_verified = True
        msg.gseq = frag.gseq
        return msg

    def _kick_pump(self) -> None:
        home = self.messenger.home_loop
        if home is None:
            try:
                home = asyncio.get_running_loop()
            except RuntimeError:
                return
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if running is home:
            self._ensure_pump()
        else:
            home.call_soon_threadsafe(self._ensure_pump)

    def _ensure_pump(self) -> None:
        if self._wake is None:
            self._wake = asyncio.Event()
        self._wake.set()
        if self._pump_task is None or self._pump_task.done():
            m = self.messenger
            self._pump_task = asyncio.get_running_loop().create_task(
                self._pump())
            m._tasks.add(self._pump_task)
            self._pump_task.add_done_callback(m._tasks.discard)

    async def _pump(self) -> None:
        """The group's single ordered dispatcher, on the home loop."""
        m = self.messenger
        while not self.closed and not m._shutdown:
            await self._wake.wait()
            self._wake.clear()
            while self._fifo and not self.closed and not m._shutdown:
                await self._pump_once(m)

    async def _pump_once(self, m: "Messenger") -> None:
        batch: list = []
        costs: list = []
        with self._lock:
            while self._fifo and len(batch) < m.RX_BATCH_MSGS:
                conn, msg, cost, cost_conn = self._fifo.popleft()
                batch.append((conn, msg))
                if cost:
                    costs.append((cost_conn, cost))
        if not batch:
            return
        try:
            if m.group_dispatcher is not None \
                    and (len(batch) > 1 or m.dispatcher is None):
                await m.group_dispatcher(self, [msg for _, msg in batch])
            elif m.dispatcher is not None:
                for _, msg in batch:
                    try:
                        await m.dispatcher(self, msg)
                    except (asyncio.CancelledError, GeneratorExit):
                        raise
                    except Exception:
                        traceback.print_exc()
        except (asyncio.CancelledError, GeneratorExit):
            raise
        except Exception:
            traceback.print_exc()
        finally:
            for conn, cost in costs:
                m._throttle_put(conn, cost)

    # -- lifecycle ------------------------------------------------------------

    def bind_lane(self, conn: Connection, lane: int) -> None:
        if 0 <= lane < self.n_lanes:
            self.lanes[lane] = conn
        conn.lane_group = self
        conn.lane_idx = lane

    async def close(self) -> None:
        self.closed = True
        for conn in self.lanes:
            if conn is not None:
                await self.messenger._conn_close(conn)
        if self._pump_task is not None:
            self._pump_task.cancel()
        # undispatched fifo entries still hold dispatch-throttle budget
        # (pump releases after dispatch): return it now or the shard's
        # receive path leaks it permanently under group churn
        with self._lock:
            entries = list(self._fifo)
            self._fifo.clear()
            self._parked.clear()
            self._frags.clear()
            self._frag_bytes = 0
        for _c, _m, cost, cost_conn in entries:
            if cost:
                self.messenger._throttle_put(cost_conn, cost)

    def dump(self) -> Dict[str, Any]:
        lanes = []
        for i, c in enumerate(self.lanes):
            if c is None:
                lanes.append({"lane": i, "state": "absent"})
                continue
            lanes.append({
                "lane": i, "state": "closed" if c.closed else "open",
                "control": i == 0,
                "outbox_frames": c._outbox_frames,
                "outbox_bytes": c._outbox_bytes,
                "unacked": len(c.unacked),
                "out_seq": c.out_seq, "in_seq": c.in_seq,
                "reactor": c.reactor.index if c.reactor is not None
                else None,
                # process mode: worker pid + per-shard shm-ring depths
                "shm": (c.reader.dump()
                        if isinstance(c.reader, ShmConnEndpoint)
                        else None)})
        with self._lock:
            parked = len(self._parked)
            fifo = len(self._fifo)
            frags = len(self._frags)
        return {"peer": list(self.peer), "group": self.group_id,
                "outbound": self.outbound, "n_lanes": self.n_lanes,
                "tx_gseq": self._tx_gseq, "rx_next": self._rx_next,
                "rx_parked": parked, "rx_fifo": fifo,
                "reassembling": frags, "lanes": lanes}


# -- messenger ---------------------------------------------------------------


class Messenger:
    """One per daemon.  dispatcher(conn, msg) is awaited per message
    (fast-dispatch style); receive-side bytes ride a dispatch throttle."""

    def __init__(self, name: str, conf: Optional[Any] = None,
                 entity_type: str = "client"):
        self.name = name
        self.conf = conf if conf is not None else {}
        self.entity_type = entity_type
        # resolve the frame checksum NOW (may g++-build the native
        # library, seconds): daemon construction, never the hot path
        checksum_kind()
        # native wirepath arm for this messenger (utils/wirepath.py):
        # the bridge module when the native hot loop resolved AND the
        # config allows it, else None (pure-python arm).  Resolved here
        # for the same reason as the checksum — never on the hot path.
        self.wirepath = (_wirepath.impl()
                         if bool(_cget(self.conf, "ms_wirepath_native",
                                       True)) else None)
        # the `wire` counter set (framing vs socket-io split; schema in
        # _build_wire_perf) — owning daemons add it to their collection
        self.perf = _build_wire_perf()
        self.perf.set("wirepath_kind", 1 if self.wirepath is not None
                      else 0)
        # gauge survives `perf reset` (bench/tests zero the window's
        # counters; the ARM doesn't change) — the resync hook restores
        # it, the service-plane gauge discipline
        self.perf.resync = lambda: self.perf.set(
            "wirepath_kind", 1 if self.wirepath is not None else 0)
        # per-daemon log (debug_ms levels): daemons attach their
        # Context's Log; raw messengers stay silent.  Per-frame douts are
        # call-site guarded with log.wants("ms", 20) so a disabled level
        # costs one cached compare on the hot path — turning up debug_ms
        # at runtime (asok / `ceph tell ... config set`) is the
        # diagnostic workflow.
        self.log = None
        self.dispatcher: Optional[Callable] = None
        # optional group-dispatch hook: group_dispatcher(conn, msgs) gets
        # a whole rx batch (frames that were already buffered) so the
        # daemon can hand stripe groups to the EC tier in one submit and
        # coalesce replies; falls back to per-message dispatcher when None
        self.group_dispatcher: Optional[Callable] = None
        self.server: Optional[asyncio.AbstractServer] = None
        self.addr: Optional[Tuple[str, int]] = None
        self._conns: Dict[Tuple[str, int], Connection] = {}
        self._conn_locks: Dict[Tuple[str, int], asyncio.Lock] = {}
        self._tasks: set = set()
        # reference defaults: clients are lossy, daemon peers lossless
        self.policies: Dict[str, Policy] = {
            "client": Policy.lossy_client(),
            "osd": Policy.lossless_peer(),
            "mon": Policy.lossless_peer(),
            "mgr": Policy.lossless_peer(),
        }
        self.dispatch_throttle = Throttle(
            f"{name}-dispatch", _cget(self.conf, "ms_dispatch_throttle_bytes", 100 << 20)
        )
        self._shutdown = False
        # cephx-lite state: this entity's service ticket + session key
        # (initiator side) and the rotating-secret keyring used to
        # validate presented tickets (acceptor side, daemons only)
        self.ticket: Optional[bytes] = None
        self.session_key: Optional[bytes] = None
        self.keyring = None  # Optional[TicketKeyring]
        # async callable: re-fetch rotating secrets on a validation miss
        # (a ticket sealed under a JUST-rotated secret must not be
        # refused until the periodic refresh happens to run)
        self.keyring_refresh: Optional[Callable] = None
        # session id -> session Connection, LRU-capped (peers come and go)
        self._sessions: "collections.OrderedDict[str, Connection]" = (
            collections.OrderedDict()
        )
        # colocated-daemon fast dispatch (LocalConnection): opt-in, and
        # only meaningful when BOTH endpoints run with it on
        self._local_fastpath = bool(
            _cget(self.conf, "ms_local_fastpath", False))
        self._local_conns: Dict[Tuple[str, int], LocalConnection] = {}
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        # -- sharded multi-reactor wire plane (module docstring) -------------
        # the daemon's dispatch loop; reactor-owned serve loops hop here
        self.home_loop: Optional[asyncio.AbstractEventLoop] = None
        # reactor substrate: thread shards (r13) or forked worker
        # PROCESSES (ms_reactor_mode=process / CEPH_TPU_REACTOR=) whose
        # sockets run on truly independent cores, frames crossing via
        # shm rings into the home-loop dispatch pump (reactor_proc.py)
        mode = str(_cget(self.conf, "ms_reactor_mode", "thread")
                   or "thread").strip().lower()
        env_mode = os.environ.get("CEPH_TPU_REACTOR", "").strip().lower()
        if env_mode in ("thread", "process"):
            mode = env_mode
        elif env_mode in ("0", "off"):
            mode = "thread"
        if mode not in ("thread", "process"):
            mode = "thread"
        if mode == "process" and not hasattr(os, "fork"):
            mode = "thread"  # non-posix host: degrade, never fail
        self.reactor_mode = mode
        n_reactors = int(_cget(self.conf, "ms_async_op_threads", 0) or 0)
        if mode == "process" and n_reactors <= 0:
            n_reactors = 2  # process mode implies a pool
        self.reactors: Optional[ReactorPool] = (
            ReactorPool(name, n_reactors, mode=mode,
                        use_native=self.wirepath is not None)
            if n_reactors > 0 else None)
        self.shm_ring_bytes = int(
            _cget(self.conf, "ms_shm_ring_bytes", 4 << 20) or (4 << 20))
        self._conn_ids = itertools.count(1)
        # worker-process counters fold into this set at dump time
        self.perf.presample = self._refresh_proc_perf
        self.lanes_per_peer = max(1, int(
            _cget(self.conf, "ms_lanes_per_peer", 1) or 1))
        # colocated ring transport: negotiated at connect time; never
        # engaged under secure mode, configured auth, or socket-fault
        # injection (those configurations exist to exercise the real
        # wire, and authorization decisions key on how a peer proved
        # itself over it)
        self._ring_ok = bool(
            _cget(self.conf, "ms_colocated_ring", False)
            and not _cget(self.conf, "ms_secure_mode", False)
            and not _cget(self.conf, "ms_auth_secret", "")
            and not _cget(self.conf, "auth_cephx", False)
            and not _cget(self.conf, "ms_inject_socket_failures", 0))
        # live ring connections (both directions), for dump_reactors
        # and shutdown — acceptor-side rings are not in _conns
        self._ring_conns: list = []
        # acceptor-side lane groups, keyed by group id (LRU-capped with
        # the session table); guarded — lanes may land on reactor loops
        self._lane_groups: "collections.OrderedDict[str, LaneGroup]" = (
            collections.OrderedDict())
        self._lane_lock = threading.Lock()
        self._sessions_lock = threading.Lock()
        # per-reactor-loop dispatch throttles (Throttle futures are
        # loop-bound; backpressure is per shard)
        self._loop_throttles: Dict[Any, Throttle] = {}

    def policy_for(self, peer_type: str) -> Policy:
        return self.policies.get(peer_type, Policy.lossy_client())

    def dout(self, level: int, message: str) -> None:
        """debug_ms-leveled dout into the owning daemon's log (no-op on
        raw messengers).  Hot paths guard with ``self.log.wants`` first."""
        log = self.log
        if log is not None:
            log.dout("ms", level, message)

    # -- cross-loop plumbing (reactor plane) ---------------------------------

    def _throttle_here(self) -> Throttle:
        """Dispatch throttle for the CURRENT loop: the messenger-wide
        one on the home loop, a per-worker one on reactor loops."""
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return self.dispatch_throttle
        if self.home_loop is None or loop is self.home_loop:
            return self.dispatch_throttle
        t = self._loop_throttles.get(loop)
        if t is None:
            t = self._loop_throttles[loop] = Throttle(
                f"{self.name}-dispatch-shard",
                _cget(self.conf, "ms_dispatch_throttle_bytes", 100 << 20))
        return t

    def _throttle_put(self, conn, cost: int) -> None:
        """Return dispatch-throttle budget to ``conn``'s shard, from any
        loop (Throttle wakeups are loop-bound futures)."""
        if not cost:
            return
        loop = getattr(conn, "loop", None)
        throttle = getattr(conn, "throttle", None)
        if throttle is None:
            return
        try:
            here = asyncio.get_running_loop()
        except RuntimeError:
            here = None
        if loop is None or loop is here or loop.is_closed():
            throttle.put(cost)
        else:
            loop.call_soon_threadsafe(throttle.put, cost)

    async def _conn_send(self, conn, msg: Any) -> None:
        """Send on a connection that may live on another loop (its
        reactor shard): hop with run_coroutine_threadsafe, no-op hop for
        home-loop connections."""
        loop = getattr(conn, "loop", None)
        if loop is None or loop is asyncio.get_running_loop():
            await conn.send(msg)
            return
        fut = asyncio.run_coroutine_threadsafe(conn.send(msg), loop)
        await asyncio.wrap_future(fut)

    async def _conn_close(self, conn) -> None:
        loop = getattr(conn, "loop", None)
        try:
            here = asyncio.get_running_loop()
        except RuntimeError:
            here = None
        if loop is None or loop is here or loop.is_closed():
            await conn.close()
            return
        fut = asyncio.run_coroutine_threadsafe(conn.close(), loop)
        try:
            await asyncio.wait_for(asyncio.wrap_future(fut), timeout=1.0)
        except Exception:
            pass

    async def _dispatch_home(self, conn, msg: Any) -> None:
        """Invoke the daemon dispatcher on the HOME loop (daemon state is
        single-loop); serve loops on reactor shards hop here."""
        if self.dispatcher is None:
            return
        if self.home_loop is None \
                or self.home_loop is asyncio.get_running_loop():
            await self.dispatcher(conn, msg)
            return
        fut = asyncio.run_coroutine_threadsafe(
            self.dispatcher(conn, msg), self.home_loop)
        await asyncio.wrap_future(fut)

    async def _dispatch_group_home(self, conn, msgs: list) -> None:
        if self.group_dispatcher is None:
            return
        if self.home_loop is None \
                or self.home_loop is asyncio.get_running_loop():
            await self.group_dispatcher(conn, msgs)
            return
        fut = asyncio.run_coroutine_threadsafe(
            self.group_dispatcher(conn, msgs), self.home_loop)
        await asyncio.wrap_future(fut)

    # -- process-sharded reactor plane (delegation seam) ---------------------

    def _delegatable(self) -> bool:
        return (self.reactors is not None
                and self.reactors.mode == "process")

    def _crc_mode_for(self, crc_fn, crc_enabled: bool) -> str:
        if not crc_enabled:
            return "off"
        return "shared" if crc_fn is checksum else "zlib"

    def _delegate_transport(self, reader, writer, worker, crc_fn,
                            crc_enabled: bool):
        """Hand a live plaintext transport to a reactor worker PROCESS:
        extract the raw socket + any already-buffered rx bytes, build
        the shm ring pair, send the fd over the worker's ctrl channel,
        and close the parent's copy (the worker's dup now OWNS the
        socket — worker death = transport death, the revival signal).
        Returns (reader, writer) shm endpoints, or None when this
        transport can't delegate (secure stream, no raw socket, pending
        tx bytes, worker unavailable) — the caller keeps the in-process
        transport, a graceful fallback never an error."""
        pool = self.reactors
        if not self._delegatable() or not pool.ensure_worker(worker):
            return None
        # raw socket extraction (plaintext only — a SecureStream has no
        # transport to hand across; delegation happens below the AES
        # layer or not at all)
        if isinstance(writer, CorkedWriter):
            transport, sock = writer._transport, writer._sock
            if writer._buffered:
                return None  # unsent segments would interleave
        elif isinstance(writer, asyncio.StreamWriter):
            transport = writer.transport
            sock = transport.get_extra_info("socket") \
                if transport is not None else None
            sock = getattr(sock, "_sock", sock)
        else:
            return None
        if transport is None or sock is None or transport.is_closing():
            return None
        try:
            if transport.get_write_buffer_size() != 0:
                return None  # buffered tx would race the worker's writes
        except Exception:
            return None
        # leftover rx bytes: captured only after the ctrl handoff
        # succeeds, so a failed delegation leaves the reader intact
        if isinstance(reader, FrameReceiver):
            leftover = bytes(memoryview(reader._pending)[reader._off:])
        elif isinstance(reader, asyncio.StreamReader):
            leftover = bytes(reader._buffer)
        else:
            return None
        try:
            transport.pause_reading()
        except Exception:
            pass
        conn_id = next(self._conn_ids)
        try:
            ep = delegate_socket(worker, conn_id, sock.fileno(), leftover,
                                 self.shm_ring_bytes,
                                 self._crc_mode_for(crc_fn, crc_enabled),
                                 wp=self.wirepath, perf=self.perf)
        except OSError:
            ep = None
        if ep is None:
            try:
                transport.resume_reading()
            except Exception:
                pass
            return None
        # handoff complete: the worker owns a dup of the fd.  Clear the
        # captured bytes from the parent reader and close our copy.
        if isinstance(reader, FrameReceiver):
            reader._pending.clear()
            reader._off = 0
        else:
            reader._buffer.clear()
        if isinstance(writer, CorkedWriter):
            writer._detach()
        try:
            transport.close()
        except Exception:
            pass
        # proc_delegated_conns has ONE owner: the presample refresh
        # (worker.sockets tally) — no inc here, two sources would drift
        self.dout(4, f"conn {conn_id} delegated to reactor worker "
                     f"{worker.index} (pid {worker.pid})")
        return ep, ep

    async def _delegate_conn(self, conn: "Connection", lane: int) -> None:
        """Delegate a LIVE connection (acceptor side, right after its
        MLaneHello bound it into a lane group).  Runs under the send
        lock so an in-flight flush window can't race the writer swap;
        the caller is the connection's own serve loop, so no reader
        race exists."""
        if isinstance(conn.reader, ShmConnEndpoint) or conn.closed:
            return
        worker = self.reactors.worker_for(conn.peer, lane)
        async with conn._send_lock:
            if conn.closed or isinstance(conn.reader, ShmConnEndpoint):
                return
            pair = self._delegate_transport(conn.reader, conn.writer,
                                            worker, conn.crc_fn,
                                            conn.crc_enabled)
            if pair is None:
                return
            conn.reader, conn.writer = pair
            conn.shm_worker = worker

    def _accepted_fd_cb(self, fd: int, worker) -> None:
        """A worker's accept loop forwarded a fresh inbound socket: run
        the normal handshake/accept path on the home loop (auth,
        session resume and ring negotiation need parent state)."""
        loop = self.home_loop
        if loop is None or loop.is_closed() or self._shutdown:
            try:
                os.close(fd)
            except OSError:
                pass
            return

        async def _adopt():
            try:
                sock = socket_mod.socket(fileno=fd)
            except OSError:
                try:
                    os.close(fd)
                except OSError:
                    pass
                return
            try:
                sock.setblocking(False)
                reader, writer = await asyncio.open_connection(sock=sock)
            except OSError:
                # close via the OBJECT (it owns the fd now): a raw
                # os.close here would double-close a number the socket
                # destructor closes again later — onto whoever reused it
                sock.close()
                return
            await self._accept(reader, writer)

        def _spawn():
            # runs ON the home loop (call_soon_threadsafe below)
            t = asyncio.get_running_loop().create_task(_adopt())
            self._tasks.add(t)
            t.add_done_callback(self._tasks.discard)

        loop.call_soon_threadsafe(_spawn)

    def _refresh_proc_perf(self) -> None:
        """perf presample hook: fold the worker processes' counter
        blocks into the wire set so the daemon's `perf dump` (and with
        it /metrics and BENCH) reports the WHOLE reactor plane."""
        pool = self.reactors
        if pool is None or pool.mode != "process":
            return
        agg = pool.counters_sum()
        if not agg:
            return
        p = self.perf
        p.set("proc_workers",
              sum(1 for w in pool.workers if w.is_alive()))
        p.set("proc_delegated_conns",
              sum(w.sockets for w in pool.workers))
        p.set("proc_worker_respawns",
              sum(w.respawns for w in pool.workers))
        p.set("proc_rx_frames", agg.get("rx_frames", 0))
        p.set("proc_rx_bytes", agg.get("rx_bytes", 0))
        p.set("proc_tx_calls", agg.get("tx_calls", 0))
        p.set("proc_tx_bytes", agg.get("tx_bytes", 0))
        p.set("proc_native_rx_calls", agg.get("native_rx_calls", 0))
        p.set("proc_native_tx_calls", agg.get("native_tx_calls", 0))
        p.set("proc_native_bytes", agg.get("native_bytes", 0))

    # -- wire accounting -----------------------------------------------------

    def _note_tx(self, type_name: str, nbytes: int, framing_s: float) -> None:
        # tx_bytes is NOT counted here: _write_raw owns it, so acks and
        # session replays land in the socket totals too
        p = self.perf
        p.inc("tx_msgs")
        p.tinc("tx_framing", framing_s)
        p.ensure(f"tx_{type_name}", desc=f"{type_name} messages sent")
        p.ensure(f"tx_bytes_{type_name}", desc=f"{type_name} bytes sent")
        p.inc(f"tx_{type_name}")
        p.inc(f"tx_bytes_{type_name}", nbytes)

    def _note_rx(self, type_name: str, nbytes: int, framing_s: float) -> None:
        p = self.perf
        p.inc("rx_msgs")
        p.tinc("rx_framing", framing_s)
        p.ensure(f"rx_{type_name}", desc=f"{type_name} messages dispatched")
        p.ensure(f"rx_bytes_{type_name}",
                 desc=f"{type_name} bytes received")
        p.inc(f"rx_{type_name}")
        p.inc(f"rx_bytes_{type_name}", nbytes)

    # -- handshake -----------------------------------------------------------

    def _auth_tag(self, nonce: bytes, key: Optional[bytes] = None,
                  transcript: bytes = b"") -> str:
        """HMAC proof over a handshake nonce + negotiated-mode transcript:
        with a ticket session key when one is in play (cephx role), else
        the cluster bootstrap secret.  Binding the transcript (the secure
        flags both sides sent) into the tag makes mode-stripping by an
        active MITM detectable — the reference binds the negotiated mode
        into msgr2's signed handshake payload the same way."""
        if key is not None:
            return hmac.new(key, nonce + transcript, hashlib.sha256).hexdigest()
        secret = str(_cget(self.conf, "ms_auth_secret", "") or "")
        if not secret:
            return ""
        return hmac.new(secret.encode(), nonce + transcript,
                        hashlib.sha256).hexdigest()

    @staticmethod
    def _mode_transcript(initiator_secure: bool, acceptor_secure: bool) -> bytes:
        return f"|mode:i{int(bool(initiator_secure))}a{int(bool(acceptor_secure))}".encode()

    def _secure_key(self, session_key: Optional[bytes],
                    nonce_a: bytes, nonce_b: bytes) -> Optional[bytes]:
        """Key material for AES-GCM on-wire mode: the ticket session key,
        else a key derived from the cluster secret and both nonces."""
        if session_key is not None:
            return session_key
        secret = str(_cget(self.conf, "ms_auth_secret", "") or "")
        if not secret:
            return None
        return hmac.new(secret.encode(), b"onwire" + nonce_a + nonce_b,
                        hashlib.sha256).digest()

    def _wrap_secure(self, reader, writer, key: bytes):
        from ceph_tpu.rados.auth import SecureStream

        s = SecureStream(reader, writer, key)
        return s, s

    async def _handshake_out(self, reader, writer, lossless: bool,
                             session_id: str, want_ring: bool = False):
        """Returns (peer_name, resumed, peer_ckind, lanes_ok, ring_id,
        reader, writer) — the pair is AES-GCM wrapped when secure mode
        was negotiated.  ``lanes_ok`` says the acceptor understands the
        multi-lane plane (old peers fall back to one lane); ``ring_id``
        is non-empty when the acceptor offered a colocated in-process
        ring (its fin carries the id; see reactor.py)."""
        secure_want = bool(_cget(self.conf, "ms_secure_mode", False))
        writer.write(BANNER)
        nonce = random.randbytes(16)
        hello = {"name": self.name, "type": self.entity_type,
                 "nonce": nonce.hex(), "auth": "",
                 "session": session_id, "lossless": lossless,
                 "secure": secure_want, "ckind": checksum_kind(),
                 "proc": PROC_TOKEN, "ring": bool(want_ring),
                 "lanes_ok": True}
        if self.ticket is not None:
            hello["ticket"] = self.ticket.hex()
        writer.write(json.dumps(hello).encode() + b"\n")
        await writer.drain()
        banner = await reader.readexactly(len(BANNER))
        if banner != BANNER:
            raise BadFrame("bad banner from peer")
        peer_hello = json.loads(await reader.readline())
        key = self.session_key if self.ticket is not None else None
        # both secure flags ride the HMAC material: a stripped flag makes
        # the tags disagree instead of silently downgrading to plaintext
        transcript = self._mode_transcript(secure_want,
                                           peer_hello.get("secure", False))
        # acceptor proves knowledge of the secret (or of OUR ticket's
        # session key, which only rotating-secret holders can open) by
        # tagging OUR nonce
        expect = self._auth_tag(nonce, key, transcript)
        if expect and not hmac.compare_digest(peer_hello.get("auth", ""), expect):
            raise PermissionError("peer failed auth (bad cluster secret)")
        # then we prove ourselves by tagging THEIR nonce
        try:
            their_nonce = bytes.fromhex(peer_hello.get("nonce", ""))
        except ValueError:
            raise BadFrame("garbled nonce in peer hello") from None
        tag = self._auth_tag(their_nonce, key, transcript)
        writer.write(json.dumps({"auth": tag}).encode() + b"\n")
        await writer.drain()
        fin = json.loads(await reader.readline())
        if not fin.get("ok", False):
            raise PermissionError("peer rejected our auth")
        if secure_want:
            # ms_secure_mode is a REQUIREMENT, not a preference: ending up
            # on plaintext (peer refused, or no key material to derive a
            # session key from) is a failed connection, never a downgrade
            skey = (self._secure_key(key, nonce, their_nonce)
                    if peer_hello.get("secure") else None)
            if skey is None:
                raise PermissionError(
                    "ms_secure_mode set but connection would be plaintext")
            reader, writer = self._wrap_secure(reader, writer, skey)
        return (peer_hello.get("name", ""), bool(peer_hello.get("resumed")),
                peer_hello.get("ckind", "zlib"),
                bool(peer_hello.get("lanes_ok")),
                str(fin.get("ring", "") or ""), reader, writer)

    async def _handshake_in(self, reader, writer):
        """Returns (peer_name, peer_type, session, lossless, auth_kind,
        auth_entity_type, reader, writer) — the pair is AES-GCM wrapped
        when secure mode was negotiated.  ``auth_kind`` records HOW the
        peer proved itself ("ticket", "secret", or "none"): authorization
        decisions (e.g. who may fetch the rotating service secrets) key on
        it, not on the peer's self-declared type."""
        secure_want = bool(_cget(self.conf, "ms_secure_mode", False))
        banner = await reader.readexactly(len(BANNER))
        if banner != BANNER:
            raise BadFrame("bad banner from peer")
        peer_hello = json.loads(await reader.readline())
        writer.write(BANNER)
        nonce = random.randbytes(16)
        their_nonce = bytes.fromhex(peer_hello.get("nonce", ""))
        key: Optional[bytes] = None
        auth_kind = "none"
        auth_entity_type = ""
        ticket_hex = peer_hello.get("ticket", "")
        if ticket_hex and self.keyring is not None:
            tkt = self.keyring.validate(bytes.fromhex(ticket_hex))
            if tkt is None and self.keyring_refresh is not None:
                # maybe sealed under a rotation we haven't fetched yet
                try:
                    await asyncio.wait_for(self.keyring_refresh(), timeout=2.0)
                except Exception:
                    pass
                tkt = self.keyring.validate(bytes.fromhex(ticket_hex))
            if tkt is None:
                # a PRESENTED ticket must verify: silently falling back to
                # the shared-secret path would let an expired/forged
                # ticket ride a daemon's bootstrap credentials
                writer.write(json.dumps({"ok": False}).encode() + b"\n")
                await writer.drain()
                raise PermissionError(
                    f"invalid ticket from {peer_hello.get('name')}")
            key = tkt["session_key"]
            auth_kind = "ticket"
            auth_entity_type = tkt.get("type", "")
        # tell the initiator whether we still hold its session: if not, it
        # must reset its reply-dedupe floor (our out_seq restarts at 1)
        resumed = peer_hello.get("session", "") in self._sessions
        transcript = self._mode_transcript(peer_hello.get("secure", False),
                                           secure_want)
        hello = {"name": self.name, "type": self.entity_type,
                 "nonce": nonce.hex(),
                 "auth": self._auth_tag(their_nonce, key, transcript),
                 "resumed": resumed, "secure": secure_want,
                 "ckind": checksum_kind(),
                 "proc": PROC_TOKEN, "lanes_ok": True}
        writer.write(json.dumps(hello).encode() + b"\n")
        await writer.drain()
        proof = json.loads(await reader.readline())
        expect = self._auth_tag(nonce, key, transcript)
        ok = not expect or hmac.compare_digest(proof.get("auth", ""), expect)
        # colocated ring offer (reactor.py): only to an AUTHENTICATED
        # peer that shares our process token and asked for one — the fin
        # carries the ring id the initiator claims from the in-process
        # registry.  Never under secure mode (the wire security applies
        # to wires; a colocated ring has none, but the configuration
        # asked to exercise the secured path).
        ring_offered: Optional[Tuple[str, Any, Any]] = None
        fin: Dict[str, Any] = {"ok": ok}
        if (ok and self._ring_ok and not secure_want
                and peer_hello.get("ring")
                and peer_hello.get("proc") == PROC_TOKEN):
            ring_id, rx, tx = ring_offer()
            ring_offered = (ring_id, rx, tx)
            fin["ring"] = ring_id
        writer.write(json.dumps(fin).encode() + b"\n")
        await writer.drain()
        if not ok:
            raise PermissionError(f"auth failed for peer {peer_hello.get('name')}")
        if expect and auth_kind == "none":
            auth_kind = "secret"  # peer proved the cluster bootstrap secret
        if secure_want:
            # required, not best-effort (see _handshake_out)
            skey = (self._secure_key(key, their_nonce, nonce)
                    if peer_hello.get("secure") else None)
            if skey is None:
                raise PermissionError(
                    "ms_secure_mode set but connection would be plaintext")
            reader, writer = self._wrap_secure(reader, writer, skey)
        return (peer_hello.get("name", ""), peer_hello.get("type", "client"),
                peer_hello.get("session", ""), bool(peer_hello.get("lossless")),
                auth_kind, auth_entity_type,
                peer_hello.get("ckind", "zlib"), ring_offered,
                reader, writer)

    # -- lifecycle -----------------------------------------------------------

    async def disconnect(self, addr) -> None:
        """Drop the live outbound connection to ``addr`` (if any): the
        next send re-dials and re-runs the handshake — used when the
        credentials the old handshake was built on changed (e.g. a ticket
        was dropped to force bootstrap-secret auth)."""
        key = tuple(addr)
        conn = self._conns.pop(key, None)
        if conn is not None:
            await conn.close()

    async def bind(self, host: str = "127.0.0.1", port: int = 0) -> Tuple[str, int]:
        self.home_loop = asyncio.get_running_loop()
        self.server = await asyncio.start_server(self._accept, host, port)
        self.addr = self.server.sockets[0].getsockname()[:2]
        if self.reactors is not None:
            self.reactors.log = self.log
            # shard the listening socket across the reactor workers:
            # inbound sockets are owned by whichever reactor accepts
            self.reactors.start()
            try:
                if self.reactors.mode == "process":
                    # worker processes accept on dup'd listening fds
                    # and forward fresh sockets here for the handshake
                    self.reactors.serve_shards_process(
                        self.server.sockets[0], self._accepted_fd_cb)
                else:
                    await self.reactors.serve_shards(
                        self.server.sockets[0], self._accept)
            except (OSError, NotImplementedError):
                pass  # platform without dup'd-fd accept: home loop only
        if self._local_fastpath:
            self._loop = asyncio.get_running_loop()
            _LOCAL_REGISTRY[tuple(self.addr)] = self
        self.dout(1, f"bind {self.addr[0]}:{self.addr[1]} (reactors "
                     f"{self.reactors.n_workers if self.reactors else 0}, "
                     f"lanes/peer {self.lanes_per_peer})")
        return self.addr

    @staticmethod
    def _negotiated_crc(peer_ckind: str):
        """Per-connection frame checksum: the fast shared resolver when
        both ends resolved the same KIND, zlib (which every build has)
        when they differ — a per-host native-build failure must degrade,
        never loop every frame through BadFrame."""
        return checksum if peer_ckind == checksum_kind() else zlib.crc32

    async def _accept(self, reader, writer) -> None:
        peer = writer.get_extra_info("peername")[:2]
        task = asyncio.current_task()
        self._tasks.add(task)
        try:
            try:
                (peer_name, peer_type, cookie, lossless, auth_kind,
                 auth_entity_type, peer_ckind, ring_offered,
                 reader, writer) = await self._handshake_in(reader, writer)
            except (PermissionError, BadFrame, ConnectionError, json.JSONDecodeError,
                    asyncio.IncompleteReadError, ValueError):
                writer.close()
                return
            if ring_offered is not None:
                # colocated ring negotiated: the TCP socket's job is
                # done — serve the in-process ring instead
                ring_id, rx, tx = ring_offered
                rconn = RingConnection(self, peer, peer_name, rx, tx,
                                       outbound=False,
                                       auth_entity_type=auth_entity_type)
                self._ring_conns.append(rconn)
                rconn.start_pump()
                try:
                    writer.close()
                except Exception:
                    pass
                return
            evicted_conns = []
            if lossless and cookie:
                with self._sessions_lock:
                    conn = self._sessions.get(cookie)
                    if conn is not None:
                        self._sessions.move_to_end(cookie)
                    else:
                        conn = Connection(self, reader, writer, peer,
                                          Policy.lossless_peer(), peer_name)
                        self._sessions[cookie] = conn
                        while len(self._sessions) > MAX_SESSIONS:
                            _, ev = self._sessions.popitem(last=False)
                            evicted_conns.append(ev)
                for ev in evicted_conns:
                    await self._conn_close(ev)
                here = asyncio.get_running_loop()
                if conn.reader is not reader \
                        and conn.loop not in (None, here):
                    # session reconnect landed on a different reactor
                    # shard than the session's owner: migrate the fresh
                    # socket to the owning loop (transports and the
                    # session's replay machinery are loop-bound)
                    conn.auth_kind = auth_kind
                    conn.auth_entity_type = auth_entity_type
                    pair = await self._migrate_transport(reader, writer,
                                                         conn.loop)
                    if pair is None:
                        # unmigratable (secure stream / dead socket):
                        # forget the session — the initiator's next dial
                        # starts a fresh one (reqid dedupe above absorbs
                        # the at-least-once window, acceptor-restart rule)
                        with self._sessions_lock:
                            if self._sessions.get(cookie) is conn:
                                self._sessions.pop(cookie, None)
                        await self._conn_close(conn)
                        return
                    r2, w2 = pair
                    conn.crc_fn = self._negotiated_crc(peer_ckind)

                    async def _adopt_and_serve():
                        await conn.adopt_transport(r2, w2)
                        await self._serve(conn)

                    fut = asyncio.run_coroutine_threadsafe(
                        _adopt_and_serve(), conn.loop)
                    await asyncio.wrap_future(fut)
                    return
                if conn.reader is not reader:
                    # session reconnect: adopt the new socket, replay our
                    # un-acked frames (e.g. replies lost in the drop)
                    pair = None
                    if (self._delegatable() and conn.lane_group is not None
                            and conn.lane_idx >= 1):
                        # revived acceptor-side data lane: its byte work
                        # goes back to a worker process (pending replies
                        # replay through the ring inside adopt)
                        w = self.reactors.worker_for(conn.peer,
                                                     conn.lane_idx)
                        pair = self._delegate_transport(
                            reader, writer, w,
                            self._negotiated_crc(peer_ckind),
                            conn.crc_enabled)
                        if pair is not None:
                            reader, writer = pair
                            conn.shm_worker = w
                    try:
                        await conn.adopt_transport(reader, writer)
                    except BaseException:
                        if pair is not None:
                            pair[0].close()
                        raise
            else:
                conn = Connection(self, reader, writer, peer,
                                  Policy.lossy_client(), peer_name)
            # how the peer proved itself, for authorization decisions
            # (refreshed on every reconnect handshake)
            conn.auth_kind = auth_kind
            conn.auth_entity_type = auth_entity_type
            conn.crc_fn = self._negotiated_crc(peer_ckind)
            if conn.reactor is None and self.reactors is not None:
                try:
                    conn.reactor = next(
                        w for w in self.reactors.workers
                        if w.loop is asyncio.get_running_loop())
                    conn.reactor.sockets += 1
                except StopIteration:
                    pass
            await self._serve(conn)
        finally:
            self._tasks.discard(task)

    async def _migrate_transport(self, reader, writer, target_loop):
        """Move a freshly-accepted plaintext socket to another loop:
        dup the fd, close the local transport (the dup keeps the socket
        open), rebuild the stream pair on the target loop with any
        already-buffered bytes carried over.  Returns (reader, writer)
        on the target loop, or None when the socket can't be migrated."""
        if not isinstance(reader, asyncio.StreamReader):
            return None  # SecureStream: no raw transport to migrate
        transport = writer.transport
        try:
            transport.pause_reading()
        except Exception:
            pass
        leftover = bytes(reader._buffer)
        reader._buffer.clear()
        sock = transport.get_extra_info("socket")
        sock = getattr(sock, "_sock", sock)
        try:
            dup = sock.dup()
            dup.setblocking(False)
        except Exception:
            return None
        transport.close()

        async def _attach():
            r, w = await asyncio.open_connection(sock=dup)
            if leftover:
                # no await between open and feed: the new transport has
                # not had a chance to deliver socket bytes yet, so the
                # leftover keeps its position at the front of the stream
                r.feed_data(leftover)
            return r, w

        fut = asyncio.run_coroutine_threadsafe(_attach(), target_loop)
        try:
            return await asyncio.wait_for(asyncio.wrap_future(fut),
                                          timeout=2.0)
        except Exception:
            try:
                dup.close()
            except Exception:
                pass
            return None

    # rx batch budget: how many already-buffered frames one dispatch
    # round may drain before acking (bounds latency of the first ack and
    # the throttle bytes held across a group dispatch)
    RX_BATCH_MSGS = 32
    RX_BATCH_BYTES = 32 << 20

    @staticmethod
    def _buffered_frame_len(reader) -> Optional[int]:
        """Payload length of a COMPLETE frame (header + payload) already
        buffered on the reader, else None — the rx batching predicate:
        batch only what needs no further network wait, so a half-arrived
        frame never stalls dispatch of messages already in hand."""
        try:
            if isinstance(reader, FrameReceiver):
                buf, off = reader._pending, reader._off
            elif isinstance(reader, asyncio.StreamReader):
                buf, off = reader._buffer, 0
            else:  # SecureStream
                buf, off = reader._buf, 0
            avail = len(buf) - off
            if avail < _HDR.size:
                return None
            (length,) = struct.unpack_from("<I", buf, off)
            return length if avail >= _HDR.size + length else None
        except (AttributeError, struct.error):
            return None

    async def _serve(self, conn: Connection) -> None:
        gen = conn.transport_gen
        conn.enable_fast_read()
        try:
            while not conn.closed and conn.transport_gen == gen:
                # drain every frame ALREADY buffered into one batch: one
                # dispatch round, one cumulative ack — under a sub-write
                # burst or an op-reply flood the per-message standalone
                # ack (and its flush) collapses into one frame
                batch: list = []  # (seq, msg)
                costs: list = []
                top_seq = 0
                try:
                    while (len(batch) < self.RX_BATCH_MSGS
                           and sum(costs) < self.RX_BATCH_BYTES):
                        if batch:
                            nxt = conn.buffered_frame_len()
                            if nxt is None or not \
                                    conn.throttle.would_admit(nxt):
                                # nothing fully buffered, or the throttle
                                # would BLOCK — and its budget only
                                # returns after dispatch, which this
                                # batch still owes (self-deadlock)
                                break
                        (type_id, version, seq, payload, cost,
                         blob, fixed, verified) = await conn.read_frame()
                        if conn.transport_gen != gen:
                            conn.throttle.put(cost)
                            return  # transport replaced while suspended
                        if type_id == ACK_TYPE:
                            conn.handle_ack(struct.unpack("<Q", payload)[0])
                            conn.throttle.put(cost)
                            continue
                        if seq and seq <= conn.in_seq:
                            # replayed duplicate: re-ack (the original ack
                            # may have been lost) but don't re-dispatch
                            conn.queue_ack(seq)
                            conn.throttle.put(cost)
                            continue
                        try:
                            t_dec = time.monotonic()
                            msg = decode_message(type_id, version, payload,
                                                 blob, fixed)
                            if verified:
                                # the frame layer checked the blob's crc:
                                # handlers holding an app-level crc of the
                                # same bytes skip their own pass
                                msg._wire_verified = True
                            self._note_rx(type(msg).__name__,
                                          _HDR.size + cost,
                                          time.monotonic() - t_dec)
                            if conn.reactor is not None:
                                conn.reactor.rx_msgs += 1
                            log = self.log
                            if log is not None and log.wants("ms", 20):
                                # per-frame rx trace: debug_ms 20 only
                                # (the wants() guard keeps the hot path
                                # at one cached compare)
                                log.dout(
                                    "ms", 20,
                                    f"rx {type(msg).__name__} seq={seq} "
                                    f"{cost}B from {conn.peer[0]}:"
                                    f"{conn.peer[1]}")
                        except Exception as e:
                            # undecodable (type/version skew): poison-
                            # discard so replay can't redeliver it forever
                            print(f"messenger {self.name}: dropping "
                                  f"undecodable frame type={type_id} "
                                  f"v={version}: {e}")
                            if seq:
                                conn.in_seq = seq
                                conn.queue_ack(seq)
                            conn.throttle.put(cost)
                            continue
                        if isinstance(msg, MLaneHello):
                            # lane negotiation frame: messenger-internal
                            # — binds this connection into its lane
                            # group, never reaches the daemon
                            self._bind_lane(conn, msg)
                            if seq:
                                conn.in_seq = max(conn.in_seq, seq)
                                conn.queue_ack(seq)
                            conn.throttle.put(cost)
                            if msg.lane >= 1 and self._delegatable():
                                # process mode: a freshly bound DATA
                                # lane's socket moves to its worker
                                # process; this serve loop keeps
                                # running, now pulling records off the
                                # shm ring instead of the socket
                                await self._delegate_conn(conn, msg.lane)
                            continue
                        if conn.lane_group is not None:
                            # striped session: the LaneGroup restores
                            # gseq order, reassembles fragments, and
                            # dispatches through its single pump — ack
                            # per frame (the flush window coalesces)
                            if seq:
                                conn.in_seq = max(conn.in_seq, seq)
                                conn.queue_ack(seq)
                            conn.lane_group.rx_push(conn, msg, cost)
                            continue
                        batch.append((seq, msg))
                        costs.append(cost)
                        if seq:
                            top_seq = max(top_seq, seq)
                    if not batch:
                        continue
                    if len(batch) > 1:
                        self.perf.inc("rx_batches")
                        self.perf.hinc("rx_batch_msgs", len(batch))
                    try:
                        if self.group_dispatcher is not None \
                                and (len(batch) > 1
                                     or self.dispatcher is None):
                            # whole-group handoff: the daemon partitions
                            # the batch itself (stripe groups to the EC
                            # tier in one submit, coalesced replies).
                            # Singletons also route here when no plain
                            # dispatcher is installed — a group-only
                            # daemon must not have isolated frames
                            # consumed-and-acked undispatched.
                            await self._dispatch_group_home(
                                conn, [m for _, m in batch])
                        elif self.dispatcher is not None:
                            for _, msg in batch:
                                try:
                                    await self._dispatch_home(conn, msg)
                                except (asyncio.CancelledError,
                                        GeneratorExit):
                                    raise
                                except Exception:
                                    # a dispatcher bug must not wedge the
                                    # session into infinite redelivery
                                    traceback.print_exc()
                    except (asyncio.CancelledError, GeneratorExit):
                        raise
                    except Exception:
                        traceback.print_exc()
                    # ack AFTER dispatch: an ack'd frame is a consumed
                    # frame; one cumulative ack covers the whole batch
                    if top_seq:
                        conn.in_seq = max(conn.in_seq, top_seq)
                        conn.queue_ack(top_seq)
                finally:
                    for c in costs:
                        conn.throttle.put(c)
        except (asyncio.IncompleteReadError, ConnectionError, BadFrame):
            pass
        finally:
            await conn.close(gen)
            if conn.closed:
                self.dout(1, f"connection {conn.peer[0]}:{conn.peer[1]} "
                             f"({conn.peer_name or '?'}) closed"
                             + (" [lane]" if conn.lane_group is not None
                                else ""))
            group = conn.lane_group
            if group is not None:
                # lane death: a LOSSLESS lane revives in place (its
                # unacked frames — and only its — replay on the fresh
                # transport while the other lanes keep draining); a
                # lossy lane group dies wholesale, like a lossy conn
                if (conn.outbound and conn.closed and not self._shutdown
                        and not group.closed):
                    coro = (self._revive_lane(group, conn)
                            if conn.policy.replay
                            else self._group_fatal(group))
                    t = asyncio.get_running_loop().create_task(coro)
                    self._tasks.add(t)
                    t.add_done_callback(self._tasks.discard)
            # lossless sessions reconnect from the initiator side so queued
            # frames (ours AND the acceptor's pending replies) replay even
            # when no further application send would trigger it
            elif (conn.outbound and conn.policy.replay and conn.closed
                    and not self._shutdown):
                t = asyncio.get_running_loop().create_task(self._reconnect(conn))
                self._tasks.add(t)
                t.add_done_callback(self._tasks.discard)

    async def _reconnect(self, conn: Connection) -> None:
        delay = 0.02
        for _ in range(10):
            await asyncio.sleep(delay)
            delay = min(delay * 2, 1.0)
            if self._shutdown or self._conns.get(conn.peer) is not conn:
                return
            if not conn.closed:
                return  # something else already revived it
            try:
                await self.connect(conn.peer)
                return
            except (ConnectionError, OSError):
                continue
        # peer looks gone for good: forget the session (the cluster map's
        # failure detection is responsible for marking it down)
        if self._conns.get(conn.peer) is conn:
            self._conns.pop(conn.peer, None)

    # -- lane plane ----------------------------------------------------------

    def _bind_lane(self, conn: Connection, m: "MLaneHello") -> None:
        """Acceptor side of lane negotiation: an MLaneHello (first frame
        on every lane) attaches the carrying connection to its group,
        creating the group on lane 0's hello."""
        evicted = []
        with self._lane_lock:
            group = self._lane_groups.get(m.group)
            if group is None:
                group = LaneGroup(self, conn.peer, m.group,
                                  max(2, m.n_lanes), outbound=False,
                                  policy=conn.policy)
                self._lane_groups[m.group] = group
                while len(self._lane_groups) > MAX_SESSIONS:
                    _, old = self._lane_groups.popitem(last=False)
                    evicted.append(old)
            else:
                self._lane_groups.move_to_end(m.group)
        for old in evicted:
            # full close on the home loop (lanes + pump + queued
            # throttle costs), not just a flag — _bind_lane may run on
            # a reactor serve loop, so hop
            old.closed = True
            home = self.home_loop
            if home is not None and not home.is_closed():
                home.call_soon_threadsafe(
                    lambda g=old: home.create_task(g.close()))
        self.dout(4, f"lane {m.lane}/{m.n_lanes} bound for group "
                     f"{m.group[:8]} from {conn.peer[0]}:{conn.peer[1]}")
        group.bind_lane(conn, m.lane)

    async def _revive_lane(self, group: LaneGroup, conn: Connection) -> None:
        """Initiator-side failover for one dead lossless lane: redial on
        the lane's own loop (the stable worker hash put us here), adopt
        the fresh transport into the SAME lane session — its pinned
        unacked frames (and only its) replay; the gseq reorder buffer on
        the far side absorbs the refilled hole.  An acceptor that lost
        the lane session (restart/eviction) is group-fatal: per-lane
        dedupe floors can't be trusted across it, so the whole group is
        torn down and the next send dials a fresh one."""
        key = (id(conn),)
        if key in group._reviving:
            return
        group._reviving.add(key)
        try:
            delay = 0.02
            for _ in range(10):
                await asyncio.sleep(delay)
                delay = min(delay * 2, 1.0)
                if self._shutdown or group.closed:
                    return
                if not conn.closed:
                    return  # already revived
                try:
                    reader, writer = await asyncio.open_connection(
                        *group.peer)
                except (ConnectionError, OSError):
                    continue
                try:
                    (peer_name, resumed, peer_ckind, lanes_ok, ring_id,
                     reader, writer) = await self._handshake_out(
                        reader, writer, True, conn.session_id)
                    if ring_id:
                        ring_abandon(ring_id)
                except TRANSPORT_ERRORS:
                    try:
                        writer.close()
                    except Exception:
                        pass
                    continue
                if not resumed:
                    try:
                        writer.close()
                    except Exception:
                        pass
                    await self._group_fatal(group)
                    return
                conn.crc_fn = self._negotiated_crc(peer_ckind)
                pair = None
                if self._delegatable() and conn.lane_idx >= 1:
                    # the shard revives in a worker PROCESS (a fresh one
                    # if the old worker died — ensure_worker respawns
                    # the slot); the pinned unacked frames replay
                    # through the new shm ring inside adopt_transport
                    worker = self.reactors.worker_for(group.peer,
                                                      conn.lane_idx)
                    pair = self._delegate_transport(reader, writer,
                                                    worker, conn.crc_fn,
                                                    conn.crc_enabled)
                    if pair is not None:
                        reader, writer = pair
                        conn.shm_worker = worker
                try:
                    await conn.adopt_transport(reader, writer)
                except BaseException:
                    # adopt failed/cancelled AFTER the handoff: the shm
                    # pair must not outlive it (teardown returns parked
                    # budget + unlinks the shared memory)
                    if pair is not None:
                        pair[0].close()
                    raise
                self.perf.inc("lane_revivals")
                self.dout(1, f"lane revived in place for group "
                             f"{group.group_id[:8]} peer "
                             f"{group.peer[0]}:{group.peer[1]} (unacked "
                             f"frames replayed)")
                t = asyncio.get_running_loop().create_task(
                    self._serve(conn))
                self._tasks.add(t)
                t.add_done_callback(self._tasks.discard)
                return
            await self._group_fatal(group)
        finally:
            group._reviving.discard(key)

    async def _group_fatal(self, group: LaneGroup) -> None:
        """Tear a lane group down wholesale (lossy lane death, peer gone
        for good, acceptor session loss): the next send dials fresh."""
        if group.closed:
            return
        group.closed = True
        if self._conns.get(group.peer) is group:
            self._conns.pop(group.peer, None)
        await group.close()

    # -- outbound ------------------------------------------------------------

    async def connect(self, addr: Tuple[str, int],
                      peer_type: str = "osd") -> Connection:
        """Get (or create) an ordered session with a peer.  A cached dead
        lossless connection is revived in place (same session state, fresh
        transport, unacked replay); dead lossy connections are replaced.
        Serialized per addr so concurrent senders share one session.

        Wire-plane negotiation happens here: a colocated peer that
        matches our process token gets the in-process ring transport
        (RingConnection); a lanes-capable peer gets ``ms_lanes_per_peer``
        parallel lanes (LaneGroup) with data lanes bound to reactor
        workers by the stable hash; anything else falls back to the
        single TCP Connection — transparently, the caller just gets an
        object with ``send``."""
        addr = tuple(addr)
        if self.home_loop is None:
            self.home_loop = asyncio.get_running_loop()
        conn = self._conns.get(addr)
        if conn is not None and not conn.closed:
            return conn
        lock = self._conn_locks.setdefault(addr, asyncio.Lock())
        async with lock:
            conn = self._conns.get(addr)
            if conn is not None and not conn.closed:
                return conn
            policy = self.policy_for(peer_type)
            reviving = (isinstance(conn, Connection)
                        and conn.lane_group is None and conn.policy.replay)
            session_id = conn.session_id if reviving \
                else random.randbytes(8).hex()
            reader, writer = await asyncio.open_connection(*addr)
            try:
                (peer_name, resumed, peer_ckind, lanes_ok, ring_id,
                 reader, writer) = await self._handshake_out(
                    reader, writer, policy.replay, session_id,
                    want_ring=self._ring_ok,
                )
            except Exception:
                writer.close()
                raise
            if ring_id:
                pair = ring_claim(ring_id)
                if pair is not None:
                    # colocated ring negotiated: zero-serialization
                    # in-process transport; the TCP socket retires
                    self.dout(1, f"colocated ring negotiated with "
                                 f"{peer_name or '?'} at "
                                 f"{addr[0]}:{addr[1]}")
                    rx, tx = pair
                    rconn = RingConnection(self, addr, peer_name, rx, tx,
                                           outbound=True)
                    self._ring_conns.append(rconn)
                    rconn.start_pump()
                    try:
                        writer.close()
                    except Exception:
                        pass
                    self._conns[addr] = rconn
                    return rconn
                # offer vanished (shutdown race): TCP fallback, transparent
            crc_fn = self._negotiated_crc(peer_ckind)
            if reviving:
                if not resumed:
                    # acceptor lost the session (restart/eviction): its reply
                    # stream restarts at seq 1, so our dedupe floor must too.
                    # Replayed frames may re-dispatch there (at-least-once
                    # across an acceptor restart, as in the reference — PG
                    # reqid dedupe above absorbs it).
                    conn.in_seq = 0
                conn.crc_fn = crc_fn
                await conn.adopt_transport(reader, writer)
                task = asyncio.get_running_loop().create_task(
                    self._serve(conn))
                self._tasks.add(task)
                task.add_done_callback(self._tasks.discard)
                return conn
            base = Connection(self, reader, writer, addr, policy,
                              peer_name, outbound=True)
            base.crc_fn = crc_fn
            base.session_id = session_id
            want_lanes = self.lanes_per_peer if lanes_ok else 1
            if want_lanes <= 1:
                self._conns[addr] = base
                task = asyncio.get_running_loop().create_task(
                    self._serve(base))
                self._tasks.add(task)
                task.add_done_callback(self._tasks.discard)
                return base
            # multi-lane session: lane 0 (this conn) is the control
            # lane on the home loop; data lanes ride reactor shards
            group = LaneGroup(self, addr, random.randbytes(8).hex(),
                              want_lanes, outbound=True, policy=policy)
            group.bind_lane(base, 0)
            task = asyncio.get_running_loop().create_task(self._serve(base))
            self._tasks.add(task)
            task.add_done_callback(self._tasks.discard)
            await base.send(MLaneHello(group=group.group_id, lane=0,
                                       n_lanes=want_lanes,
                                       proc=PROC_TOKEN[:8]))
            results = await asyncio.gather(
                *[self._dial_lane(group, k)
                  for k in range(1, want_lanes)],
                return_exceptions=True)
            errs = [r for r in results if isinstance(r, BaseException)]
            if errs:
                await self._group_fatal(group)
                raise errs[0] if isinstance(errs[0], Exception) \
                    else ConnectionError("lane dial failed")
            self._conns[addr] = group
            return group

    async def _dial_lane(self, group: LaneGroup, lane_idx: int) -> None:
        """Open one data lane of a lane group, on the reactor worker the
        stable hash binds it to: in thread mode the dial runs ON the
        worker's loop; in process mode the handshake runs here and the
        socket is then DELEGATED to the worker process (home loop
        without a pool)."""
        worker = None
        proc_mode = self._delegatable()
        if self.reactors is not None:
            self.reactors.start()
            worker = self.reactors.worker_for(group.peer, lane_idx)

        async def _do():
            reader, writer = await asyncio.open_connection(*group.peer)
            session_id = random.randbytes(8).hex()
            try:
                (peer_name, _resumed, peer_ckind, _lanes_ok, ring_id,
                 reader, writer) = await self._handshake_out(
                    reader, writer, group.policy.replay, session_id)
                if ring_id:
                    ring_abandon(ring_id)
            except Exception:
                writer.close()
                raise
            crc_fn = self._negotiated_crc(peer_ckind)
            shm_worker = None
            if proc_mode:
                pair = self._delegate_transport(
                    reader, writer, worker, crc_fn,
                    bool(_cget(self.conf, "ms_crc_data", True)))
                if pair is not None:
                    reader, writer = pair
                    shm_worker = worker
            conn = Connection(self, reader, writer, group.peer,
                              group.policy, peer_name, outbound=True)
            conn.crc_fn = crc_fn
            conn.session_id = session_id
            if shm_worker is not None:
                conn.shm_worker = shm_worker
                worker.dialed += 1
            elif worker is not None and not proc_mode:
                conn.reactor = worker
                worker.sockets += 1
                worker.dialed += 1
            group.bind_lane(conn, lane_idx)
            # the lane's first frame binds it on the acceptor — before
            # any striped data can ride it
            await conn.send(MLaneHello(group=group.group_id,
                                       lane=lane_idx,
                                       n_lanes=group.n_lanes,
                                       proc=PROC_TOKEN[:8]))
            task = asyncio.get_running_loop().create_task(
                self._serve(conn))
            self._tasks.add(task)
            task.add_done_callback(self._tasks.discard)

        if worker is not None and not proc_mode:
            await worker.submit(_do())
        else:
            await _do()

    async def send(self, addr: Tuple[str, int], msg: Any, retries: int = 3,
                   peer_type: str = "osd") -> None:
        if self._local_fastpath:
            addr_t = tuple(addr)
            for _ in range(2):  # one retry: the peer may have re-bound
                peer = _LOCAL_REGISTRY.get(addr_t)
                if (peer is None or peer._shutdown
                        or not peer._local_fastpath
                        or peer._loop is not asyncio.get_running_loop()):
                    break  # not colocated (or another loop): real wire
                conn = self._local_conns.get(addr_t)
                if conn is None or conn.closed \
                        or conn.peer_messenger is not peer:
                    conn = LocalConnection(self, peer)
                    self._local_conns[addr_t] = conn
                try:
                    await conn.send(msg)
                    return
                except ConnectionError:
                    self._local_conns.pop(addr_t, None)
        last: Optional[Exception] = None
        for _ in range(retries + 1):
            try:
                conn = await self.connect(addr, peer_type)
                await conn.send(msg)
                return
            except PermissionError:
                raise
            except (ConnectionError, OSError) as e:
                last = e
                conn = self._conns.get(tuple(addr))
                if conn is not None and not conn.policy.replay:
                    self._conns.pop(tuple(addr), None)
        raise last  # type: ignore[misc]

    async def shutdown(self) -> None:
        self._shutdown = True
        if self.addr is not None \
                and _LOCAL_REGISTRY.get(tuple(self.addr)) is self:
            _LOCAL_REGISTRY.pop(tuple(self.addr), None)
        for lconn in list(self._local_conns.values()):
            await lconn.close()
        self._local_conns.clear()
        # cancel serve loops FIRST: in py3.12 Server.wait_closed() waits for
        # all connection handlers, so live inbound loops would deadlock it.
        # Tasks living on reactor loops must be cancelled FROM their own
        # loop (Task.cancel is not thread-safe across loops).
        here = asyncio.get_running_loop()
        for t in list(self._tasks):
            t_loop = t.get_loop()
            if t_loop is here:
                t.cancel()
            elif not t_loop.is_closed():
                try:
                    t_loop.call_soon_threadsafe(t.cancel)
                except RuntimeError:
                    pass  # loop shut down under us
        for conn in list(self._conns.values()):
            if isinstance(conn, LaneGroup):
                await conn.close()
            else:
                await self._conn_close(conn)
        for rconn in list(self._ring_conns):
            await rconn.close()
        self._ring_conns.clear()
        with self._lane_lock:
            groups = list(self._lane_groups.values())
            self._lane_groups.clear()
        for g in groups:
            await g.close()
        with self._sessions_lock:
            sessions = list(self._sessions.values())
            self._sessions.clear()
        for conn in sessions:
            await self._conn_close(conn)
        if self.server is not None:
            self.server.close()
            try:
                await asyncio.wait_for(self.server.wait_closed(), timeout=1.0)
            except asyncio.TimeoutError:
                pass
        if self.reactors is not None:
            self.reactors.shutdown()

    # -- wire-plane introspection --------------------------------------------

    def dump_reactors(self) -> Dict[str, Any]:
        """asok ``dump_reactors`` payload: per-reactor socket shards and
        per-peer lane/ring state (rendered by ``ceph daemon``)."""
        peers = []
        rings = []
        seen = set()
        groups = [c for c in self._conns.values()
                  if isinstance(c, LaneGroup)]
        with self._lane_lock:
            for g in self._lane_groups.values():
                groups.append(g)
        for g in groups:
            if id(g) in seen:
                continue
            seen.add(id(g))
            peers.append(g.dump())
        for c in self._ring_conns:
            rings.append(c.dump())
        out = {
            "op_threads": (self.reactors.n_workers
                           if self.reactors is not None else 0),
            "reactor_mode": self.reactor_mode,
            "lanes_per_peer": self.lanes_per_peer,
            "colocated_ring": self._ring_ok,
            "wirepath": "native" if self.wirepath is not None else "python",
            "workers": (self.reactors.dump()
                        if self.reactors is not None else []),
            "peers": peers,
            "rings": rings,
        }
        if self._delegatable():
            # whole-plane view: worker pids + the shm aggregate the
            # perf presample folds into `perf dump`
            self._refresh_proc_perf()
            out["worker_pids"] = [w.pid for w in self.reactors.workers]
            out["proc_perf"] = self.reactors.counters_sum()
        return out
