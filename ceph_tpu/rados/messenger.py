"""Async messenger: typed messages over an authenticated, crc-guarded,
replay-safe framed TCP protocol.

Role-equivalent of the reference's AsyncMessenger + ProtocolV2 stack
(reference src/msg/async/AsyncMessenger.h:73, ProtocolV2.cc, frames_v2.cc):
every daemon creates one Messenger, registers a Dispatcher, and exchanges
versioned typed messages over ordered per-peer Connections.  The v2-style
connection bring-up is banner -> hello (peer name/type, nonce, session
cookie, requested policy, optional HMAC auth over a shared secret — the
cephx role, src/auth/) -> session.  Data frames carry a crc32 (ms_crc_data
mode) and an optional zlib-compressed payload (compression_onwire.cc role,
ms_compress_min_size).

Policies mirror the reference's (Policy::lossy_client vs lossless_peer),
negotiated at handshake: on a lossless session BOTH sides keep one
long-lived Connection object per peer session — frames are sequenced,
acked, and kept queued until acked; after a transport drop the initiator
reconnects and each side replays its un-acked frames onto the new transport
(the server adopts the new socket into the existing session Connection, the
reference's session-reconnect + out_queue replay, ProtocolV2.cc
reuse_connection) — with receiver-side seq dedupe making dispatch
exactly-once in both directions, the OSD<->OSD guarantee PG consistency is
built on.  Lossy connections just fail and are replaced wholesale.

A config-driven fault injector (reference
src/common/options/global.yaml.in:1240) exercises the failure paths
without code changes: ms_inject_socket_failures severs connections,
ms_inject_delay_max delays sends, and ms_inject_dup_frames delivers
client-op-plane messages twice (two frames, two seqs — duplicates the
receiver's seq dedupe CANNOT filter, proving the application layer's
reqid/pop-once dedup instead).  A dispatch throttle
(ms_dispatch_throttle_bytes) applies receive-side backpressure.

Wire formats, by plane (see README "Wire-format threat model"):
- DATA plane (MOSDOp/MOSDOpReply/ECSub*/MPushShard): fixed binary field
  layouts (FLAG_FIXED; FIXED_FIELDS declared in types.py) — struct-speed
  and incapable of executing code on decode, like the reference's
  fixed-layout dencoder structs.  Bulk bytes ride the zero-copy blob
  lane with their own crc32c.
- CONTROL plane (maps, peering, paxos, config): pickled dataclass
  fields — an internal trusted-cluster format behind cephx-lite auth.
- COLOCATED daemons (ms_local_fastpath): no serialization at all —
  typed messages hand over by reference (Messenger local_connection
  role).
The reference's cross-version dencoder discipline is represented by the
per-type version field checked on decode (and exercised by
tools/dencoder + the wire corpus).

Cork/flush discipline (the corked wire data plane): every Connection owns
an OUTBOX.  ``send()`` frames the message and appends the segments to the
outbox; a single per-connection flusher task drains the outbox with ONE
``writelines`` + ONE ``drain()`` per flush window, so frames queued by
concurrent senders (a k+m stripe fan-out, a burst of sub-write replies)
coalesce into one scatter-gather write instead of paying a
lock/write/drain round-trip each (the reference's ProtocolV2 out_queue +
segment writev).  The flush window is self-clocking: while one window
drains, new frames pile into the next — no added latency for an isolated
send, automatic batching under load.  On plaintext TCP the flusher also
swaps the StreamWriter for a CorkedWriter that ``sendmsg``-writevs the
frame segments STRAIGHT FROM their owning buffers (encode outputs, store
blobs, BufferList pieces) — zero copies between codec and kernel.

Acks are PIGGYBACKED: dispatching a frame queues a cumulative ack
(highest contiguous seq) on the connection instead of writing a
standalone ACK_TYPE frame; the next flush carries one ack frame for the
whole window (acks are cumulative, so the latest seq covers every
earlier one).  An ack-only flush is still written promptly when no data
frames are outbound.  The rx side mirrors the batching: the serve loop
drains every frame ALREADY BUFFERED on the transport into one batch,
dispatches the batch (through ``group_dispatcher`` when the daemon
installs one — the whole-stripe group handoff seam), and acks once.

Lossless-replay interaction: a frame enters the unacked replay queue
BEFORE it enters the outbox, and close() fails the pending flush window
and clears the outbox — un-flushed frames replay from the unacked queue
onto the adopted transport in seq order, and the receiver's dedupe floor
makes any flush/replay overlap exactly-once.
"""

from __future__ import annotations

import asyncio
import collections
import hashlib
import hmac
import itertools
import json
import pickle
import random
import struct
import time
import traceback
import zlib
from dataclasses import dataclass
from typing import Any, Callable, Deque, Dict, Optional, Tuple

import numpy as np

from ceph_tpu.common.perf_counters import PerfCounters, PerfCountersBuilder
from ceph_tpu.common.throttle import Throttle


def _build_wire_perf() -> PerfCounters:
    """The `wire` counter set — one per Messenger, added to the owning
    daemon's PerfCountersCollection so `perf dump` and the mgr prometheus
    exporter carry the wire-path breakdown the ROADMAP names as the
    reason the device-tier win is invisible over TCP.  COUNTER SCHEMA
    (name -> meaning -> kind):

      tx_msgs / rx_msgs    u64         messages sent / dispatched
      tx_bytes / rx_bytes  u64         frame bytes written / received on
                                       the socket (tx side counts EVERY
                                       write: messages, acks, session
                                       replays)
      tx_framing           longrunavg  encode + frame-build seconds per send
      tx_io                longrunavg  socket write + drain seconds per
                                       write (messages, acks, replays)
      rx_io                longrunavg  payload read seconds per frame
                                       (clock starts AFTER the header
                                       lands, so idle wait between
                                       messages never pollutes it)
      rx_framing           longrunavg  decode_message seconds per dispatch
      local_msgs           u64         colocated-fastpath handoffs (no
                                       framing or socket at all)
      tx_flushes           u64         outbox flush windows written (each is
                                       one writelines + one drain)
      tx_flush_frames      histogram   frames coalesced per flush window
      tx_flush_bytes       histogram   bytes per flush window
      tx_flush_data        u64         windows cut carrying data frames
      tx_flush_ack         u64         ack-only windows (no data pending)
      tx_acks              u64         ack frames written
      tx_acks_coalesced    u64         acks absorbed into a pending ack
                                       (would have been standalone frames)
      tx_crc_reused        u64         blob frames whose wire crc reused an
                                       app-level crc (no recompute pass)
      rx_batches           u64         multi-frame rx batches drained
      rx_batch_msgs        histogram   messages per rx dispatch batch
      tx_<Type> / rx_<Type>        u64  per-message-type counts (dynamic)
      tx_bytes_<Type> / rx_bytes_<Type>  u64  per-type frame bytes

    framing vs io is the actionable split: framing seconds are Python
    encode cost a scatter-gather/zero-copy PR can remove; io seconds are
    the socket's.  With the corked outbox, tx_io is per FLUSH WINDOW (not
    per message): sum(tx_io)/tx_msgs is the per-message socket cost and
    drops as flush windows batch more frames."""
    b = PerfCountersBuilder("wire")
    b.add_u64_counter("tx_msgs", "messages sent")
    b.add_u64_counter("tx_bytes", "frame bytes sent")
    b.add_u64_counter("rx_msgs", "messages dispatched")
    b.add_u64_counter("rx_bytes", "frame bytes received")
    b.add_time_avg("tx_framing", "encode + frame-build seconds per send")
    b.add_time_avg("tx_io", "socket write + drain seconds per flush window")
    b.add_time_avg("rx_io", "payload read seconds per frame (post-header)")
    b.add_time_avg("rx_framing", "decode seconds per dispatched message")
    b.add_u64_counter("local_msgs", "colocated-fastpath handoffs")
    b.add_u64_counter("tx_flushes", "outbox flush windows written")
    b.add_histogram("tx_flush_frames", "frames coalesced per flush window")
    b.add_histogram("tx_flush_bytes", "bytes per flush window")
    b.add_u64_counter("tx_flush_data", "flush windows carrying data frames")
    b.add_u64_counter("tx_flush_ack", "ack-only flush windows")
    b.add_u64_counter("tx_acks", "ack frames written")
    b.add_u64_counter("tx_acks_coalesced",
                      "acks absorbed into a pending cumulative ack")
    b.add_u64_counter("tx_crc_reused",
                      "blob frames reusing an app-level crc on the wire")
    b.add_u64_counter("rx_batches", "multi-frame rx dispatch batches")
    b.add_histogram("rx_batch_msgs", "messages per rx dispatch batch")
    # µs histograms of the socket-io longrunavgs: tail-latency
    # percentiles (p50/p99/p999) come out of the power-of-2 buckets, so
    # the BENCH record reports wire tx/rx TAILS, not just means
    b.add_histogram("tx_io_us", "socket write+drain µs per flush window")
    b.add_histogram("rx_io_us", "payload read µs per frame")
    return b.create_perf_counters()

BANNER = b"ceph_tpu msgr v2\n"
_HDR = struct.Struct("<IHHBIQ")  # len, type, version, flags, crc, seq

# blob-frame payload prefix: pickled length + blob checksum
_BLOB_PFX = struct.Struct("<II")

FLAG_COMPRESSED = 1
# FLAG_FIXED: the payload (or the header part of a blob frame) is the
# class's FIXED_FIELDS binary layout, not pickle — the data-plane
# framing discipline (reference fixed-layout dencoder encode for
# MOSDOp/ECSubWrite wire structs, src/osd/ECMsgTypes.h encode_payload):
# nothing on the hot path can execute code on decode, and field packing
# is struct-speed.  Control-plane types keep pickle (internal
# trusted-cluster format; see module docstring).
FLAG_FIXED = 4
# FLAG_BLOB: payload = [u32 plen][u32 blob_crc][pickled(plen)][blob].
# The large binary field of a message (MOSDOp.data, MECSubWrite.chunk, ...)
# rides OUT OF BAND from the pickle: the sender never copies it into a
# serialized buffer (scatter-gather writev via writer.writelines), the
# header crc covers only the small pickled part, and the blob's own
# hardware crc32c protects the bulk bytes — the zero-copy framing half of
# the reference's bufferlist-based wire path (src/msg/async/ProtocolV2.cc
# segments + crc sections role).
FLAG_BLOB = 2
# only bulk payloads are worth the second checksum + reattach bookkeeping
BLOB_MIN = 16 * 1024

ACK_TYPE = 0xFFF0  # control frame: payload is the acked seq (u64)

MAX_SESSIONS = 4096  # LRU cap on server-side peer sessions

# -- message registry --------------------------------------------------------

_MSG_TYPES: Dict[int, type] = {}
_MSG_IDS: Dict[type, int] = {}


def message(type_id: int, version: int = 1):
    """Register a message dataclass with a wire type id + version."""

    def deco(cls):
        existing = _MSG_TYPES.get(type_id)
        if existing is not None and existing.__name__ != cls.__name__:
            raise ValueError(
                f"wire type id {type_id} already taken by "
                f"{existing.__name__}; cannot register {cls.__name__}"
            )
        cls = dataclass(cls)
        cls.TYPE_ID = type_id
        cls.VERSION = version
        _MSG_TYPES[type_id] = cls
        _MSG_IDS[cls] = type_id
        return cls

    return deco


# store-resident buffers may be memoryviews (ownership-transferred
# encode outputs); when one rides a pickled message field on the REAL
# wire, serialize it as its bytes — the local fastpath never serializes
import copyreg  # noqa: E402

copyreg.pickle(memoryview, lambda m: (bytes, (bytes(m),)))


def _norm_segments(segments):
    """Normalize buffers to non-empty 1-D byte memoryviews; returns
    (views, total_bytes).  Shared by BufferList and CorkedWriter so the
    cast/skip-empty rules cannot drift apart."""
    segs = []
    total = 0
    for s in segments:
        mv = s if isinstance(s, memoryview) else memoryview(s)
        if mv.ndim != 1 or mv.itemsize != 1:
            mv = mv.cast("B")
        if mv.nbytes:
            segs.append(mv)
            total += mv.nbytes
    return segs, total


class BufferList:
    """A blob made of multiple buffers (the reference's bufferlist,
    src/common/buffer.h): a message's bulk field may be handed over as a
    LIST of byte pieces — per-stripe chunk views, extent slices — and the
    corked send path writev's the pieces straight from their owning
    buffers.  No producer-side gather copy: the de-interleave a read
    reply used to pay (stripes -> one contiguous buffer -> frame) becomes
    a list of views the kernel gathers.  The frame crc chains across the
    pieces, so the bytes on the wire (and the receiver, which sees one
    contiguous blob land in its frame buffer) are identical to the
    concatenation.  Pickling one (control-plane ride-along, sub-threshold
    fallback) materializes to plain bytes."""

    __slots__ = ("segments", "nbytes")

    def __init__(self, segments=()):
        self.segments, self.nbytes = _norm_segments(segments)

    def __len__(self) -> int:
        return self.nbytes

    def tobytes(self) -> bytes:
        return b"".join(self.segments)

    def __bytes__(self) -> bytes:
        return self.tobytes()


# a BufferList that rides pickle (local-fastpath control copy, or a
# sub-threshold blob folded into the payload) lands as plain bytes
copyreg.pickle(BufferList, lambda bl: (bytes, (bl.tobytes(),)))


def as_bytes(data) -> bytes:
    """Materialize a message bulk field to bytes: blob-lane fields may be
    bytes, bytearray, memoryview, or BufferList depending on the path the
    message took (wire rx buffer, store view, scatter-gather reply)."""
    if isinstance(data, bytes):
        return data
    if isinstance(data, BufferList):
        return data.tobytes()
    return bytes(data)


# -- fixed binary field codec ------------------------------------------------
# Data-plane messages declare FIXED_FIELDS = [(name, kind)]: a flat,
# versioned-by-frame binary layout.  Kinds: q/Q/d/? scalars, s (u32-len
# utf8), y (u32-len bytes), Q* (u64 list), s* (str list), qq* (list of
# (i64, i64) pairs), addr ((host, port) or None).  A class may gate
# eligibility with FIXED_WHEN(msg) — e.g. MOSDOp falls back to pickle
# when a compound op vector is attached.

_FIX = {k: struct.Struct("<" + k) for k in ("q", "Q", "d", "?")}
_LEN32 = struct.Struct("<I")
_PAIR = struct.Struct("<qq")


def _pack_fixed(msg: Any, fields, blob_attr=None) -> bytes:
    parts = []
    for name, kind in fields:
        v = msg.__dict__.get(name)
        if name == blob_attr:
            v = b""  # rides the blob lane; reattached on decode
        st = _FIX.get(kind)
        if st is not None:
            parts.append(st.pack(v if kind != "?" else bool(v)))
        elif kind == "s":
            b = (v or "").encode()
            parts.append(_LEN32.pack(len(b)))
            parts.append(b)
        elif kind == "y":
            b = v if isinstance(v, (bytes, bytearray)) else \
                (b"" if v is None else bytes(v))
            parts.append(_LEN32.pack(len(b)))
            parts.append(b)
        elif kind == "Q*":
            v = v or ()
            parts.append(_LEN32.pack(len(v)))
            parts.append(struct.pack(f"<{len(v)}Q", *v))
        elif kind == "s*":
            v = v or ()
            parts.append(_LEN32.pack(len(v)))
            for s in v:
                b = s.encode()
                parts.append(_LEN32.pack(len(b)))
                parts.append(b)
        elif kind == "qq*":
            v = v or ()
            parts.append(_LEN32.pack(len(v)))
            for a, b in v:
                parts.append(_PAIR.pack(a, b))
        elif kind == "addr":
            if not v:
                parts.append(_LEN32.pack(0xFFFFFFFF))
            else:
                h = str(v[0]).encode()
                parts.append(_LEN32.pack(len(h)))
                parts.append(h)
                parts.append(_FIX["q"].pack(int(v[1])))
        else:  # pragma: no cover - schema bug
            raise ValueError(f"unknown fixed kind {kind!r}")
    return b"".join(parts)


def _default_copy(v):
    return list(v) if isinstance(v, list) else (
        dict(v) if isinstance(v, dict) else v)


def _unpack_fixed(cls, payload: bytes, blob: Any):
    obj = cls.__new__(cls)
    d = obj.__dict__
    # non-fixed fields keep their dataclass defaults (fresh containers)
    defaults = _FIXED_DEFAULTS.get(cls)
    if defaults is None:
        defaults = _FIXED_DEFAULTS[cls] = {
            k: v for k, v in cls().__dict__.items()}
    fixed_names = {n for n, _ in cls.FIXED_FIELDS}
    for k, v in defaults.items():
        if k not in fixed_names:
            d[k] = _default_copy(v)
    off = 0
    mv = memoryview(payload)
    for idx, (name, kind) in enumerate(cls.FIXED_FIELDS):
        if off >= len(payload):
            # truncated tail: the sender's FIXED_FIELDS list was SHORTER
            # — an old build predating trailing additions like the
            # trace-id pair.  Default the unsent remainder (the
            # fixed-layout analog of the reference's versioned-decode
            # "new fields default" rule); new fields MUST append.
            for tail_name, _ in cls.FIXED_FIELDS[idx:]:
                d[tail_name] = _default_copy(defaults[tail_name])
            break
        st = _FIX.get(kind)
        if st is not None:
            d[name] = st.unpack_from(payload, off)[0]
            off += st.size
        elif kind in ("s", "y"):
            (n,) = _LEN32.unpack_from(payload, off)
            off += 4
            raw = bytes(mv[off:off + n])
            off += n
            d[name] = raw.decode() if kind == "s" else raw
        elif kind == "Q*":
            (n,) = _LEN32.unpack_from(payload, off)
            off += 4
            d[name] = list(struct.unpack_from(f"<{n}Q", payload, off))
            off += 8 * n
        elif kind == "s*":
            (n,) = _LEN32.unpack_from(payload, off)
            off += 4
            out = []
            for _ in range(n):
                (sn,) = _LEN32.unpack_from(payload, off)
                off += 4
                out.append(bytes(mv[off:off + sn]).decode())
                off += sn
            d[name] = out
        elif kind == "qq*":
            (n,) = _LEN32.unpack_from(payload, off)
            off += 4
            out = []
            for _ in range(n):
                out.append(_PAIR.unpack_from(payload, off))
                off += _PAIR.size
            d[name] = out
        elif kind == "addr":
            (n,) = _LEN32.unpack_from(payload, off)
            off += 4
            if n == 0xFFFFFFFF:
                d[name] = None
            else:
                host = bytes(mv[off:off + n]).decode()
                off += n
                port = _FIX["q"].unpack_from(payload, off)[0]
                off += 8
                d[name] = (host, port)
    if blob is not None:
        d[getattr(cls, "BLOB_ATTR")] = blob
    return obj


_FIXED_DEFAULTS: Dict[type, Dict[str, Any]] = {}


def encode_payload(msg: Any) -> bytes:
    return pickle.dumps(msg.__dict__, protocol=5)


def encode_payload_parts(msg: Any):
    """(header, blob, fixed): when the message class declares BLOB_ATTR
    and the field is bulk bytes, it is stripped from the header part and
    returned separately so framing can scatter-gather it with zero
    copies.  Data-plane classes with FIXED_FIELDS get the fixed binary
    layout for the header part (fixed=True) instead of pickle."""
    cls = type(msg)
    attr = getattr(cls, "BLOB_ATTR", None)
    blob = None
    if attr is not None:
        b = msg.__dict__.get(attr)
        if isinstance(b, (bytes, bytearray, memoryview, BufferList)) \
                and len(b) >= BLOB_MIN:
            blob = b
    fields = getattr(cls, "FIXED_FIELDS", None)
    if fields is not None:
        when = getattr(cls, "FIXED_WHEN", None)
        if when is None or when(msg):
            return (_pack_fixed(msg, fields,
                                blob_attr=attr if blob is not None
                                else None),
                    blob, True)
    if blob is not None:
        d = dict(msg.__dict__)
        d[attr] = None  # reattached by decode_message
        return pickle.dumps(d, protocol=5), blob, False
    if attr is not None:
        b = msg.__dict__.get(attr)
        if isinstance(b, memoryview):
            # below the blob threshold the field rides the pickle,
            # which cannot serialize memoryviews natively fast
            d = dict(msg.__dict__)
            d[attr] = bytes(b)
            return pickle.dumps(d, protocol=5), None, False
    return pickle.dumps(msg.__dict__, protocol=5), None, False


def decode_message(type_id: int, version: int, payload: bytes,
                   blob: Any = None, fixed: bool = False) -> Any:
    cls = _MSG_TYPES.get(type_id)
    if cls is None:
        raise ValueError(f"unknown message type {type_id}")
    if version > cls.VERSION:
        raise ValueError(
            f"{cls.__name__} wire version {version} > supported {cls.VERSION}"
        )
    if fixed:
        if getattr(cls, "FIXED_FIELDS", None) is None:
            raise ValueError(f"{cls.__name__}: unexpected fixed frame")
        return _unpack_fixed(cls, payload, blob)
    obj = cls.__new__(cls)
    obj.__dict__.update(pickle.loads(payload))
    if blob is not None:
        setattr(obj, getattr(cls, "BLOB_ATTR"), blob)
    return obj


# frame/bulk checksum: the shared hardware-crc32c resolver.  The KIND in
# use rides the handshake hello: when the two ends resolved differently
# (one host's native build failed), the connection falls back to zlib for
# its frames instead of looping on BadFrame forever.
from ceph_tpu.utils.checksum import checksum, checksum_kind  # noqa: E402


class BadFrame(Exception):
    pass


# Everything a send/dial can legitimately raise when the PEER (not this
# process) is at fault: socket errors, handshake refusals/garbage, dial
# timeouts.  Daemons catching "send failed, treat as missing ack" catch
# THIS, not Exception — a TypeError in our own framing code must crash
# loudly, not melt into a silent degraded loop.  (ConnectionError and
# PermissionError are OSError subclasses and IncompleteReadError an
# EOFError subclass — listed anyway to document the intended surface.)
TRANSPORT_ERRORS = (ConnectionError, OSError, asyncio.TimeoutError,
                    asyncio.IncompleteReadError, EOFError, BadFrame,
                    PermissionError, json.JSONDecodeError)


# -- policies ----------------------------------------------------------------


@dataclass
class Policy:
    lossy: bool = True
    replay: bool = False  # keep unacked queue + replay on reconnect

    @classmethod
    def lossy_client(cls) -> "Policy":
        return cls(lossy=True, replay=False)

    @classmethod
    def lossless_peer(cls) -> "Policy":
        return cls(lossy=False, replay=True)


def _cget(conf, key: str, default: Any) -> Any:
    try:
        v = conf.get(key, default)
    except TypeError:
        v = conf.get(key) if key in conf else default
    return default if v is None else v


# -- local fast dispatch -----------------------------------------------------

# addr -> live Messenger in THIS process.  Colocated daemons' frames can
# skip the TCP stack entirely (ms_local_fastpath): the in-process
# equivalent of the reference's Messenger local_connection fast dispatch
# and the colocated-transport seam its pluggable NetworkStack keeps open
# (src/msg/async/Stack.h; DPDK/RDMA lanes plug in there the same way).
_LOCAL_REGISTRY: Dict[Tuple[str, int], "Messenger"] = {}


class LocalConnection:
    """In-process session with a colocated daemon: typed messages hand
    over BY REFERENCE through a receiver-side FIFO — no sockets,
    framing, checksums, or serialization.  Delivery matches a lossless
    wire session: per-connection order (one pump task), exactly-once
    (no transport to fail mid-frame), and dispatcher isolation
    (exceptions log, never propagate into the sender — the _serve
    discipline).  Shared contract with the reference's local delivery:
    a message is immutable once sent.

    Enabled per-messenger by ms_local_fastpath; vstart turns it on for
    plain clusters, while any wire-exercising configuration (auth,
    secure mode, fault injection) keeps real sockets so those paths
    stay covered."""

    def __init__(self, messenger: "Messenger", peer_messenger: "Messenger",
                 reverse: Optional["LocalConnection"] = None):
        self.messenger = messenger
        self.peer_messenger = peer_messenger
        self.peer = tuple(peer_messenger.addr or ("local", 0))
        self.peer_name = peer_messenger.name
        self.policy = Policy.lossless_peer()
        self.outbound = reverse is None
        # how the peer "authenticated": same-process construction IS the
        # trust statement (fastpath is off whenever auth is configured)
        self.auth_kind = "local"
        self.auth_entity_type = peer_messenger.entity_type
        self.closed = False
        # bounded: a flooding sender parks on put() exactly like a full
        # socket buffer parks drain()
        self._queue: asyncio.Queue = asyncio.Queue(maxsize=1024)
        self._pump: Optional[asyncio.Task] = None
        self.reverse = reverse if reverse is not None else \
            LocalConnection(peer_messenger, messenger, reverse=self)

    async def send(self, msg: Any) -> None:
        peer = self.peer_messenger
        if (self.closed or peer._shutdown
                or _LOCAL_REGISTRY.get(self.peer) is not peer):
            self.closed = True
            raise ConnectionError(f"local peer {self.peer_name} gone")
        cls = type(msg)
        fields = getattr(cls, "FIXED_FIELDS", None)
        when = getattr(cls, "FIXED_WHEN", None)
        if fields is None or (when is not None and not when(msg)):
            # CONTROL-plane (or exotic) payload: give the receiver its
            # own object graph, exactly as the pickled wire would.
            # By-reference handoff is only safe for the flat, immutable
            # data-plane set — a control payload like MMapReply carries
            # the mon's LIVE OSDMap, whose next in-place mutation would
            # otherwise tear every colocated daemon's shared copy.
            msg = pickle.loads(pickle.dumps(msg, protocol=5))
        await self.reverse._deliver(msg)
        self.messenger.perf.inc("local_msgs")

    async def _deliver(self, msg: Any) -> None:
        await self._queue.put(msg)
        if self._pump is None or self._pump.done():
            m = self.messenger
            self._pump = asyncio.get_running_loop().create_task(
                self._pump_loop())
            m._tasks.add(self._pump)
            self._pump.add_done_callback(m._tasks.discard)

    async def _pump_loop(self) -> None:
        while not self.closed and not self.messenger._shutdown:
            msg = await self._queue.get()
            disp = self.messenger.dispatcher
            if disp is None:
                continue
            try:
                await disp(self, msg)
            except (asyncio.CancelledError, GeneratorExit):
                raise
            except Exception:
                traceback.print_exc()

    async def close(self, gen: int = 0) -> None:
        self.closed = True
        if self._pump is not None:
            self._pump.cancel()


# -- connection --------------------------------------------------------------


class FrameReceiver(asyncio.BufferedProtocol):
    """Zero-copy receive path: installed over the connection's transport
    (transport.set_protocol) AFTER the handshake, replacing the
    StreamReader chain whose kernel-copy -> feed_data-extend ->
    readexactly-slice pipeline double-copies every byte.  BufferedProtocol
    hands the transport OUR buffer: while a readexactly() is pending, the
    destination frame buffer itself is exposed, so payload bytes land
    exactly once.  Write-side flow control keeps working by forwarding
    pause_writing/resume_writing to the original stream protocol (the
    StreamWriter's drain() still consults it)."""

    # small backlog cap: bytes that arrive before a readexactly() is
    # waiting land in _pending and must be COPIED out, so the transport
    # pauses early — the single-copy path is bytes landing directly in
    # the registered destination buffer
    _LIMIT = 128 << 10

    def __init__(self, transport, stream_protocol, leftover: bytes = b""):
        self._transport = transport
        self._stream_protocol = stream_protocol
        self._pending = bytearray(leftover)
        self._off = 0  # consumed prefix of _pending (O(1) front-consume)
        self._dest = None  # memoryview being filled by get_buffer
        self._dest_pos = 0
        self._scratch = bytearray(64 * 1024)
        self._scratch_view = memoryview(self._scratch)
        self._waiter: Optional[asyncio.Future] = None
        self._eof = False
        self._exc: Optional[BaseException] = None
        self._read_paused = False
        self._via_scratch = True  # last get_buffer handed out scratch
        # the connection's CorkedWriter, when one took over the tx side:
        # connection_lost must fail its drain waiters too
        self.corked = None

    # -- protocol side -------------------------------------------------------

    def get_buffer(self, sizehint: int):
        if self._dest is not None and self._dest_pos < len(self._dest):
            remaining = len(self._dest) - self._dest_pos
            if remaining >= len(self._scratch):
                # bulk destination (blob body): single-copy direct fill
                self._via_scratch = False
                return self._dest[self._dest_pos:]
            # SMALL destination (frame header, short payload): read
            # GREEDILY through scratch so one recv drains everything the
            # kernel has — the surplus (trailing frames of a burst)
            # lands in _pending, which is what the serve loop's rx
            # batching predicate looks at.  A per-dest-sized recv here
            # would hand frames over one at a time (two syscalls per
            # tiny frame) and batching would never see a second frame.
            self._via_scratch = True
            return self._scratch_view
        self._via_scratch = True
        return self._scratch_view

    def buffer_updated(self, nbytes: int) -> None:
        if self._dest is not None and self._dest_pos < len(self._dest):
            if not self._via_scratch:
                self._dest_pos += nbytes
                # wake the reader only when its buffer is COMPLETE: a
                # wake per network chunk would round-trip the event loop
                # hundreds of times per blob, each competing with every
                # other ready callback in a busy daemon
                if self._dest_pos >= len(self._dest):
                    self._wake()
                return
            # greedy scratch read: split between the waiting dest and
            # the pending backlog
            remaining = len(self._dest) - self._dest_pos
            take = min(nbytes, remaining)
            self._dest[self._dest_pos:self._dest_pos + take] = \
                self._scratch_view[:take]
            self._dest_pos += take
            if nbytes > take:
                self._pending += self._scratch_view[take:nbytes]
                self._check_limit()
            if self._dest_pos >= len(self._dest):
                self._wake()
        else:
            self._pending += self._scratch_view[:nbytes]
            self._check_limit()
            self._wake()

    def _check_limit(self) -> None:
        if len(self._pending) - self._off > self._LIMIT \
                and not self._read_paused:
            self._read_paused = True
            try:
                self._transport.pause_reading()
            except Exception:
                pass

    def eof_received(self):
        self._eof = True
        self._wake()
        return False

    def connection_lost(self, exc) -> None:
        self._eof = True
        self._exc = exc
        self._wake()
        if self.corked is not None:
            self.corked._on_lost(exc)
        # the StreamWriter still drains through the ORIGINAL stream
        # protocol: without this forward, a drain() parked on a paused
        # writer never learns the connection died and waits forever —
        # holding the connection send lock and wedging every reconnect
        try:
            self._stream_protocol.connection_lost(exc)
        except Exception:
            pass

    def pause_writing(self) -> None:
        self._stream_protocol.pause_writing()

    def resume_writing(self) -> None:
        self._stream_protocol.resume_writing()

    def _wake(self) -> None:
        w = self._waiter
        if w is not None and not w.done():
            w.set_result(None)

    # -- reader side ---------------------------------------------------------

    async def readexactly(self, n: int, uninit: bool = False):
        """Read n bytes.  With ``uninit=True`` the destination is an
        UNINITIALIZED buffer (np.empty) returned as a memoryview:
        bytearray(n) memsets n zero bytes the socket is about to
        overwrite, a full extra pass over the data volume on blob
        frames.  Only blob fields whose consumers are buffer-safe
        (BLOB_VIEW_OK types: store/decode lanes) opt in — everything
        else keeps bytearray semantics (concat, decode, mutation)."""
        pend = self._pending
        avail = len(pend) - self._off
        if avail >= n:
            out = bytes(pend[self._off:self._off + n])
            self._consume(n)
            return out
        if uninit:
            buf = memoryview(np.empty(n, dtype=np.uint8)).cast("B")
            mv = buf
        else:
            buf = bytearray(n)
            mv = memoryview(buf)
        pos = avail
        if pos:
            mv[:pos] = pend[self._off:]
            self._off = 0
            pend.clear()
            self._maybe_resume()
        self._dest = mv
        self._dest_pos = pos
        try:
            while self._dest_pos < n:
                if self._eof:
                    if self._exc is not None and not isinstance(
                            self._exc, (ConnectionError, OSError)):
                        raise self._exc
                    raise asyncio.IncompleteReadError(
                        bytes(mv[:self._dest_pos]), n)
                self._waiter = asyncio.get_running_loop().create_future()
                try:
                    await self._waiter
                finally:
                    self._waiter = None
        finally:
            self._dest = None
        return buf

    def _consume(self, n: int) -> None:
        """Advance the consumed-prefix pointer; compact only when the
        dead prefix dominates (amortized O(1) — a del-from-front per
        read is an O(len) memmove that dominated profiles)."""
        self._off += n
        pend = self._pending
        if self._off == len(pend):
            self._off = 0
            pend.clear()
        elif self._off > 1 << 16 and self._off * 2 > len(pend):
            del pend[:self._off]
            self._off = 0
        self._maybe_resume()

    def _maybe_resume(self) -> None:
        if self._read_paused \
                and len(self._pending) - self._off < self._LIMIT // 2:
            self._read_paused = False
            try:
                self._transport.resume_reading()
            except Exception:
                pass


class CorkedWriter:
    """Zero-copy scatter-gather tx path: once the handshake is done (and
    the transport's own write buffer is empty), the connection's flusher
    swaps the StreamWriter for this — writes go STRAIGHT from the frame
    segments to ``socket.sendmsg`` (writev), so frame bytes are never
    joined or copied into a transport buffer.  The asyncio transport
    keeps owning the rx side (FrameReceiver) and the fd's lifetime; this
    class only owns which bytes leave.

    Congestion handling: segments queue in a deque; a full socket
    registers an add_writer callback that resumes sendmsg as the kernel
    drains.  ``drain()`` parks senders until the backlog is fully
    written: queued segments are VIEWS of live caller buffers (encode
    outputs, store blobs), and a drain that returned with segments still
    queued would let the owner mutate bytes before the kernel reads
    them.  Zero-copy therefore trades the overlap a buffered writer has
    — the copies it saves are the whole point.

    Failure: a send error (or the transport's connection_lost, forwarded
    by FrameReceiver) fails queued segments and drain waiters with the
    transport error — the same surface StreamWriter.drain() has."""

    IOV_MAX = 512  # segments per sendmsg call (conservative vs UIO_MAXIOV)

    def __init__(self, transport, sock, stream_writer):
        self._transport = transport
        self._sock = sock
        self._sw = stream_writer  # close/wait_closed/extra-info delegate
        loop = asyncio.get_running_loop()
        self._loop = loop
        # the PRIVATE writer registration transports themselves use: the
        # public add_writer refuses fds owned by a transport (ours is —
        # the transport keeps the rx side).  _maybe_cork gates on these
        # existing, so an event loop without them just never corks.
        self._add_writer = loop._add_writer
        self._remove_writer = loop._remove_writer
        self._fd = sock.fileno()
        self._segs: Deque = collections.deque()
        self._buffered = 0
        self._writer_on = False  # add_writer registered
        self._waiters: list = []
        self._exc: Optional[BaseException] = None

    # -- StreamWriter surface -------------------------------------------------

    def write(self, data) -> None:
        self.writelines([data])

    def writelines(self, segments) -> None:
        if self._exc is not None:
            return  # error surfaces at drain(), like StreamWriter
        segs, total = _norm_segments(segments)
        self._segs.extend(segs)
        self._buffered += total
        if not self._writer_on:
            self._do_send()

    async def drain(self) -> None:
        while self._exc is None and self._buffered > 0:
            fut = self._loop.create_future()
            self._waiters.append(fut)
            await fut
        if self._exc is not None:
            exc = self._exc
            raise exc if isinstance(exc, Exception) \
                else ConnectionResetError("connection lost")

    def close(self) -> None:
        # best-effort final flush, then the transport closes the fd; any
        # still-unsent segments are dropped (lossless replay re-delivers)
        if self._exc is None and self._segs and not self._writer_on:
            self._do_send()
        self._detach()
        self._sw.close()

    async def wait_closed(self) -> None:
        await self._sw.wait_closed()

    def get_extra_info(self, *a, **kw):
        return self._sw.get_extra_info(*a, **kw)

    @property
    def transport(self):
        return self._transport

    # -- socket side ----------------------------------------------------------

    def _do_send(self) -> None:
        try:
            while self._segs:
                if len(self._segs) > self.IOV_MAX:
                    batch = list(itertools.islice(self._segs, self.IOV_MAX))
                else:
                    batch = list(self._segs)
                sent = self._sock.sendmsg(batch)
                self._advance(sent)
        except (BlockingIOError, InterruptedError):
            if not self._writer_on:
                self._writer_on = True
                self._add_writer(self._fd, self._do_send)
            return
        except OSError as e:
            self._on_lost(e)
            return
        if self._writer_on:
            self._writer_on = False
            try:
                self._remove_writer(self._fd)
            except Exception:
                pass
        self._wake()

    def _advance(self, n: int) -> None:
        self._buffered -= n
        while n and self._segs:
            head = self._segs[0]
            if n >= head.nbytes:
                n -= head.nbytes
                self._segs.popleft()
            else:
                self._segs[0] = head[n:]
                n = 0

    def _wake(self) -> None:
        if self._buffered == 0 or self._exc is not None:
            waiters, self._waiters = self._waiters, []
            for w in waiters:
                if not w.done():
                    w.set_result(None)

    def _detach(self) -> None:
        if self._writer_on:
            self._writer_on = False
            try:
                self._remove_writer(self._fd)
            except Exception:
                pass

    def _on_lost(self, exc) -> None:
        if self._exc is None:
            self._exc = exc if exc is not None else \
                ConnectionResetError("connection lost")
        self._detach()
        self._segs.clear()
        self._buffered = 0
        self._wake()


class Connection:
    """One ordered session with a peer.  For lossless sessions this object
    outlives TCP transports: seqs, the unacked queue, and the dedupe floor
    persist while transports come and go (transport_gen fences stale serve
    loops)."""

    def __init__(self, messenger: "Messenger", reader, writer,
                 peer: Tuple[str, int], policy: Policy,
                 peer_name: str = "", outbound: bool = False):
        self.messenger = messenger
        self.reader = reader
        self.writer = writer
        self.peer = peer
        self.peer_name = peer_name
        self.policy = policy
        self.outbound = outbound
        # how the peer authenticated ("ticket" / "secret" / "none") — set
        # by the acceptor after _handshake_in; outbound conns keep "none"
        self.auth_kind = "none"
        self.auth_entity_type = ""
        self.closed = False
        self.transport_gen = 0
        self.out_seq = 0
        self.in_seq = 0  # highest data seq dispatched (dedupe floor)
        # per-connection session id: acceptors key replay sessions on it, so
        # a REPLACED connection never collides with its predecessor's seqs
        self.session_id = random.randbytes(8).hex()
        self.unacked: Deque[Tuple[int, bytes]] = collections.deque()
        from ceph_tpu.common.lockdep import make_async_mutex

        self._send_lock = make_async_mutex("conn-send")
        # corked outbox (module docstring "Cork/flush discipline"):
        # framed segments awaiting the next flush window, the shared
        # future senders in that window await, and the single flusher
        # task that drains windows with one writelines+drain each
        self._outbox: list = []
        self._outbox_frames = 0
        self._outbox_bytes = 0
        self._ack_pending = -1  # highest seq owed an ack; -1 = none
        self._flush_fut: Optional[asyncio.Future] = None
        self._flusher: Optional[asyncio.Task] = None
        self._corked_ok = bool(_cget(messenger.conf, "ms_corked_writev",
                                     True))
        # crc/compression resolved once per connection (v2 negotiates at
        # handshake time; avoids typed-config parsing on the hot path)
        conf = messenger.conf
        self.crc_enabled = bool(_cget(conf, "ms_crc_data", True))
        self.compress_min = int(_cget(conf, "ms_compress_min_size", 0) or 0)
        # frame checksum for THIS connection: crc32c when both ends run
        # the native build (negotiated via the hello's "ckind"), zlib
        # otherwise — a silent per-host resolver difference must degrade,
        # not deadlock (set by the handshake; default local resolver)
        self.crc_fn = checksum

    def enable_fast_read(self) -> None:
        """Swap the StreamReader for the zero-copy FrameReceiver when the
        transport allows it (plaintext TCP; not already swapped).  Called
        at serve-loop start — the handshake has fully drained its reads,
        and any bytes the stream already buffered carry over."""
        r = self.reader
        if not isinstance(r, asyncio.StreamReader):
            return  # SecureStream (AES-GCM) or already a FrameReceiver
        try:
            transport = r._transport  # the stream pair shares it
            if transport is None:
                return
            proto = transport.get_protocol()
            leftover = bytes(r._buffer)
            r._buffer.clear()
            receiver = FrameReceiver(transport, proto, leftover)
            if r.at_eof():
                receiver._eof = True  # FIN landed before the swap
            transport.set_protocol(receiver)
            # the StreamReader may have left the transport paused (its
            # own flow control); the receiver starts unpaused, so resume
            # or reads would hang forever once the leftover drains
            try:
                transport.resume_reading()
            except Exception:
                pass
        except Exception:
            return
        self.reader = receiver

    # -- frame IO ------------------------------------------------------------

    def _frame(self, type_id: int, version: int, payload: bytes, seq: int,
               flags: int = 0) -> bytes:
        if self.compress_min and len(payload) >= self.compress_min:
            compressed = zlib.compress(payload, 1)
            if len(compressed) < len(payload):
                payload = compressed
                flags |= FLAG_COMPRESSED
        crc = self.crc_fn(payload) if self.crc_enabled else 0
        return _HDR.pack(len(payload), type_id, version, flags, crc, seq) + payload

    def _frame_segments(self, type_id: int, version: int, pickled: bytes,
                        blob, seq: int, flags: int = 0,
                        blob_crc: Optional[int] = None):
        """Scatter-gather frame for a blob message: the bulk bytes are
        never concatenated into a serialized buffer — the transport
        writev's [hdr, prefix, pickled, blob...] as-is (a BufferList blob
        contributes each piece unjoined).  The header crc covers
        prefix+pickled (small); the blob carries its own crc32c —
        ``blob_crc`` passes a crc the sender already holds over exactly
        these bytes (MECSubWrite.chunk_crc, a stored shard's meta crc) so
        the wire pass is skipped, the reference's bufferlist cached-crc
        discipline.  Blob frames skip on-wire compression (bulk data is
        usually incompressible shard bytes; the pickled part is tiny)."""
        if isinstance(blob, BufferList):
            segs = blob.segments
            blob_len = blob.nbytes
        else:
            segs = [blob]
            blob_len = len(blob)
        if blob_crc is None:
            if self.crc_enabled:
                blob_crc = 0
                for s in segs:
                    blob_crc = self.crc_fn(s, blob_crc)
            else:
                blob_crc = 0
        else:
            self.messenger.perf.inc("tx_crc_reused")
        prefix = _BLOB_PFX.pack(len(pickled), blob_crc)
        crc = (self.crc_fn(pickled, self.crc_fn(prefix))
               if self.crc_enabled else 0)
        hdr = _HDR.pack(_BLOB_PFX.size + len(pickled) + blob_len,
                        type_id, version, FLAG_BLOB | flags, crc, seq)
        return [hdr, prefix, pickled, *segs]

    # -- corked outbox (tx coalescing) ---------------------------------------

    def _seg_len(self, s) -> int:
        return s.nbytes if isinstance(s, memoryview) else len(s)

    async def _enqueue(self, data) -> None:
        """Append one framed message to the outbox and await the flush
        window that carries it.  Concurrent senders in the same window
        share ONE writelines + ONE drain; a transport failure fails the
        whole window (each sender sees ConnectionResetError)."""
        if self.closed:
            raise ConnectionResetError("connection closed")
        segs = data if isinstance(data, list) else [data]
        self._outbox.extend(segs)
        self._outbox_frames += 1
        self._outbox_bytes += sum(self._seg_len(s) for s in segs)
        fut = self._flush_fut
        if fut is None:
            fut = self._flush_fut = \
                asyncio.get_running_loop().create_future()
        self._kick_flusher()
        await fut

    def queue_ack(self, seq: int) -> None:
        """Queue a cumulative ack for ``seq`` (acks are cumulative: the
        receiver pops every unacked frame <= seq, so only the highest
        pending seq ever needs a frame).  The ack piggybacks on the next
        flush window — one ack frame per window instead of one per
        dispatched message."""
        if self.closed:
            return
        if self._ack_pending >= 0:
            self.messenger.perf.inc("tx_acks_coalesced")
        self._ack_pending = max(self._ack_pending, seq)
        self._kick_flusher()

    def _kick_flusher(self) -> None:
        if self._flusher is None or self._flusher.done():
            m = self.messenger
            self._flusher = asyncio.get_running_loop().create_task(
                self._flush_loop())
            m._tasks.add(self._flusher)
            self._flusher.add_done_callback(m._tasks.discard)

    def _ack_frame(self) -> bytes:
        payload = struct.pack("<Q", self._ack_pending)
        self._ack_pending = -1
        return _HDR.pack(8, ACK_TYPE, 1, 0, self.crc_fn(payload), 0) + payload

    async def _flush_loop(self) -> None:
        """The per-connection flusher: drains flush windows until the
        outbox and pending ack are empty.  tx accounting lives HERE so
        every socket write — messages, acks — lands in tx_io/tx_bytes;
        per-message framing cost and per-type counts are send()'s
        (_note_tx).  The tx_io timer starts INSIDE the lock: queueing
        behind an adopt_transport replay is not socket time."""
        perf = self.messenger.perf
        try:
            while (self._outbox or self._ack_pending >= 0) \
                    and not self.closed:
                async with self._send_lock:
                    if self.closed:
                        break
                    self._maybe_cork()
                    segs = self._outbox
                    self._outbox = []
                    frames = self._outbox_frames
                    self._outbox_frames = 0
                    nbytes = self._outbox_bytes
                    self._outbox_bytes = 0
                    fut, self._flush_fut = self._flush_fut, None
                    had_data = bool(segs)
                    if self._ack_pending >= 0:
                        ack = self._ack_frame()
                        segs.append(ack)
                        frames += 1
                        nbytes += len(ack)
                        perf.inc("tx_acks")
                    if not segs:
                        break
                    perf.inc("tx_flush_data" if had_data else "tx_flush_ack")
                    perf.inc("tx_flushes")
                    perf.hinc("tx_flush_frames", frames)
                    perf.hinc("tx_flush_bytes", nbytes)
                    gen = self.transport_gen
                    t_io = time.monotonic()
                    try:
                        with perf.time_avg("tx_io"):
                            self.writer.writelines(segs)
                            await self.writer.drain()
                    except (ConnectionError, OSError,
                            asyncio.TimeoutError) as e:
                        if fut is not None and not fut.done():
                            fut.set_exception(ConnectionResetError(
                                f"flush failed: {e}"))
                            fut.exception()  # mark retrieved (no-waiter GC)
                        # gen-fenced: a no-op here means adopt_transport
                        # replaced the transport under us — loop again and
                        # retry the remaining windows on the new writer
                        # (a genuine close ends the loop via its condition)
                        await self.close(gen)
                        continue
                    except asyncio.CancelledError:
                        raise
                    except BaseException as e:
                        # a framing/writer BUG must crash loudly — but
                        # never by leaving the window's senders parked on
                        # a future nobody will resolve
                        if fut is not None and not fut.done():
                            fut.set_exception(
                                ConnectionResetError(f"flush failed: {e}"))
                            fut.exception()
                        await self.close(gen)
                        raise
                    perf.inc("tx_bytes", nbytes)
                    perf.hinc("tx_io_us",
                              (time.monotonic() - t_io) * 1e6)
                    if fut is not None and not fut.done():
                        fut.set_result(None)
        finally:
            if self.closed:
                self._fail_pending(ConnectionResetError("connection closed"))

    def _pin_replay_queue(self) -> None:
        """Materialize view segments of queued unacked frames to bytes.
        Runs at transport death: from here the frames may sit queued for
        a whole reconnect window (or forever, for a gone peer), and a
        queued VIEW would pin its whole backing buffer (e.g. the k-row
        encode matrix behind one shard's 1/k-sized view) for that long.
        While the transport is healthy the queue turns over within an
        RTT, so the hot path never pays this copy."""
        for i, (seq, data) in enumerate(self.unacked):
            if isinstance(data, list) \
                    and any(not isinstance(s, bytes) for s in data):
                self.unacked[i] = (seq, [
                    s if isinstance(s, bytes) else bytes(s) for s in data])

    def _fail_pending(self, exc: Exception) -> None:
        """Fail the pending flush window (senders awaiting it see the
        transport error) and drop un-flushed segments: lossless frames
        live in the unacked queue and replay on the adopted transport;
        un-flushed acks are re-queued by the dedupe path when the peer
        replays."""
        fut, self._flush_fut = self._flush_fut, None
        self._outbox = []
        self._outbox_frames = 0
        self._outbox_bytes = 0
        self._ack_pending = -1
        if fut is not None and not fut.done():
            fut.set_exception(exc)
            fut.exception()  # mark retrieved: ok if every sender left

    def _maybe_cork(self) -> None:
        """Swap the StreamWriter for the zero-copy CorkedWriter when the
        transport allows it (plaintext TCP, nothing buffered in the
        transport, sendmsg available).  Called under the send lock at
        flush time — lazily, so it naturally re-engages after an
        adopt_transport handed us a fresh StreamWriter."""
        if not self._corked_ok:
            return
        w = self.writer
        if not isinstance(w, asyncio.StreamWriter):
            return  # SecureStream (AES-GCM) or already corked
        try:
            transport = w.transport
            if (transport is None or transport.is_closing()
                    or transport.get_write_buffer_size() != 0):
                return
            sock = transport.get_extra_info("socket")
            # unwrap asyncio's TransportSocket: its sendmsg() warns (and
            # is slated for removal); the raw socket is the real surface
            sock = getattr(sock, "_sock", sock)
            if sock is None or not hasattr(sock, "sendmsg"):
                return
            loop = asyncio.get_running_loop()
            if not hasattr(loop, "_add_writer"):
                return  # non-selector loop: keep the stream writer
            corked = CorkedWriter(transport, sock, w)
            proto = transport.get_protocol()
            if isinstance(proto, FrameReceiver):
                proto.corked = corked  # connection_lost fails its waiters
        except Exception:
            return
        self.writer = corked

    async def send(self, msg: Any) -> None:
        conf = self.messenger.conf
        inj = _cget(conf, "ms_inject_socket_failures", 0)
        injected = bool(inj) and random.randrange(inj) == 0
        if injected and not self.policy.replay:
            await self.close()
            raise ConnectionResetError("injected socket failure")
        delay = _cget(conf, "ms_inject_delay_max", 0)
        if delay:
            await asyncio.sleep(random.uniform(0, delay))
        # ms_inject_dup_frames: deliver this message TWICE (two frames,
        # two seqs — a genuine at-least-once delivery the receiver's seq
        # dedupe cannot filter), exercising the APPLICATION layer's
        # duplicate absorption.  Scoped to the client-op plane, which is
        # the layer contracted to absorb duplicates: MOSDOp dups dedupe
        # against the PG log's reqid set, MOSDOpReply dups against the
        # client's pop-once reply futures.  Other planes (sub-write
        # replies, peering gathers) count messages and are entitled to
        # the session's exactly-once delivery.
        dup_inj = _cget(conf, "ms_inject_dup_frames", 0)
        duplicate = (bool(dup_inj)
                     and type(msg).__name__ in ("MOSDOp", "MOSDOpReply")
                     and random.randrange(dup_inj) == 0)
        self.out_seq += 1
        seq = self.out_seq
        t_frame = time.monotonic()
        pickled, blob, fixed = encode_payload_parts(msg)
        flags = FLAG_FIXED if fixed else 0
        if blob is not None:
            # cached-crc reuse: a message that already carries a crc of
            # EXACTLY its blob bytes (BLOB_CRC_ATTR) skips the wire crc
            # pass — only when this connection's negotiated checksum is
            # the shared resolver the app-level crc was computed with
            pre_crc = None
            crc_attr = getattr(type(msg), "BLOB_CRC_ATTR", None)
            if crc_attr is not None and self.crc_enabled \
                    and self.crc_fn is checksum:
                v = msg.__dict__.get(crc_attr) or 0
                if v:
                    pre_crc = v & 0xFFFFFFFF
            data = self._frame_segments(msg.TYPE_ID, msg.VERSION, pickled,
                                        blob, seq, flags, blob_crc=pre_crc)
        else:
            pre_crc = None
            data = self._frame(msg.TYPE_ID, msg.VERSION, pickled, seq,
                               flags)
        self.messenger._note_tx(type(msg).__name__,
                                sum(self._seg_len(p) for p in data)
                                if isinstance(data, list) else len(data),
                                time.monotonic() - t_frame)
        if self.policy.replay:
            # lossless send never fails: the frame joins the session queue
            # and reconnect+replay delivers it exactly once (reference
            # lossless_peer out_queue semantics).  Blob VIEWS stay views
            # here — on a healthy session the ack pops the frame within
            # an RTT, so the pin on the backing buffer is transient; the
            # frames only materialize to bytes when the transport DIES
            # (close() -> _pin_replay_queue), which is when a frame can
            # actually sit queued long enough for pinning to matter.
            self.unacked.append((seq, data))
            if injected:
                # injected transport failure: frame stays queued, session
                # survives, reconnect+replay delivers
                await self.close()
                return
            try:
                await self._enqueue(data)
            except (ConnectionError, OSError):
                await self.close()
        else:
            await self._enqueue(data)
        if duplicate and not self.closed:
            # the duplicate frame is best-effort: the knob exists to
            # exercise dedup, and a transport error here already has the
            # original frame's failure handling covering the message
            self.out_seq += 1
            dseq = self.out_seq
            if blob is not None:
                ddata = self._frame_segments(
                    msg.TYPE_ID, msg.VERSION, pickled, blob, dseq, flags,
                    blob_crc=pre_crc)
            else:
                ddata = self._frame(msg.TYPE_ID, msg.VERSION, pickled,
                                    dseq, flags)
            if self.policy.replay:
                self.unacked.append((dseq, ddata))
            try:
                await self._enqueue(ddata)
            except (ConnectionError, OSError):
                pass

    async def send_ack(self, seq: int) -> None:
        """Compat shim: queue a cumulative ack (piggybacked on the next
        flush window; see queue_ack)."""
        self.queue_ack(seq)

    def handle_ack(self, seq: int) -> None:
        while self.unacked and self.unacked[0][0] <= seq:
            self.unacked.popleft()

    async def read_frame(self) -> Tuple[int, int, int, bytes, int, Any,
                                        bool, bool]:
        """Returns (type_id, version, seq, payload, cost, blob, fixed,
        blob_verified).  The dispatch throttle is charged `cost` bytes
        BEFORE the payload is read (receive-side backpressure, reference
        DispatchQueue throttle); the caller must put() cost back when
        done with the payload.  Blob frames (FLAG_BLOB) return the bulk
        bytes separately, checked against their own crc32c —
        ``blob_verified`` says that check actually ran (crc enabled and
        present), so handlers holding an app-level crc of the same bytes
        (MECSubWrite.chunk_crc) can skip their own verify pass."""
        hdr = await self.reader.readexactly(_HDR.size)
        length, type_id, version, flags, crc, seq = _HDR.unpack(hdr)
        cost = length
        await self.messenger.dispatch_throttle.get(cost)
        # rx_io clock starts AFTER the header lands: the header read is
        # where idle between-message waiting parks, and folding that into
        # the per-frame number would drown the transfer cost it measures
        t_io = time.monotonic()
        blob_verified = False
        try:
            blob = None
            if flags & FLAG_BLOB:
                # the blob reads into ITS OWN buffer (FrameReceiver lands
                # bytes there directly — no giant payload slice)
                head = await self.reader.readexactly(_BLOB_PFX.size)
                plen, blob_crc = _BLOB_PFX.unpack_from(head)
                if _BLOB_PFX.size + plen > length:
                    # a corrupt plen would drive the blob read negative
                    # and desync the stream — reject before any read
                    raise BadFrame(f"bad blob prefix on type {type_id}")
                pickled = await self.reader.readexactly(plen)
                blob_len = length - _BLOB_PFX.size - plen
                cls = _MSG_TYPES.get(type_id)
                if getattr(cls, "BLOB_VIEW_OK", False) \
                        and isinstance(self.reader, FrameReceiver):
                    # store/decode-lane blob: land in an uninitialized
                    # buffer (no memset pass over the data volume)
                    blob = await self.reader.readexactly(blob_len,
                                                         uninit=True)
                else:
                    blob = await self.reader.readexactly(blob_len)
                if crc and self.crc_enabled \
                        and self.crc_fn(pickled, self.crc_fn(head)) != crc:
                    raise BadFrame(f"crc mismatch on frame type {type_id}")
                if blob_crc and self.crc_enabled:
                    if self.crc_fn(blob) != blob_crc:
                        raise BadFrame(f"blob crc mismatch on type {type_id}")
                    blob_verified = True
                payload = pickled
            else:
                payload = await self.reader.readexactly(length)
                if crc and self.crc_enabled \
                        and self.crc_fn(payload) != crc:
                    raise BadFrame(f"crc mismatch on frame type {type_id}")
                if flags & FLAG_COMPRESSED:
                    payload = zlib.decompress(payload)
        except BaseException:
            self.messenger.dispatch_throttle.put(cost)
            raise
        perf = self.messenger.perf
        rx_dt = time.monotonic() - t_io
        perf.tinc("rx_io", rx_dt)
        perf.hinc("rx_io_us", rx_dt * 1e6)
        perf.inc("rx_bytes", _HDR.size + length)
        return (type_id, version, seq, payload, cost, blob,
                bool(flags & FLAG_FIXED), blob_verified)

    async def adopt_transport(self, reader, writer) -> None:
        """Adopt a fresh transport into this session and replay unacked
        frames (both directions of the reference's session reconnect:
        the initiator replays requests, the acceptor replays replies)."""
        old_writer = self.writer
        async with self._send_lock:
            self.reader = reader
            self.writer = writer
            self.closed = False
            self.transport_gen += 1
            try:
                old_writer.close()
            except Exception:
                pass
            replayed = 0
            with self.messenger.perf.time_avg("tx_io"):
                for _, data in list(self.unacked):
                    if isinstance(data, list):
                        self.writer.writelines(data)
                        replayed += sum(len(p) for p in data)
                    else:
                        self.writer.write(data)
                        replayed += len(data)
                await self.writer.drain()
            if replayed:
                self.messenger.perf.inc("tx_bytes", replayed)

    async def close(self, gen: Optional[int] = None) -> None:
        """Close the current transport.  With gen, only close if the
        transport hasn't been replaced since the caller observed it."""
        if gen is not None and gen != self.transport_gen:
            return
        if not self.closed:
            self.closed = True
            # senders parked on the pending flush window see the error
            # now; their frames replay from the unacked queue (lossless)
            self._fail_pending(ConnectionResetError("connection closed"))
            self._pin_replay_queue()
            self.writer.close()
            try:
                # bounded: wait_closed can block if the peer never reads
                await asyncio.wait_for(self.writer.wait_closed(), timeout=0.5)
            except Exception:
                pass


# -- messenger ---------------------------------------------------------------


class Messenger:
    """One per daemon.  dispatcher(conn, msg) is awaited per message
    (fast-dispatch style); receive-side bytes ride a dispatch throttle."""

    def __init__(self, name: str, conf: Optional[Any] = None,
                 entity_type: str = "client"):
        self.name = name
        self.conf = conf if conf is not None else {}
        self.entity_type = entity_type
        # resolve the frame checksum NOW (may g++-build the native
        # library, seconds): daemon construction, never the hot path
        checksum_kind()
        # the `wire` counter set (framing vs socket-io split; schema in
        # _build_wire_perf) — owning daemons add it to their collection
        self.perf = _build_wire_perf()
        self.dispatcher: Optional[Callable] = None
        # optional group-dispatch hook: group_dispatcher(conn, msgs) gets
        # a whole rx batch (frames that were already buffered) so the
        # daemon can hand stripe groups to the EC tier in one submit and
        # coalesce replies; falls back to per-message dispatcher when None
        self.group_dispatcher: Optional[Callable] = None
        self.server: Optional[asyncio.AbstractServer] = None
        self.addr: Optional[Tuple[str, int]] = None
        self._conns: Dict[Tuple[str, int], Connection] = {}
        self._conn_locks: Dict[Tuple[str, int], asyncio.Lock] = {}
        self._tasks: set = set()
        # reference defaults: clients are lossy, daemon peers lossless
        self.policies: Dict[str, Policy] = {
            "client": Policy.lossy_client(),
            "osd": Policy.lossless_peer(),
            "mon": Policy.lossless_peer(),
            "mgr": Policy.lossless_peer(),
        }
        self.dispatch_throttle = Throttle(
            f"{name}-dispatch", _cget(self.conf, "ms_dispatch_throttle_bytes", 100 << 20)
        )
        self._shutdown = False
        # cephx-lite state: this entity's service ticket + session key
        # (initiator side) and the rotating-secret keyring used to
        # validate presented tickets (acceptor side, daemons only)
        self.ticket: Optional[bytes] = None
        self.session_key: Optional[bytes] = None
        self.keyring = None  # Optional[TicketKeyring]
        # async callable: re-fetch rotating secrets on a validation miss
        # (a ticket sealed under a JUST-rotated secret must not be
        # refused until the periodic refresh happens to run)
        self.keyring_refresh: Optional[Callable] = None
        # session id -> session Connection, LRU-capped (peers come and go)
        self._sessions: "collections.OrderedDict[str, Connection]" = (
            collections.OrderedDict()
        )
        # colocated-daemon fast dispatch (LocalConnection): opt-in, and
        # only meaningful when BOTH endpoints run with it on
        self._local_fastpath = bool(
            _cget(self.conf, "ms_local_fastpath", False))
        self._local_conns: Dict[Tuple[str, int], LocalConnection] = {}
        self._loop: Optional[asyncio.AbstractEventLoop] = None

    def policy_for(self, peer_type: str) -> Policy:
        return self.policies.get(peer_type, Policy.lossy_client())

    # -- wire accounting -----------------------------------------------------

    def _note_tx(self, type_name: str, nbytes: int, framing_s: float) -> None:
        # tx_bytes is NOT counted here: _write_raw owns it, so acks and
        # session replays land in the socket totals too
        p = self.perf
        p.inc("tx_msgs")
        p.tinc("tx_framing", framing_s)
        p.ensure(f"tx_{type_name}", desc=f"{type_name} messages sent")
        p.ensure(f"tx_bytes_{type_name}", desc=f"{type_name} bytes sent")
        p.inc(f"tx_{type_name}")
        p.inc(f"tx_bytes_{type_name}", nbytes)

    def _note_rx(self, type_name: str, nbytes: int, framing_s: float) -> None:
        p = self.perf
        p.inc("rx_msgs")
        p.tinc("rx_framing", framing_s)
        p.ensure(f"rx_{type_name}", desc=f"{type_name} messages dispatched")
        p.ensure(f"rx_bytes_{type_name}",
                 desc=f"{type_name} bytes received")
        p.inc(f"rx_{type_name}")
        p.inc(f"rx_bytes_{type_name}", nbytes)

    # -- handshake -----------------------------------------------------------

    def _auth_tag(self, nonce: bytes, key: Optional[bytes] = None,
                  transcript: bytes = b"") -> str:
        """HMAC proof over a handshake nonce + negotiated-mode transcript:
        with a ticket session key when one is in play (cephx role), else
        the cluster bootstrap secret.  Binding the transcript (the secure
        flags both sides sent) into the tag makes mode-stripping by an
        active MITM detectable — the reference binds the negotiated mode
        into msgr2's signed handshake payload the same way."""
        if key is not None:
            return hmac.new(key, nonce + transcript, hashlib.sha256).hexdigest()
        secret = str(_cget(self.conf, "ms_auth_secret", "") or "")
        if not secret:
            return ""
        return hmac.new(secret.encode(), nonce + transcript,
                        hashlib.sha256).hexdigest()

    @staticmethod
    def _mode_transcript(initiator_secure: bool, acceptor_secure: bool) -> bytes:
        return f"|mode:i{int(bool(initiator_secure))}a{int(bool(acceptor_secure))}".encode()

    def _secure_key(self, session_key: Optional[bytes],
                    nonce_a: bytes, nonce_b: bytes) -> Optional[bytes]:
        """Key material for AES-GCM on-wire mode: the ticket session key,
        else a key derived from the cluster secret and both nonces."""
        if session_key is not None:
            return session_key
        secret = str(_cget(self.conf, "ms_auth_secret", "") or "")
        if not secret:
            return None
        return hmac.new(secret.encode(), b"onwire" + nonce_a + nonce_b,
                        hashlib.sha256).digest()

    def _wrap_secure(self, reader, writer, key: bytes):
        from ceph_tpu.rados.auth import SecureStream

        s = SecureStream(reader, writer, key)
        return s, s

    async def _handshake_out(self, reader, writer, lossless: bool,
                             session_id: str):
        """Returns (peer_name, resumed, peer_ckind, reader, writer) —
        the pair is AES-GCM wrapped when secure mode was negotiated."""
        secure_want = bool(_cget(self.conf, "ms_secure_mode", False))
        writer.write(BANNER)
        nonce = random.randbytes(16)
        hello = {"name": self.name, "type": self.entity_type,
                 "nonce": nonce.hex(), "auth": "",
                 "session": session_id, "lossless": lossless,
                 "secure": secure_want, "ckind": checksum_kind()}
        if self.ticket is not None:
            hello["ticket"] = self.ticket.hex()
        writer.write(json.dumps(hello).encode() + b"\n")
        await writer.drain()
        banner = await reader.readexactly(len(BANNER))
        if banner != BANNER:
            raise BadFrame("bad banner from peer")
        peer_hello = json.loads(await reader.readline())
        key = self.session_key if self.ticket is not None else None
        # both secure flags ride the HMAC material: a stripped flag makes
        # the tags disagree instead of silently downgrading to plaintext
        transcript = self._mode_transcript(secure_want,
                                           peer_hello.get("secure", False))
        # acceptor proves knowledge of the secret (or of OUR ticket's
        # session key, which only rotating-secret holders can open) by
        # tagging OUR nonce
        expect = self._auth_tag(nonce, key, transcript)
        if expect and not hmac.compare_digest(peer_hello.get("auth", ""), expect):
            raise PermissionError("peer failed auth (bad cluster secret)")
        # then we prove ourselves by tagging THEIR nonce
        try:
            their_nonce = bytes.fromhex(peer_hello.get("nonce", ""))
        except ValueError:
            raise BadFrame("garbled nonce in peer hello") from None
        tag = self._auth_tag(their_nonce, key, transcript)
        writer.write(json.dumps({"auth": tag}).encode() + b"\n")
        await writer.drain()
        fin = json.loads(await reader.readline())
        if not fin.get("ok", False):
            raise PermissionError("peer rejected our auth")
        if secure_want:
            # ms_secure_mode is a REQUIREMENT, not a preference: ending up
            # on plaintext (peer refused, or no key material to derive a
            # session key from) is a failed connection, never a downgrade
            skey = (self._secure_key(key, nonce, their_nonce)
                    if peer_hello.get("secure") else None)
            if skey is None:
                raise PermissionError(
                    "ms_secure_mode set but connection would be plaintext")
            reader, writer = self._wrap_secure(reader, writer, skey)
        return (peer_hello.get("name", ""), bool(peer_hello.get("resumed")),
                peer_hello.get("ckind", "zlib"), reader, writer)

    async def _handshake_in(self, reader, writer):
        """Returns (peer_name, peer_type, session, lossless, auth_kind,
        auth_entity_type, reader, writer) — the pair is AES-GCM wrapped
        when secure mode was negotiated.  ``auth_kind`` records HOW the
        peer proved itself ("ticket", "secret", or "none"): authorization
        decisions (e.g. who may fetch the rotating service secrets) key on
        it, not on the peer's self-declared type."""
        secure_want = bool(_cget(self.conf, "ms_secure_mode", False))
        banner = await reader.readexactly(len(BANNER))
        if banner != BANNER:
            raise BadFrame("bad banner from peer")
        peer_hello = json.loads(await reader.readline())
        writer.write(BANNER)
        nonce = random.randbytes(16)
        their_nonce = bytes.fromhex(peer_hello.get("nonce", ""))
        key: Optional[bytes] = None
        auth_kind = "none"
        auth_entity_type = ""
        ticket_hex = peer_hello.get("ticket", "")
        if ticket_hex and self.keyring is not None:
            tkt = self.keyring.validate(bytes.fromhex(ticket_hex))
            if tkt is None and self.keyring_refresh is not None:
                # maybe sealed under a rotation we haven't fetched yet
                try:
                    await asyncio.wait_for(self.keyring_refresh(), timeout=2.0)
                except Exception:
                    pass
                tkt = self.keyring.validate(bytes.fromhex(ticket_hex))
            if tkt is None:
                # a PRESENTED ticket must verify: silently falling back to
                # the shared-secret path would let an expired/forged
                # ticket ride a daemon's bootstrap credentials
                writer.write(json.dumps({"ok": False}).encode() + b"\n")
                await writer.drain()
                raise PermissionError(
                    f"invalid ticket from {peer_hello.get('name')}")
            key = tkt["session_key"]
            auth_kind = "ticket"
            auth_entity_type = tkt.get("type", "")
        # tell the initiator whether we still hold its session: if not, it
        # must reset its reply-dedupe floor (our out_seq restarts at 1)
        resumed = peer_hello.get("session", "") in self._sessions
        transcript = self._mode_transcript(peer_hello.get("secure", False),
                                           secure_want)
        hello = {"name": self.name, "type": self.entity_type,
                 "nonce": nonce.hex(),
                 "auth": self._auth_tag(their_nonce, key, transcript),
                 "resumed": resumed, "secure": secure_want,
                 "ckind": checksum_kind()}
        writer.write(json.dumps(hello).encode() + b"\n")
        await writer.drain()
        proof = json.loads(await reader.readline())
        expect = self._auth_tag(nonce, key, transcript)
        ok = not expect or hmac.compare_digest(proof.get("auth", ""), expect)
        writer.write(json.dumps({"ok": ok}).encode() + b"\n")
        await writer.drain()
        if not ok:
            raise PermissionError(f"auth failed for peer {peer_hello.get('name')}")
        if expect and auth_kind == "none":
            auth_kind = "secret"  # peer proved the cluster bootstrap secret
        if secure_want:
            # required, not best-effort (see _handshake_out)
            skey = (self._secure_key(key, their_nonce, nonce)
                    if peer_hello.get("secure") else None)
            if skey is None:
                raise PermissionError(
                    "ms_secure_mode set but connection would be plaintext")
            reader, writer = self._wrap_secure(reader, writer, skey)
        return (peer_hello.get("name", ""), peer_hello.get("type", "client"),
                peer_hello.get("session", ""), bool(peer_hello.get("lossless")),
                auth_kind, auth_entity_type,
                peer_hello.get("ckind", "zlib"), reader, writer)

    # -- lifecycle -----------------------------------------------------------

    async def disconnect(self, addr) -> None:
        """Drop the live outbound connection to ``addr`` (if any): the
        next send re-dials and re-runs the handshake — used when the
        credentials the old handshake was built on changed (e.g. a ticket
        was dropped to force bootstrap-secret auth)."""
        key = tuple(addr)
        conn = self._conns.pop(key, None)
        if conn is not None:
            await conn.close()

    async def bind(self, host: str = "127.0.0.1", port: int = 0) -> Tuple[str, int]:
        self.server = await asyncio.start_server(self._accept, host, port)
        self.addr = self.server.sockets[0].getsockname()[:2]
        if self._local_fastpath:
            self._loop = asyncio.get_running_loop()
            _LOCAL_REGISTRY[tuple(self.addr)] = self
        return self.addr

    @staticmethod
    def _negotiated_crc(peer_ckind: str):
        """Per-connection frame checksum: the fast shared resolver when
        both ends resolved the same KIND, zlib (which every build has)
        when they differ — a per-host native-build failure must degrade,
        never loop every frame through BadFrame."""
        return checksum if peer_ckind == checksum_kind() else zlib.crc32

    async def _accept(self, reader, writer) -> None:
        peer = writer.get_extra_info("peername")[:2]
        task = asyncio.current_task()
        self._tasks.add(task)
        try:
            try:
                (peer_name, peer_type, cookie, lossless, auth_kind,
                 auth_entity_type, peer_ckind,
                 reader, writer) = await self._handshake_in(reader, writer)
            except (PermissionError, BadFrame, ConnectionError, json.JSONDecodeError,
                    asyncio.IncompleteReadError, ValueError):
                writer.close()
                return
            if lossless and cookie:
                conn = self._sessions.get(cookie)
                if conn is not None:
                    # session reconnect: adopt the new socket, replay our
                    # un-acked frames (e.g. replies lost in the drop)
                    self._sessions.move_to_end(cookie)
                    await conn.adopt_transport(reader, writer)
                else:
                    conn = Connection(self, reader, writer, peer,
                                      Policy.lossless_peer(), peer_name)
                    self._sessions[cookie] = conn
                    while len(self._sessions) > MAX_SESSIONS:
                        _, evicted = self._sessions.popitem(last=False)
                        await evicted.close()
            else:
                conn = Connection(self, reader, writer, peer,
                                  Policy.lossy_client(), peer_name)
            # how the peer proved itself, for authorization decisions
            # (refreshed on every reconnect handshake)
            conn.auth_kind = auth_kind
            conn.auth_entity_type = auth_entity_type
            conn.crc_fn = self._negotiated_crc(peer_ckind)
            await self._serve(conn)
        finally:
            self._tasks.discard(task)

    # rx batch budget: how many already-buffered frames one dispatch
    # round may drain before acking (bounds latency of the first ack and
    # the throttle bytes held across a group dispatch)
    RX_BATCH_MSGS = 32
    RX_BATCH_BYTES = 32 << 20

    @staticmethod
    def _buffered_frame_len(reader) -> Optional[int]:
        """Payload length of a COMPLETE frame (header + payload) already
        buffered on the reader, else None — the rx batching predicate:
        batch only what needs no further network wait, so a half-arrived
        frame never stalls dispatch of messages already in hand."""
        try:
            if isinstance(reader, FrameReceiver):
                buf, off = reader._pending, reader._off
            elif isinstance(reader, asyncio.StreamReader):
                buf, off = reader._buffer, 0
            else:  # SecureStream
                buf, off = reader._buf, 0
            avail = len(buf) - off
            if avail < _HDR.size:
                return None
            (length,) = struct.unpack_from("<I", buf, off)
            return length if avail >= _HDR.size + length else None
        except (AttributeError, struct.error):
            return None

    async def _serve(self, conn: Connection) -> None:
        gen = conn.transport_gen
        conn.enable_fast_read()
        try:
            while not conn.closed and conn.transport_gen == gen:
                # drain every frame ALREADY buffered into one batch: one
                # dispatch round, one cumulative ack — under a sub-write
                # burst or an op-reply flood the per-message standalone
                # ack (and its flush) collapses into one frame
                batch: list = []  # (seq, msg)
                costs: list = []
                top_seq = 0
                try:
                    while (len(batch) < self.RX_BATCH_MSGS
                           and sum(costs) < self.RX_BATCH_BYTES):
                        if batch:
                            nxt = self._buffered_frame_len(conn.reader)
                            if nxt is None or not \
                                    self.dispatch_throttle.would_admit(nxt):
                                # nothing fully buffered, or the throttle
                                # would BLOCK — and its budget only
                                # returns after dispatch, which this
                                # batch still owes (self-deadlock)
                                break
                        (type_id, version, seq, payload, cost,
                         blob, fixed, verified) = await conn.read_frame()
                        if conn.transport_gen != gen:
                            self.dispatch_throttle.put(cost)
                            return  # transport replaced while suspended
                        if type_id == ACK_TYPE:
                            conn.handle_ack(struct.unpack("<Q", payload)[0])
                            self.dispatch_throttle.put(cost)
                            continue
                        if seq and seq <= conn.in_seq:
                            # replayed duplicate: re-ack (the original ack
                            # may have been lost) but don't re-dispatch
                            conn.queue_ack(seq)
                            self.dispatch_throttle.put(cost)
                            continue
                        try:
                            t_dec = time.monotonic()
                            msg = decode_message(type_id, version, payload,
                                                 blob, fixed)
                            if verified:
                                # the frame layer checked the blob's crc:
                                # handlers holding an app-level crc of the
                                # same bytes skip their own pass
                                msg._wire_verified = True
                            self._note_rx(type(msg).__name__,
                                          _HDR.size + cost,
                                          time.monotonic() - t_dec)
                        except Exception as e:
                            # undecodable (type/version skew): poison-
                            # discard so replay can't redeliver it forever
                            print(f"messenger {self.name}: dropping "
                                  f"undecodable frame type={type_id} "
                                  f"v={version}: {e}")
                            if seq:
                                conn.in_seq = seq
                                conn.queue_ack(seq)
                            self.dispatch_throttle.put(cost)
                            continue
                        batch.append((seq, msg))
                        costs.append(cost)
                        if seq:
                            top_seq = max(top_seq, seq)
                    if not batch:
                        continue
                    if len(batch) > 1:
                        self.perf.inc("rx_batches")
                        self.perf.hinc("rx_batch_msgs", len(batch))
                    try:
                        if self.group_dispatcher is not None \
                                and (len(batch) > 1
                                     or self.dispatcher is None):
                            # whole-group handoff: the daemon partitions
                            # the batch itself (stripe groups to the EC
                            # tier in one submit, coalesced replies).
                            # Singletons also route here when no plain
                            # dispatcher is installed — a group-only
                            # daemon must not have isolated frames
                            # consumed-and-acked undispatched.
                            await self.group_dispatcher(
                                conn, [m for _, m in batch])
                        elif self.dispatcher is not None:
                            for _, msg in batch:
                                try:
                                    await self.dispatcher(conn, msg)
                                except (asyncio.CancelledError,
                                        GeneratorExit):
                                    raise
                                except Exception:
                                    # a dispatcher bug must not wedge the
                                    # session into infinite redelivery
                                    traceback.print_exc()
                    except (asyncio.CancelledError, GeneratorExit):
                        raise
                    except Exception:
                        traceback.print_exc()
                    # ack AFTER dispatch: an ack'd frame is a consumed
                    # frame; one cumulative ack covers the whole batch
                    if top_seq:
                        conn.in_seq = max(conn.in_seq, top_seq)
                        conn.queue_ack(top_seq)
                finally:
                    for c in costs:
                        self.dispatch_throttle.put(c)
        except (asyncio.IncompleteReadError, ConnectionError, BadFrame):
            pass
        finally:
            await conn.close(gen)
            # lossless sessions reconnect from the initiator side so queued
            # frames (ours AND the acceptor's pending replies) replay even
            # when no further application send would trigger it
            if (conn.outbound and conn.policy.replay and conn.closed
                    and not self._shutdown):
                t = asyncio.get_running_loop().create_task(self._reconnect(conn))
                self._tasks.add(t)
                t.add_done_callback(self._tasks.discard)

    async def _reconnect(self, conn: Connection) -> None:
        delay = 0.02
        for _ in range(10):
            await asyncio.sleep(delay)
            delay = min(delay * 2, 1.0)
            if self._shutdown or self._conns.get(conn.peer) is not conn:
                return
            if not conn.closed:
                return  # something else already revived it
            try:
                await self.connect(conn.peer)
                return
            except (ConnectionError, OSError):
                continue
        # peer looks gone for good: forget the session (the cluster map's
        # failure detection is responsible for marking it down)
        if self._conns.get(conn.peer) is conn:
            self._conns.pop(conn.peer, None)

    # -- outbound ------------------------------------------------------------

    async def connect(self, addr: Tuple[str, int],
                      peer_type: str = "osd") -> Connection:
        """Get (or create) an ordered connection to a peer.  A cached dead
        lossless connection is revived in place (same session state, fresh
        transport, unacked replay); dead lossy connections are replaced.
        Serialized per addr so concurrent senders share one session."""
        addr = tuple(addr)
        conn = self._conns.get(addr)
        if conn is not None and not conn.closed:
            return conn
        lock = self._conn_locks.setdefault(addr, asyncio.Lock())
        async with lock:
            conn = self._conns.get(addr)
            if conn is not None and not conn.closed:
                return conn
            policy = self.policy_for(peer_type)
            reviving = conn is not None and conn.policy.replay
            session_id = conn.session_id if reviving else random.randbytes(8).hex()
            reader, writer = await asyncio.open_connection(*addr)
            try:
                (peer_name, resumed, peer_ckind, reader,
                 writer) = await self._handshake_out(
                    reader, writer, policy.replay, session_id
                )
            except Exception:
                writer.close()
                raise
            crc_fn = self._negotiated_crc(peer_ckind)
            if reviving:
                if not resumed:
                    # acceptor lost the session (restart/eviction): its reply
                    # stream restarts at seq 1, so our dedupe floor must too.
                    # Replayed frames may re-dispatch there (at-least-once
                    # across an acceptor restart, as in the reference — PG
                    # reqid dedupe above absorbs it).
                    conn.in_seq = 0
                conn.crc_fn = crc_fn
                await conn.adopt_transport(reader, writer)
            else:
                conn = Connection(self, reader, writer, addr, policy,
                                  peer_name, outbound=True)
                conn.crc_fn = crc_fn
                conn.session_id = session_id
                self._conns[addr] = conn
            # serve replies arriving on the outbound connection too
            task = asyncio.get_running_loop().create_task(self._serve(conn))
            self._tasks.add(task)
            task.add_done_callback(self._tasks.discard)
            return conn

    async def send(self, addr: Tuple[str, int], msg: Any, retries: int = 3,
                   peer_type: str = "osd") -> None:
        if self._local_fastpath:
            addr_t = tuple(addr)
            for _ in range(2):  # one retry: the peer may have re-bound
                peer = _LOCAL_REGISTRY.get(addr_t)
                if (peer is None or peer._shutdown
                        or not peer._local_fastpath
                        or peer._loop is not asyncio.get_running_loop()):
                    break  # not colocated (or another loop): real wire
                conn = self._local_conns.get(addr_t)
                if conn is None or conn.closed \
                        or conn.peer_messenger is not peer:
                    conn = LocalConnection(self, peer)
                    self._local_conns[addr_t] = conn
                try:
                    await conn.send(msg)
                    return
                except ConnectionError:
                    self._local_conns.pop(addr_t, None)
        last: Optional[Exception] = None
        for _ in range(retries + 1):
            try:
                conn = await self.connect(addr, peer_type)
                await conn.send(msg)
                return
            except PermissionError:
                raise
            except (ConnectionError, OSError) as e:
                last = e
                conn = self._conns.get(tuple(addr))
                if conn is not None and not conn.policy.replay:
                    self._conns.pop(tuple(addr), None)
        raise last  # type: ignore[misc]

    async def shutdown(self) -> None:
        self._shutdown = True
        if self.addr is not None \
                and _LOCAL_REGISTRY.get(tuple(self.addr)) is self:
            _LOCAL_REGISTRY.pop(tuple(self.addr), None)
        for lconn in list(self._local_conns.values()):
            await lconn.close()
        self._local_conns.clear()
        # cancel serve loops FIRST: in py3.12 Server.wait_closed() waits for
        # all connection handlers, so live inbound loops would deadlock it
        for t in list(self._tasks):
            t.cancel()
        for conn in list(self._conns.values()):
            await conn.close()
        for conn in list(self._sessions.values()):
            await conn.close()
        self._sessions.clear()
        if self.server is not None:
            self.server.close()
            try:
                await asyncio.wait_for(self.server.wait_closed(), timeout=1.0)
            except asyncio.TimeoutError:
                pass
