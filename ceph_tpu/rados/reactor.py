"""Sharded multi-reactor wire plane: reactor worker pool + colocated ring.

Role-equivalent of the reference's AsyncMessenger worker pool (reference
src/msg/async/AsyncMessenger.{h,cc}, Stack.h): a Messenger owns N reactor
workers (``ms_async_op_threads``), each a thread running its OWN event
loop and owning a SHARD of the sockets — connections are bound to a
worker by a stable hash of (peer addr, lane), the way
``AsyncMessenger::get_connection`` binds a ``Worker`` for a peer, so a
connection's socket work (framing, crc, sendmsg/recv, flush windows)
never migrates between reactors and needs no cross-thread locking of its
own state.  The daemon keeps its single home loop: dispatch hops back to
it (``run_coroutine_threadsafe``), so daemon state stays single-loop
while the wire bytes move in parallel — crc32c, memcpy and the socket
syscalls all release the GIL, which is where the parallel win lives in
this Python reproduction.

This module also carries the COLOCATED transport: daemons sharing one
host process (the vstart/test topology, the bench loopback arm)
negotiate, at connect time, an in-process ring instead of a TCP session
(``ms_colocated_ring``; the handshake hello carries a per-process token
— matching tokens on both ends mean the "wire" would be a kernel
loopback round-trip for bytes that never leave the process).  A
:class:`RingPipe` hands typed messages over by reference —
``BufferList``/memoryview blob fields stay views, nothing is framed,
crc'd or serialized — with the same delivery contract as the messenger's
local fastpath: per-connection order, exactly-once, messages immutable
once sent, control-plane payloads isolated by deep copy.  Negotiation
failure (token mismatch, knob off on either end, registry race) falls
back to the TCP session transparently; the caller cannot tell except by
the ``ring_msgs`` counter.
"""

from __future__ import annotations

import asyncio
import collections
import hashlib
import random
import threading
import traceback
from typing import Any, Callable, Dict, List, Optional, Tuple

# Per-process identity token: two messengers whose handshakes carry the
# same token ARE the same process, so an in-process ring is reachable.
# Random (not pid): pid alone would false-positive across containers or
# a recycled pid on the far end of a real wire.
PROC_TOKEN = random.randbytes(16).hex()


# -- reactor workers ---------------------------------------------------------


class ReactorWorker(threading.Thread):
    """One reactor: a thread running its own asyncio loop, owning a shard
    of sockets (the reference's msg/async Worker: private epoll, private
    event center).  Work enters via :meth:`spawn` (fire-and-forget task
    on this loop) or :meth:`run` (awaitable from another loop)."""

    def __init__(self, name: str, index: int):
        super().__init__(name=f"{name}-reactor-{index}", daemon=True)
        self.index = index
        self.loop = asyncio.new_event_loop()
        self._started = threading.Event()
        # shard accounting for dump_reactors / the bench's reactor
        # balance: plain ints under the GIL, written only from this
        # worker's own loop (sockets) or its owner (assignments)
        self.sockets = 0        # live connections owned by this shard
        self.accepted = 0       # inbound sockets this shard accepted
        self.dialed = 0         # outbound sockets dialed on this shard
        self.rx_msgs = 0        # messages decoded on this shard
        self.tx_flushes = 0     # flush windows written on this shard

    def run(self) -> None:  # thread body
        asyncio.set_event_loop(self.loop)
        self._started.set()
        try:
            self.loop.run_forever()
        finally:
            try:
                pending = asyncio.all_tasks(self.loop)
                for t in pending:
                    t.cancel()
                if pending:
                    self.loop.run_until_complete(
                        asyncio.gather(*pending, return_exceptions=True))
            except Exception:
                pass
            self.loop.close()

    def ensure_started(self) -> None:
        if not self.is_alive():
            self.start()
        self._started.wait(timeout=5.0)

    async def submit(self, coro) -> Any:
        """Run ``coro`` on this worker's loop, awaited from the caller's
        loop (no-op hop when the caller already runs here)."""
        if asyncio.get_running_loop() is self.loop:
            return await coro
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return await asyncio.wrap_future(fut)

    def spawn(self, coro) -> None:
        """Fire-and-forget a task on this worker's loop (thread-safe)."""
        if not self.loop.is_closed():
            self.loop.call_soon_threadsafe(
                lambda: self.loop.create_task(coro))

    def stop(self) -> None:
        if self._started.is_set() and not self.loop.is_closed():
            self.loop.call_soon_threadsafe(self.loop.stop)
            self.join(timeout=2.0)

    def dump(self) -> Dict[str, Any]:
        return {"id": self.index, "alive": self.is_alive(),
                "sockets": self.sockets, "accepted": self.accepted,
                "dialed": self.dialed, "rx_msgs": self.rx_msgs,
                "tx_flushes": self.tx_flushes}


class ReactorPool:
    """The messenger's worker pool (AsyncMessenger ``workers`` +
    ``get_worker`` role).  ``worker_for(addr, lane)`` is the STABLE HASH
    binding: the same (peer, lane) always lands on the same worker, so a
    lane's revival redials on the loop (thread mode) or re-delegates to
    the shard slot (process mode) that owns its session state.

    ``mode`` selects the execution substrate (``ms_reactor_mode``):

    - ``thread`` (default): N ReactorWorker threads, each its own event
      loop owning a socket shard — the r13 plane;
    - ``process``: N forked reactor worker PROCESSES
      (reactor_proc.ReactorProcessWorker), each owning its socket shard
      outright with its own interpreter and its own copy of the native
      wirepath; frames cross via shared-memory rings (shm_ring.py) into
      the daemon's single home-loop dispatch pump.  A dead worker slot
      respawns on demand (ensure_worker) and every fork is reaped."""

    def __init__(self, name: str, n_workers: int, mode: str = "thread",
                 use_native: bool = True):
        self.name = name
        self.mode = mode if mode in ("thread", "process") else "thread"
        self.n_workers = max(1, int(n_workers))
        if self.mode == "process":
            from ceph_tpu.rados.reactor_proc import ReactorProcessWorker

            self.workers: List[Any] = [
                ReactorProcessWorker(name, i, use_native=use_native)
                for i in range(self.n_workers)]
        else:
            self.workers = [
                ReactorWorker(name, i) for i in range(self.n_workers)]
        self._servers: List[Tuple[ReactorWorker, Any]] = []
        self._started = False
        # the owning daemon's Log (debug_ms douts); attached by the
        # messenger when the daemon wires its Context in
        self.log = None
        # process-mode accept fan-out state: the listening socket the
        # workers hold dups of, the parent-side accepted-fd callback,
        # and the home loop the ctrl readers are registered on
        self._listen_sock = None
        self._on_fd = None
        self._ctrl_loop = None

    def dout(self, level: int, message: str) -> None:
        log = self.log
        if log is not None:
            log.dout("ms", level, message)

    def start(self) -> None:
        if not self._started:
            self._started = True
            for w in self.workers:
                if self.mode == "process":
                    w.start()
                else:
                    w.ensure_started()
            self.dout(1, f"reactor pool {self.name}: {self.n_workers} "
                         f"{self.mode} workers started"
                      + (f" (pids {[w.pid for w in self.workers]})"
                         if self.mode == "process" else ""))

    def worker_for(self, addr: Tuple[str, int], lane: int = 0):
        key = f"{addr[0]}:{addr[1]}:{lane}".encode()
        h = int.from_bytes(hashlib.blake2b(key, digest_size=8).digest(),
                           "little")
        return self.workers[h % self.n_workers]

    # -- process mode --------------------------------------------------------

    def ensure_worker(self, worker) -> bool:
        """Process mode: make sure the slot has a live child, respawning
        (and reaping the old pid) after a worker death — the shard slot
        identity survives, the way a revived lane keeps its session."""
        if self.mode != "process":
            return True
        self.start()
        if worker.is_alive():
            return True
        self.dout(1, f"reactor pool {self.name}: worker {worker.index} "
                     f"died; respawning shard slot")
        try:
            worker.restart()
        except OSError:
            return False
        if self._listen_sock is not None:
            worker.listen(self._listen_sock)
        self._register_ctrl_reader(worker)
        return worker.is_alive()

    def serve_shards_process(self, base_sock, on_fd: Callable) -> None:
        """Process-mode inbound sharding: every worker gets a dup of the
        listening socket and accepts on it; accepted fds forward to the
        parent (``on_fd``) whose home loop runs the handshake — the
        parent owns auth/session state, the workers own the byte work
        once the connection is delegated."""
        import asyncio as _asyncio

        self.start()
        self._listen_sock = base_sock
        self._on_fd = on_fd
        self._ctrl_loop = _asyncio.get_event_loop()
        for w in self.workers:
            w.listen(base_sock)
            self._register_ctrl_reader(w)

    def _register_ctrl_reader(self, worker) -> None:
        """Watch the worker's ctrl socket for forwarded accepted fds."""
        loop = self._ctrl_loop
        if loop is None or worker.ctrl is None or loop.is_closed():
            return
        import socket as _socket

        ctrl = worker.ctrl
        fd = ctrl.fileno()

        def _on_readable(w=worker, c=ctrl, fdnum=fd):
            while True:
                try:
                    msg, fds, _fl, _ad = _socket.recv_fds(c, 65536, 8)
                except (BlockingIOError, InterruptedError):
                    return
                except OSError:
                    msg, fds = b"", []
                if not msg:
                    try:
                        loop.remove_reader(fdnum)
                    except (OSError, ValueError):
                        pass
                    return
                if b"accepted" in msg and fds and self._on_fd is not None:
                    w.accepted += 1
                    self._on_fd(fds[0], w)
                    for extra in fds[1:]:
                        import os as _os

                        _os.close(extra)
                else:
                    import os as _os

                    for f in fds:
                        _os.close(f)

        try:
            loop.add_reader(fd, _on_readable)
        except (OSError, ValueError):
            pass

    def counters_sum(self) -> Dict[str, int]:
        """Aggregate the per-process counter blocks (perf-dump seam)."""
        agg: Dict[str, int] = {}
        if self.mode != "process":
            return agg
        for w in self.workers:
            for k, v in w.counters_dict().items():
                agg[k] = agg.get(k, 0) + v
        return agg

    async def serve_shards(self, base_sock, accept_cb: Callable) -> None:
        """Register the listening socket with EVERY worker loop (dup'd
        fd per worker): whichever reactor's selector wins the accept
        race owns the new socket — inbound sockets shard across workers
        without a handoff (the reference's per-worker Processor).
        Thread mode only; process mode shards accepts through
        :meth:`serve_shards_process`."""
        self.start()
        for w in self.workers:
            dup = base_sock.dup()
            dup.setblocking(False)

            async def _serve(sock=dup, worker=w):
                def _cb(reader, writer, _w=worker):
                    _w.accepted += 1
                    return accept_cb(reader, writer)
                return await asyncio.start_server(_cb, sock=sock)

            server = await w.submit(_serve())
            self._servers.append((w, server))

    def shutdown(self) -> None:
        for w, server in self._servers:
            try:
                w.loop.call_soon_threadsafe(server.close)
            except Exception:
                pass
        self._servers.clear()
        if self.mode == "process":
            loop = self._ctrl_loop
            for w in self.workers:
                if loop is not None and w.ctrl is not None \
                        and not loop.is_closed():
                    try:
                        loop.remove_reader(w.ctrl.fileno())
                    except (OSError, ValueError):
                        pass
                # graceful stop + guaranteed reap: daemon shutdown must
                # leave no zombies (worker.shutdown SIGKILLs stragglers
                # and waitpids them)
                w.shutdown()
            self._listen_sock = None
            self._on_fd = None
            return
        for w in self.workers:
            w.stop()

    def dump(self) -> List[Dict[str, Any]]:
        return [w.dump() for w in self.workers]


# -- colocated in-process ring transport -------------------------------------

# ring id -> (initiator_rx pipe, acceptor_rx pipe) awaiting attachment.
# Registered by the ACCEPTOR during the handshake fin, claimed by the
# initiator immediately after (same process by construction).
_RING_REGISTRY: Dict[str, Tuple["RingPipe", "RingPipe"]] = {}
_RING_LOCK = threading.Lock()


class RingPipe:
    """One direction of a colocated ring: a bounded in-process slot ring
    handing message objects (and their BufferList/memoryview blob views)
    across by reference.  Loop-agnostic and thread-safe — the two ends
    may live on different event loops (daemon home loops, reactor
    workers), so waiters are woken through their OWN loop's
    ``call_soon_threadsafe``."""

    def __init__(self, capacity: int = 1024):
        self.capacity = max(1, int(capacity))
        self._dq: collections.deque = collections.deque()
        self._lock = threading.Lock()
        self._getters: List[Tuple[asyncio.AbstractEventLoop, asyncio.Future]] = []
        self._putters: List[Tuple[asyncio.AbstractEventLoop, asyncio.Future]] = []
        self.closed = False

    @staticmethod
    def _wake(waiters: List) -> None:
        while waiters:
            loop, fut = waiters.pop(0)

            def _set(f=fut):
                if not f.done():
                    f.set_result(None)

            try:
                if loop is asyncio.get_event_loop_policy().get_event_loop() \
                        and loop.is_running():
                    _set()
                else:
                    loop.call_soon_threadsafe(_set)
            except Exception:
                try:
                    loop.call_soon_threadsafe(_set)
                except Exception:
                    pass

    async def put(self, item: Any) -> None:
        """Append one message; parks when the ring is full (the bounded
        backpressure a full socket buffer gives the TCP path)."""
        while True:
            with self._lock:
                if self.closed:
                    raise ConnectionResetError("ring closed")
                if len(self._dq) < self.capacity:
                    self._dq.append(item)
                    getters, self._getters = self._getters, []
                else:
                    getters = None
                    loop = asyncio.get_running_loop()
                    fut: asyncio.Future = loop.create_future()
                    self._putters.append((loop, fut))
            if getters is not None:
                self._wake(getters)
                return
            await fut

    async def get(self) -> Any:
        while True:
            with self._lock:
                if self._dq:
                    item = self._dq.popleft()
                    putters, self._putters = self._putters, []
                else:
                    if self.closed:
                        raise ConnectionResetError("ring closed")
                    putters = None
                    loop = asyncio.get_running_loop()
                    fut: asyncio.Future = loop.create_future()
                    self._getters.append((loop, fut))
            if putters is not None:
                self._wake(putters)
                return item
            await fut

    def close(self) -> None:
        with self._lock:
            self.closed = True
            waiters = self._getters + self._putters
            self._getters, self._putters = [], []
        self._wake(waiters)

    def depth(self) -> int:
        return len(self._dq)


def ring_offer(capacity: int = 1024) -> Tuple[str, "RingPipe", "RingPipe"]:
    """Acceptor side: allocate a ring pair, register it, return
    (ring_id, my_rx, my_tx)."""
    ring_id = random.randbytes(8).hex()
    i_rx = RingPipe(capacity)   # acceptor tx -> initiator rx
    a_rx = RingPipe(capacity)   # initiator tx -> acceptor rx
    with _RING_LOCK:
        _RING_REGISTRY[ring_id] = (i_rx, a_rx)
    return ring_id, a_rx, i_rx


def ring_claim(ring_id: str) -> Optional[Tuple["RingPipe", "RingPipe"]]:
    """Initiator side: claim the offered ring -> (my_rx, my_tx), or None
    when the offer is gone (negotiation falls back to TCP)."""
    with _RING_LOCK:
        pair = _RING_REGISTRY.pop(ring_id, None)
    if pair is None:
        return None
    i_rx, a_rx = pair
    return i_rx, a_rx


def ring_abandon(ring_id: str) -> None:
    with _RING_LOCK:
        pair = _RING_REGISTRY.pop(ring_id, None)
    if pair is not None:
        for p in pair:
            p.close()


class RingConnection:
    """A colocated session over a RingPipe pair: the Connection surface
    (send/close/peer/auth metadata) with ZERO serialization — negotiated
    at connect time by :class:`Messenger`, transparently replacing the
    TCP transport when both ends share the process.  Delivery contract
    matches the local fastpath: per-connection order (one pump task on
    the owning messenger's home loop), exactly-once, dispatcher
    isolation, messages immutable once sent; control-plane payloads are
    pickled round-trip so a live mon object graph is never shared."""

    is_ring = True

    def __init__(self, messenger, peer: Tuple[str, int], peer_name: str,
                 rx: RingPipe, tx: RingPipe, outbound: bool,
                 auth_kind: str = "ring", auth_entity_type: str = ""):
        self.messenger = messenger
        self.peer = tuple(peer)
        self.peer_name = peer_name
        self.rx = rx
        self.tx = tx
        self.outbound = outbound
        self.auth_kind = auth_kind
        self.auth_entity_type = auth_entity_type
        self.closed = False
        from ceph_tpu.rados.messenger import Policy

        self.policy = Policy.lossless_peer()
        self._pump_task: Optional[asyncio.Task] = None

    def start_pump(self) -> None:
        """Serve inbound ring messages on the owning messenger's loop."""
        loop = self.messenger.home_loop or asyncio.get_running_loop()
        if loop is asyncio.get_running_loop():
            self._pump_task = loop.create_task(self._pump())
            self.messenger._tasks.add(self._pump_task)
            self._pump_task.add_done_callback(
                self.messenger._tasks.discard)
        else:  # messenger homed on another loop (reactor-side accept)
            loop.call_soon_threadsafe(self.start_pump)

    async def send(self, msg: Any) -> None:
        if self.closed:
            raise ConnectionResetError("ring connection closed")
        from ceph_tpu.rados import messenger as m

        cls = type(msg)
        fields = getattr(cls, "FIXED_FIELDS", None)
        when = getattr(cls, "FIXED_WHEN", None)
        if fields is None or (when is not None and not when(msg)):
            # control-plane payload: isolate the receiver's object graph
            # exactly as the pickled wire would (LocalConnection rule)
            import pickle

            msg = pickle.loads(pickle.dumps(msg, protocol=5))
        try:
            await self.tx.put(msg)
        except ConnectionResetError:
            self.closed = True
            raise
        self.messenger.perf.inc("ring_msgs")
        self.messenger.perf.inc("tx_msgs")
        p = self.messenger.perf
        name = type(msg).__name__
        p.ensure(f"tx_{name}", desc=f"{name} messages sent")
        p.inc(f"tx_{name}")

    async def _pump(self) -> None:
        while not self.closed and not self.messenger._shutdown:
            try:
                msg = await self.rx.get()
            except ConnectionResetError:
                break
            self.messenger.perf.inc("rx_msgs")
            disp = self.messenger.dispatcher
            if disp is None and self.messenger.group_dispatcher is not None:
                try:
                    await self.messenger.group_dispatcher(self, [msg])
                except (asyncio.CancelledError, GeneratorExit):
                    raise
                except Exception:
                    traceback.print_exc()
                continue
            if disp is None:
                continue
            try:
                await disp(self, msg)
            except (asyncio.CancelledError, GeneratorExit):
                raise
            except Exception:
                traceback.print_exc()
        self.closed = True

    async def close(self, gen: int = 0) -> None:
        self.closed = True
        self.tx.close()
        self.rx.close()
        if self._pump_task is not None:
            self._pump_task.cancel()

    def dump(self) -> Dict[str, Any]:
        return {"peer": list(self.peer), "peer_name": self.peer_name,
                "rx_depth": self.rx.depth(), "tx_depth": self.tx.depth(),
                "closed": self.closed}
