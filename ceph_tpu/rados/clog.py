"""Cluster log + crash telemetry plane.

Role-equivalent of the reference's LogClient/LogMonitor pair
(reference src/common/LogClient.cc, src/mon/LogMonitor.cc) and the crash
module (src/pybind/mgr/crash + the ceph-crash spool agent):

- ``LogClient``: every daemon owns one; ``clog.info/warn/error`` stamp a
  ``ClogEntry`` on a channel (``cluster`` by default, ``audit`` for admin
  commands), queue it, and a flush task batches pending entries into
  ``MLog`` frames sent to the mon.  Entries are ACKED (``MLogAck`` carries
  the highest seq the mon has durably taken) and everything unacked is
  resent next flush — the mon dedupes by (sender, seq), so mon failover
  and dropped acks cannot lose or double entries.

- ``LogMonitor``: the mon-side state machine — a bounded
  (``mon_cluster_log_entries``) tail of the cluster log that rides the
  mon's paxos snapshot, per-sender last-seq dedupe, the crash-report
  registry (``ceph crash ls/info/archive/prune``), and the RECENT_CRASH
  health check.  The Monitor streams newly committed entries to
  subscribed sessions (``ceph -w``).

- Crash telemetry: ``build_crash_report`` captures a dying daemon's
  ``Log.dump_recent`` ring at max verbosity + backtrace + identity into
  an ``MCrashReport``; when the mon is unreachable the report spools to
  a crash dir (cephadm crash-dir style) and replays at next boot.

The ``ClogEntry`` binary codec is append-only with per-record length
prefixes: new fields append at the record tail, old decoders skip the
remainder, and records from OLDER builds (shorter) decode with defaults —
the truncated-tail discipline every wire blob in this tree follows,
pinned by corpus goldens.
"""

from __future__ import annotations

import asyncio
import json
import os
import struct
import time
import traceback
import uuid
from collections import OrderedDict, deque
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

# clog priorities (reference CLOG_DEBUG..CLOG_ERROR, LogEntry.h)
CLOG_DEBUG = 0
CLOG_INFO = 1
CLOG_SEC = 2
CLOG_WARN = 3
CLOG_ERROR = 4

PRIO_NAMES = {CLOG_DEBUG: "DBG", CLOG_INFO: "INF", CLOG_SEC: "SEC",
              CLOG_WARN: "WRN", CLOG_ERROR: "ERR"}
PRIO_BY_NAME = {"debug": CLOG_DEBUG, "info": CLOG_INFO, "sec": CLOG_SEC,
                "warn": CLOG_WARN, "warning": CLOG_WARN,
                "error": CLOG_ERROR, "err": CLOG_ERROR}

# default retained cluster-log tail (reference mon_cluster_log_* family)
DEFAULT_LOG_ENTRIES = 500
# unarchived crashes newer than this raise RECENT_CRASH (reference
# mgr/crash warn_recent_interval: two weeks)
DEFAULT_CRASH_WARN_AGE = 14 * 24 * 3600.0
DEFAULT_CRASH_MAX = 64


def _cget(conf, key, default):
    try:
        v = conf.get(key, default)
    except Exception:
        return default
    return default if v is None else v


@dataclass
class ClogEntry:
    """One cluster-log line (reference LogEntry, src/common/LogEntry.h):
    who said it, on which channel, at what priority.  ``seq`` is the
    SENDER's monotonic sequence (the ack/dedupe key); ``idx`` is the
    mon-assigned global position (the watcher-stream cursor) — 0 until
    the LogMonitor takes the entry."""

    stamp: float = 0.0
    name: str = ""
    channel: str = "cluster"
    prio: int = CLOG_INFO
    seq: int = 0
    message: str = ""
    idx: int = 0

    def render(self) -> str:
        ts = time.strftime("%Y-%m-%dT%H:%M:%S", time.localtime(self.stamp))
        frac = f"{self.stamp % 1:.3f}"[1:]
        return (f"{ts}{frac} {self.name} [{PRIO_NAMES.get(self.prio, '?')}]"
                f" ({self.channel}) {self.message}")


# -- binary codec ------------------------------------------------------------
# blob = u8 version | u32 count | count x record
# record = u32 reclen | d stamp | s name | s channel | q prio | Q seq
#          | s message | Q idx
# (s = u32-length-prefixed utf8.)  APPEND-ONLY: new fields append inside
# the record; reclen lets old decoders skip them, and records from older
# builds (shorter) decode with defaults — corpus-golden-pinned.

_U8 = struct.Struct("<B")
_U32 = struct.Struct("<I")
_D = struct.Struct("<d")
_Q = struct.Struct("<q")
_QU = struct.Struct("<Q")
CLOG_CODEC_VERSION = 1


def _pack_s(s: str) -> bytes:
    b = (s or "").encode()
    return _U32.pack(len(b)) + b


def encode_entries(entries: List[ClogEntry]) -> bytes:
    parts = [_U8.pack(CLOG_CODEC_VERSION), _U32.pack(len(entries))]
    for e in entries:
        rec = b"".join((
            _D.pack(e.stamp), _pack_s(e.name), _pack_s(e.channel),
            _Q.pack(e.prio), _QU.pack(e.seq), _pack_s(e.message),
            _QU.pack(e.idx),
        ))
        parts.append(_U32.pack(len(rec)))
        parts.append(rec)
    return b"".join(parts)


def decode_entries(blob: bytes) -> List[ClogEntry]:
    if not blob:
        return []
    mv = memoryview(blob)
    off = 1  # version byte: layout within records is reclen-guarded
    (count,) = _U32.unpack_from(blob, off)
    off += 4
    out: List[ClogEntry] = []

    def _s(rec: memoryview, roff: int):
        (n,) = _U32.unpack_from(rec, roff)
        roff += 4
        return bytes(rec[roff:roff + n]).decode(), roff + n

    for _ in range(count):
        (reclen,) = _U32.unpack_from(blob, off)
        off += 4
        rec = mv[off:off + reclen]
        off += reclen
        e = ClogEntry()
        try:
            roff = 0
            e.stamp = _D.unpack_from(rec, roff)[0]
            roff += 8
            e.name, roff = _s(rec, roff)
            e.channel, roff = _s(rec, roff)
            e.prio = _Q.unpack_from(rec, roff)[0]
            roff += 8
            e.seq = _QU.unpack_from(rec, roff)[0]
            roff += 8
            e.message, roff = _s(rec, roff)
            e.idx = _QU.unpack_from(rec, roff)[0]
        except struct.error:
            pass  # truncated tail (older sender): remaining fields default
        out.append(e)
    return out


def encode_recent(ring) -> bytes:
    """The local Log ring ((stamp, subsys, level, message) tuples) as a
    ClogEntry blob — the crash report's max-verbosity history."""
    return encode_entries([
        ClogEntry(stamp=st, name="", channel=subsys, prio=lvl, message=msg)
        for st, subsys, lvl, msg in ring])


# -- LogClient ----------------------------------------------------------------


class LogClient:
    """Daemon-side cluster-log submitter (reference src/common/LogClient).

    Entries queue locally (bounded; overflow drops oldest and counts),
    the flush task batches them into MLog frames on a short cadence
    (errors kick an immediate flush), and unacked entries resend every
    flush until the mon acks their seq — mon-side (sender, seq) dedupe
    makes the resend idempotent.  Seqs start from a boot-time epoch so a
    restarted daemon reusing its name cannot collide with its past
    life's acked window."""

    def __init__(self, messenger, mons, name: str, conf=None,
                 local_log=None):
        self.messenger = messenger
        self.mons = mons  # MonTargets
        self.name = name
        self.conf = conf if conf is not None else {}
        self.local_log = local_log
        self._pending: "OrderedDict[int, ClogEntry]" = OrderedDict()
        self._max_pending = int(_cget(self.conf, "clog_max_pending", 2048))
        self._batch_max = 256
        self.dropped = 0
        self.sent = 0
        self.acked = 0
        # boot-time seq epoch (micros << 8): a restarted daemon reusing
        # its name starts past its old life's acked window, so the mon's
        # last_seq dedupe cannot swallow post-restart entries
        self._seq = int(time.time() * 1e6) << 8
        self._interval = float(
            _cget(self.conf, "mon_client_log_interval", 0.25))
        self._task: Optional[asyncio.Task] = None
        self._kick: Optional[asyncio.Event] = None
        self._stopped = False

    # -- emit -----------------------------------------------------------------

    def do_log(self, channel: str, prio: int, message: str) -> ClogEntry:
        if self._task is None and not self._stopped:
            # self-heal a client created before its event loop existed:
            # the first emit from inside a loop starts the flush task
            try:
                self.start()
            except RuntimeError:
                pass  # still no loop: entries queue for a later flush
        self._seq += 1
        e = ClogEntry(stamp=time.time(), name=self.name, channel=channel,
                      prio=prio, seq=self._seq, message=str(message))
        self._pending[e.seq] = e
        while len(self._pending) > self._max_pending:
            self._pending.popitem(last=False)
            self.dropped += 1
        if self.local_log is not None:
            # mirror into the daemon's own log (and its crash ring)
            self.local_log.dout(
                "clog", 1,
                f"[{channel} {PRIO_NAMES.get(prio, '?')}] {message}")
        if prio >= CLOG_ERROR and self._kick is not None:
            self._kick.set()
        return e

    def debug(self, message: str, channel: str = "cluster") -> None:
        self.do_log(channel, CLOG_DEBUG, message)

    def info(self, message: str, channel: str = "cluster") -> None:
        self.do_log(channel, CLOG_INFO, message)

    def warn(self, message: str, channel: str = "cluster") -> None:
        self.do_log(channel, CLOG_WARN, message)

    def error(self, message: str, channel: str = "cluster") -> None:
        self.do_log(channel, CLOG_ERROR, message)

    def audit(self, message: str, prio: int = CLOG_INFO) -> None:
        self.do_log("audit", prio, message)

    # -- ack / flush ----------------------------------------------------------

    @property
    def pending(self) -> int:
        return len(self._pending)

    def handle_ack(self, msg) -> None:
        """MLogAck: the mon durably holds everything <= last_seq."""
        if getattr(msg, "who", "") and msg.who != self.name:
            return
        last = int(getattr(msg, "last_seq", 0) or 0)
        for seq in [s for s in self._pending if s <= last]:
            self._pending.pop(seq, None)
            self.acked += 1

    async def flush_now(self) -> bool:
        """One send attempt of everything pending (oldest first, batch-
        bounded).  True when a batch went out on the wire; the ack (and
        the pending-drop) arrives via the daemon's dispatcher."""
        if not self._pending:
            return True
        from ceph_tpu.rados.types import MLog

        batch = list(self._pending.values())[: self._batch_max]
        try:
            await self.messenger.send(
                self.mons.current,
                MLog(who=self.name, entries=encode_entries(batch)))
            self.sent += len(batch)
            return True
        except (ConnectionError, OSError, asyncio.TimeoutError):
            self.mons.rotate()
            return False

    def start(self) -> None:
        if self._task is None:
            self._kick = asyncio.Event()
            self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        self._stopped = True
        if self._task is not None:
            await self.flush_now()  # best-effort final drain
            self._task.cancel()
            self._task = None

    async def _run(self) -> None:
        while not self._stopped:
            try:
                await asyncio.wait_for(self._kick.wait(),
                                       timeout=self._interval)
            except asyncio.TimeoutError:
                pass
            self._kick.clear()
            if self._pending:
                await self.flush_now()


# -- LogMonitor ---------------------------------------------------------------


class LogMonitor:
    """Mon-side cluster-log + crash state (reference src/mon/LogMonitor.cc
    + the mgr/crash module's registry).  Pure state machine: the Monitor
    owns paxos replication (this state rides its snapshot) and watcher
    streaming; everything here is synchronous and unit-testable."""

    def __init__(self, conf=None, local_log=None, name: str = "mon"):
        self.conf = conf if conf is not None else {}
        self.local_log = local_log
        self.name = name
        self.max_entries = int(
            _cget(self.conf, "mon_cluster_log_entries", DEFAULT_LOG_ENTRIES))
        self.entries: "deque[ClogEntry]" = deque(maxlen=self.max_entries)
        self.last_seq: Dict[str, int] = {}
        self._idx = 0
        self._own_seq = int(time.time() * 1000) << 16
        self.crashes: Dict[str, Dict] = {}
        self.crash_warn_age = float(
            _cget(self.conf, "mon_crash_warn_age", DEFAULT_CRASH_WARN_AGE))
        self.crash_max = int(
            _cget(self.conf, "mon_crash_max", DEFAULT_CRASH_MAX))
        # stored-ring byte budget per crash: the registry rides EVERY
        # paxos snapshot, so an unbounded dump_recent blob would be
        # re-pickled on every subsequent commit forever
        self.crash_recent_max = int(
            _cget(self.conf, "mon_crash_recent_max_bytes", 32 << 10))

    @property
    def last_idx(self) -> int:
        return self._idx

    # -- log ingest -----------------------------------------------------------

    def submit(self, who: str, entries: List[ClogEntry]) -> int:
        """Take a sender's batch: entries at or below the sender's acked
        seq are resends and drop; the rest get a global idx and join the
        tail.  Returns the sender's new last seq (the MLogAck value)."""
        last = self.last_seq.get(who, 0)
        for e in sorted(entries, key=lambda x: x.seq):
            if e.seq <= last:
                continue
            last = e.seq
            e.name = e.name or who
            self._append(e)
        if who:
            self.last_seq[who] = last
            while len(self.last_seq) > 1024:
                self.last_seq.pop(next(iter(self.last_seq)))
        return last

    def log(self, channel: str, prio: int, message: str,
            name: str = "") -> ClogEntry:
        """Mon-originated entry (mark-downs, boots, audit lines)."""
        self._own_seq += 1
        e = ClogEntry(stamp=time.time(), name=name or self.name,
                      channel=channel, prio=prio, seq=self._own_seq,
                      message=str(message))
        self._append(e)
        return e

    def _append(self, e: ClogEntry) -> None:
        self._idx += 1
        e.idx = self._idx
        self.entries.append(e)
        if self.local_log is not None:
            self.local_log.dout("clog", 2, e.render())

    # -- log queries ----------------------------------------------------------

    def tail(self, n: int = 0, level: Optional[int] = None,
             channel: str = "") -> List[ClogEntry]:
        """`ceph log last [n] [level] [channel]`: the newest n matching
        entries, oldest first (n<=0: everything retained)."""
        out = [e for e in self.entries
               if (level is None or e.prio >= level)
               and (not channel or e.channel == channel)]
        return out[-n:] if n and n > 0 else out

    def since(self, idx: int, level: Optional[int] = None,
              channel: str = "") -> List[ClogEntry]:
        """Entries with a global idx strictly past ``idx`` (the watcher
        stream cursor)."""
        return [e for e in self.entries
                if e.idx > idx
                and (level is None or e.prio >= level)
                and (not channel or e.channel == channel)]

    def channel_counts(self, level: int = CLOG_WARN) -> Dict[str, int]:
        """Per-channel count of retained entries at >= level (the BENCH
        record's cluster-log summary)."""
        out: Dict[str, int] = {}
        for e in self.entries:
            if e.prio >= level:
                out[e.channel] = out.get(e.channel, 0) + 1
        return out

    # -- crash registry -------------------------------------------------------

    def add_crash(self, report) -> bool:
        """Take an MCrashReport; False when the id is already known
        (spool replay / resend).  Oldest crashes prune past crash_max."""
        cid = report.crash_id
        if not cid or cid in self.crashes:
            return False
        recent = bytes(report.recent or b"")
        if len(recent) > self.crash_recent_max:
            # keep the NEWEST entries that fit the byte budget (the
            # moments before the crash are the valuable ones)
            ents = decode_entries(recent)
            while ents and len(recent) > self.crash_recent_max:
                ents = ents[max(1, len(ents) // 4):]
                recent = encode_entries(ents)
        self.crashes[cid] = {
            "crash_id": cid,
            "entity": report.entity,
            "stamp": float(report.stamp),
            "version": report.version,
            "exception": report.exception,
            "backtrace": report.backtrace,
            "recent": recent,
            "archived": False,
        }
        while len(self.crashes) > self.crash_max:
            oldest = min(self.crashes.values(), key=lambda c: c["stamp"])
            self.crashes.pop(oldest["crash_id"], None)
        return True

    def crash_ls(self, include_archived: bool = True) -> List[Dict]:
        rows = [
            {"crash_id": c["crash_id"], "entity": c["entity"],
             "stamp": c["stamp"], "exception": c["exception"],
             "archived": bool(c.get("archived"))}
            for c in self.crashes.values()
            if include_archived or not c.get("archived")
        ]
        rows.sort(key=lambda r: r["stamp"])
        return rows

    def crash_info(self, crash_id: str) -> Optional[Dict]:
        c = self.crashes.get(crash_id)
        if c is None:
            return None
        out = dict(c)
        out["recent"] = [
            {"stamp": e.stamp, "subsys": e.channel, "level": e.prio,
             "message": e.message}
            for e in decode_entries(c.get("recent") or b"")]
        return out

    def crash_archive(self, crash_id: str = "") -> int:
        """Archive one crash ('' = all): it stays listable but stops
        raising RECENT_CRASH.  Returns how many flipped."""
        n = 0
        for c in self.crashes.values():
            if (not crash_id or c["crash_id"] == crash_id) \
                    and not c.get("archived"):
                c["archived"] = True
                n += 1
        return n

    def crash_prune(self, keep_seconds: float) -> int:
        """Drop crashes older than ``keep_seconds`` (reference
        `ceph crash prune <keep>` keeps <keep> days)."""
        cutoff = time.time() - max(0.0, keep_seconds)
        dead = [cid for cid, c in self.crashes.items()
                if c["stamp"] < cutoff]
        for cid in dead:
            del self.crashes[cid]
        return len(dead)

    def health_checks(self) -> Dict[str, Dict]:
        """RECENT_CRASH (reference mgr/crash health warning): unarchived
        crashes newer than mon_crash_warn_age."""
        now = time.time()
        recent = [c for c in self.crashes.values()
                  if not c.get("archived")
                  and now - c["stamp"] < self.crash_warn_age]
        if not recent:
            return {}
        daemons = sorted({c["entity"] for c in recent})
        return {"RECENT_CRASH": {
            "severity": "warning",
            "count": len(recent),
            "summary": f"{len(recent)} daemons have recently crashed"
                       if len(recent) > 1 else
                       f"1 daemon has recently crashed",
            "detail": [f"{c['entity']} crashed at "
                       f"{time.strftime('%Y-%m-%dT%H:%M:%S', time.localtime(c['stamp']))}"
                       f": {c['exception']}" for c in recent[:16]],
        }}

    # -- snapshot (rides the mon's paxos state) -------------------------------

    def snapshot(self) -> Dict:
        return {
            "entries": [
                (e.stamp, e.name, e.channel, e.prio, e.seq, e.message,
                 e.idx) for e in self.entries],
            "last_seq": dict(self.last_seq),
            "idx": self._idx,
            "crashes": {cid: dict(c) for cid, c in self.crashes.items()},
        }

    def load(self, state: Optional[Dict]) -> None:
        """Adopt a committed snapshot, MERGING entries the local (leader)
        state appended after the snapshot was taken: a concurrent write's
        audit line must not vanish because another write's commit landed
        first.  Peons have no local appends, so this degrades to replace."""
        if not state:
            return
        snap = [ClogEntry(*t) for t in state.get("entries", [])]
        snap_idx = int(state.get("idx", 0))
        keep = [e for e in self.entries if e.idx > snap_idx]
        self.entries = deque(snap + keep, maxlen=self.max_entries)
        self.last_seq = dict(state.get("last_seq", {}))
        for e in keep:
            if e.name and e.seq:
                self.last_seq[e.name] = max(
                    self.last_seq.get(e.name, 0), e.seq)
        self._idx = max(self._idx, snap_idx)
        crashes = {cid: dict(c)
                   for cid, c in state.get("crashes", {}).items()}
        for cid, c in self.crashes.items():
            crashes.setdefault(cid, c)
        self.crashes = crashes


# -- crash capture + spool ----------------------------------------------------


def make_crash_id(stamp: Optional[float] = None) -> str:
    ts = time.strftime("%Y-%m-%d_%H:%M:%S",
                       time.gmtime(stamp if stamp is not None
                                   else time.time()))
    return f"{ts}Z_{uuid.uuid4().hex[:12]}"


def build_crash_report(exc: BaseException, entity: str,
                       version: str = "", log=None):
    """Capture a dying daemon's state into an MCrashReport: the full
    dump_recent ring at max verbosity (including the separately pinned
    error entries), the backtrace, and the daemon identity/version —
    the ceph-crash meta file, as a wire frame."""
    from ceph_tpu.rados.types import MCrashReport

    ring = log.dump_recent() if log is not None else []
    return MCrashReport(
        entity=entity,
        crash_id=make_crash_id(),
        stamp=time.time(),
        version=version,
        exception=repr(exc),
        backtrace="".join(traceback.format_exception(exc)),
        recent=encode_recent(ring),
    )


def spool_crash(crash_dir: str, report) -> str:
    """Persist a crash report the mon could not take (cephadm crash-dir
    style: one ``<crash_id>/meta`` JSON per crash); replayed at next
    boot by ``replay_crash_spool``."""
    d = os.path.join(crash_dir, report.crash_id)
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, "meta")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({
            "crash_id": report.crash_id,
            "entity": report.entity,
            "stamp": report.stamp,
            "version": report.version,
            "exception": report.exception,
            "backtrace": report.backtrace,
            "recent_hex": bytes(report.recent or b"").hex(),
        }, f)
    os.replace(tmp, path)
    return path


def list_spooled(crash_dir: str) -> List[Any]:
    """Spooled reports, oldest first (unreadable entries skipped)."""
    from ceph_tpu.rados.types import MCrashReport

    out = []
    if not crash_dir or not os.path.isdir(crash_dir):
        return out
    for name in sorted(os.listdir(crash_dir)):
        path = os.path.join(crash_dir, name, "meta")
        try:
            with open(path) as f:
                meta = json.load(f)
            out.append(MCrashReport(
                entity=meta.get("entity", ""),
                crash_id=meta.get("crash_id", name),
                stamp=float(meta.get("stamp", 0.0)),
                version=meta.get("version", ""),
                exception=meta.get("exception", ""),
                backtrace=meta.get("backtrace", ""),
                recent=bytes.fromhex(meta.get("recent_hex", ""))))
        except (OSError, ValueError, TypeError):
            continue
    out.sort(key=lambda r: r.stamp)
    return out


def clear_spooled(crash_dir: str, crash_id: str) -> None:
    d = os.path.join(crash_dir, crash_id)
    try:
        os.unlink(os.path.join(d, "meta"))
        os.rmdir(d)
    except OSError:
        pass


async def replay_crash_spool(crash_dir: str, send: Callable) -> int:
    """Boot-time spool replay: ``send(report)`` must return truthy on a
    durable mon ack; acked spool entries are removed.  Returns how many
    replayed."""
    n = 0
    for report in list_spooled(crash_dir):
        try:
            ok = await send(report)
        except Exception:
            ok = False
        if ok:
            clear_spooled(crash_dir, report.crash_id)
            n += 1
    return n


def describe_command(msg, max_len: int = 160) -> str:
    """One-line audit rendering of a mon write command: the type name
    plus EVERY scalar field (blobs/maps and empty strings elided) —
    what lands on the ``audit`` channel for every admin mutation.  An
    audit record favors completeness over brevity: dropping falsy
    values would erase `osd down 0`'s target (0 is a valid osd id)."""
    parts = []
    for k, v in vars(msg).items():
        if k in ("tid", "inner", "entries", "recent", "backtrace"):
            continue
        if isinstance(v, (str, int, float, bool)) and v != "":
            s = str(v)
            if len(s) > 48:
                s = s[:45] + "..."
            parts.append(f"{k}={s}")
    out = f"{type(msg).__name__} {' '.join(parts)}".strip()
    return out[:max_len]
