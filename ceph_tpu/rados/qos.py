"""Per-client dmClock QoS: tenant identity, profiles, and enforcement state.

Role-equivalent of the reference's mClock client-profile machinery
(reference src/osd/scheduler/mClockScheduler.{h,cc}: client_profile_id_map
keys a dmclock ClientInfo per client; external_client_infos hold the tag
state) plus the pool-level QoS knobs the mon distributes.  Three layers:

- **Identity**: every MOSDOp v6 carries the sender's entity name
  (``client.<class>.<id>``); :func:`tenant_class` extracts the tenant
  CLASS — the granularity profiles are declared at, so thousands of
  tenants share a handful of declared profiles while each still gets its
  OWN dmClock tag state (per-client isolation inside a class).

- **Profiles**: :func:`pool_qos` resolves a client's
  (reservation, weight, limit) from the pool's osdmap-distributed opts —
  ``qos_reservation`` / ``qos_weight`` / ``qos_limit`` are the pool-wide
  client defaults, ``qos_class:<name>`` = ``"r:w:l"`` overrides one
  tenant class — falling back to OSD config defaults.  The mon validates
  every value at ``pool set`` time (:func:`validate_pool_qos`), so a bad
  profile can never wedge admission cluster-wide.

- **Enforcement state**:

  * :class:`ClientRegistry` manages the per-client ``_MClockClass``
    states INSIDE ``MClockScheduler`` (scheduler.py): lazily created
    with the client's resolved profile, refreshed when the profile
    changes, and bounded — idle states past ``max_clients`` are pruned
    oldest-idle-first so millions of tenants cannot grow a shard's state
    without bound (tag state is worth at most ~1/limit seconds of
    memory; an evicted flooder re-earns its tags within one op).
  * :class:`QosTracker` is the OSD-level ADMISSION tracker feeding the
    saturation-shed decision: it observes every arriving client data op
    (pre-shard, full offered rate — per-shard scheduler states each see
    only ~1/n_shards of a client's traffic, so the shed decision cannot
    live there) and answers "who is the most over-limit client right
    now".  At ``osd_backoff_queue_depth`` saturation the OSD sheds THAT
    client via MOSDBackoff instead of blocking everyone (osd.py
    _op_backoff_reason); with nobody over limit the legacy
    block-the-arrival behavior is preserved.

Tag math (dmClock, after the mClock paper): per client c and op arrival
at time t,

    R_tag = max(R_tag + 1/reservation, t)     (0 reservation => never due)
    P_tag = max(P_tag + 1/weight,      t)
    L_tag = max(L_tag + 1/limit,       t)     (0 limit => unlimited)

Reservation/limit are in ops/sec (IOPS — tags advance by cost 1 per op;
the byte-cost dimension stays with the queue's budget throttle).  A
client whose offered rate exceeds its limit accumulates L_tag ahead of
the clock; ``L_tag - now`` is its *over-limit excess* in seconds — the
shed-ranking key.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ceph_tpu.common.perf_counters import PerfCounters, PerfCountersBuilder

# pool opts the mon validates and every OSD reads through pool.opts
# (reference pg_pool_t::opts QoS analog): defaults for every client of
# the pool, plus per-tenant-class overrides under "qos_class:<name>"
QOS_POOL_KEYS = ("qos_reservation", "qos_weight", "qos_limit",
                 "qos_burst")
QOS_CLASS_PREFIX = "qos_class:"


@dataclass(frozen=True)
class QosParams:
    """One dmClock profile: reservation (ops/sec guaranteed), weight
    (share of surplus), limit (ops/sec hard cap; 0 = unlimited), burst
    (seconds of rho/delta credit an idle client may bank — see
    module docstring tag math; 0 = strict pacing)."""

    reservation: float = 0.0
    weight: float = 1.0
    limit: float = 0.0
    burst: float = 0.0

    def encode(self) -> str:
        base = f"{self.reservation:g}:{self.weight:g}:{self.limit:g}"
        return base + (f":{self.burst:g}" if self.burst else "")

    def normalized(self, spread: int) -> "QosParams":
        """Cross-OSD profile normalization (the dmClock distributed-
        enforcement correction): a tenant whose primaries span N OSDs
        meets N independent enforcers, so each must grant 1/N of the
        declared rates or the tenant gets N x its nominal profile
        cluster-wide.  Reservation and limit divide by the primary
        spread; weight is a RATIO (per-OSD arbitration between local
        competitors) and burst is a TIME allowance — both stay."""
        spread = max(1, int(spread))
        if spread == 1:
            return self
        return QosParams(reservation=self.reservation / spread,
                         weight=self.weight,
                         limit=self.limit / spread,
                         burst=self.burst)


# the OSD-config fallback when a pool declares nothing (matches the
# scheduler's historic CLASS_CLIENT profile so QoS-less clusters behave
# exactly as before)
DEFAULT_CLIENT_QOS = QosParams(reservation=100.0, weight=10.0, limit=0.0)


def parse_class_profile(value: str) -> QosParams:
    """``"r:w:l"`` or ``"r:w:l:b"`` -> QosParams; raises ValueError on
    anything the mon must refuse (non-numeric, weight <= 0, negative
    rates/burst)."""
    parts = str(value).split(":")
    if len(parts) not in (3, 4):
        raise ValueError(f"qos profile {value!r} is not r:w:l[:b]")
    r, w, l = (float(p) for p in parts[:3])
    b = float(parts[3]) if len(parts) == 4 else 0.0
    if r < 0 or l < 0 or w <= 0 or b < 0:
        raise ValueError(
            f"qos profile {value!r}: need r>=0, w>0, l>=0, b>=0")
    return QosParams(reservation=r, weight=w, limit=l, burst=b)


def primary_spread(osdmap: Any, pool: Any) -> int:
    """How many distinct OSDs serve as primaries across one pool's PGs
    under ``osdmap`` — the cross-OSD normalization divisor.  A tenant's
    ops hash uniformly over the pool's PGs, so its offered load meets
    this many independent per-OSD enforcers."""
    primaries = set()
    for pg in range(pool.pg_num):
        acting = osdmap.pg_to_acting(pool, pg)
        p = osdmap.primary_of(acting, seed=(pool.pool_id << 20) | pg)
        if p is not None:
            primaries.add(p)
    return max(1, len(primaries))


def validate_pool_qos(key: str, value: str) -> bool:
    """Mon-side ``pool set`` validation for the QoS opt family; False
    refuses the set (the mon replies with the unchanged map)."""
    try:
        if key == "qos_weight":
            return float(value) > 0
        if key in ("qos_reservation", "qos_limit", "qos_burst"):
            return float(value) >= 0
        if key.startswith(QOS_CLASS_PREFIX):
            name = key[len(QOS_CLASS_PREFIX):]
            # "|" is the optracker class-ring key separator
            # (cls:<name>|<phase>): a class name carrying it would
            # mislabel the per-class percentile reduction
            if not name or ":" in name or "|" in name:
                return False
            parse_class_profile(value)
            return True
    except (TypeError, ValueError):
        return False
    return False


def qos_op_cost(nbytes: int, conf: Optional[Any] = None) -> float:
    """Byte-COST of one op in dmClock tag units (IOPS-equivalents): a
    B-byte op costs ``1 + B / osd_qos_cost_per_io`` — the base IO plus a
    per-byte increment normalized to the configured bytes-per-IO.  This
    closes the bandwidth-hog hole of pure per-op tagging: a tenant
    issuing few LARGE ops (e.g. 25 x 4MiB/s against a 100 ops/s limit)
    tags as its true IOPS-equivalent load instead of escaping its limit
    (reference mClock cost model: osd_mclock_cost_per_io +
    cost_per_byte, src/osd/scheduler/mClockScheduler.cc
    calc_scaled_cost).  ``osd_qos_cost_per_io = 0`` restores pure
    per-op tagging.

    Writes are costed at ARRIVAL (the payload length is in hand).
    Reads carry no payload at arrival, so the OSD charges the
    admission tracker the byte increment at REPLY time (osd.py read
    path) — the shed ranking sees a read hog's true bandwidth; the
    per-client scheduler tags for reads stay per-op (enqueue time
    cannot know the response size)."""
    conf = conf or {}
    try:
        per_io = float(conf.get("osd_qos_cost_per_io", 65536) or 0)
    except (TypeError, ValueError):
        per_io = 65536.0
    if per_io <= 0 or nbytes <= 0:
        return 1.0
    return 1.0 + nbytes / per_io


def tenant_class(client: str) -> str:
    """Tenant class of an entity name: ``client.<class>.<id>`` -> the
    middle token; two-part names (``client.17``) and anonymous ("") map
    to the default class ''."""
    if not client:
        return ""
    parts = client.split(".")
    return parts[1] if len(parts) >= 3 else ""


def pool_qos(pool: Any, client: str,
             conf: Optional[dict] = None) -> QosParams:
    """Resolve one client's profile from the pool's opts: the tenant
    class's ``qos_class:<name>`` override when declared, else the
    pool-wide ``qos_reservation``/``qos_weight``/``qos_limit`` defaults,
    else the OSD config defaults.  Never raises — the mon validated the
    opts, but a pre-validation store must not wedge admission."""
    conf = conf or {}
    opts = getattr(pool, "opts", None) or {}
    cls = tenant_class(client)
    if cls:
        override = opts.get(QOS_CLASS_PREFIX + cls)
        if override is not None:
            try:
                return parse_class_profile(override)
            except ValueError:
                pass

    def _num(key: str, conf_key: str, default: float) -> float:
        v = opts.get(key)
        if v is None:
            v = conf.get(conf_key, default)
        try:
            return float(v)
        except (TypeError, ValueError):
            return default
    return QosParams(
        reservation=_num("qos_reservation", "osd_qos_default_reservation",
                         DEFAULT_CLIENT_QOS.reservation),
        weight=max(1e-9, _num("qos_weight", "osd_qos_default_weight",
                              DEFAULT_CLIENT_QOS.weight)),
        limit=_num("qos_limit", "osd_qos_default_limit",
                   DEFAULT_CLIENT_QOS.limit),
        burst=max(0.0, _num("qos_burst", "osd_qos_burst_allowance",
                            DEFAULT_CLIENT_QOS.burst)),
    )


@dataclass
class ClientState:
    """dmClock tag state + FIFO for one scheduling class — the shape
    scheduler.MClockScheduler arbitrates over (its historic
    ``_MClockClass``), shared by op classes and per-client states."""

    reservation: float  # ops/sec guaranteed
    weight: float  # share when capacity remains
    limit: float  # ops/sec cap (0 = unlimited)
    r_tag: float = 0.0
    p_tag: float = 0.0
    l_tag: float = 0.0
    # rho/delta burst allowance (seconds): how far behind `now` an idle
    # state's LIMIT tag may fall — banked credit for burst*limit
    # immediately-eligible ops (R/P stay clamped to now: banked
    # reservation credit would invert the reservation guarantee)
    burst: float = 0.0
    queue: List[Any] = field(default_factory=list)
    last_active: float = 0.0

    def apply_params(self, params: QosParams) -> None:
        """Refresh r/w/l/burst in place (a `pool set` mid-stream applies
        to live states; accumulated tags keep their meaning — they are
        absolute times)."""
        if (self.reservation, self.weight, self.limit, self.burst) != (
                params.reservation, params.weight, params.limit,
                params.burst):
            self.reservation = params.reservation
            self.weight = max(1e-9, params.weight)
            self.limit = params.limit
            self.burst = max(0.0, params.burst)


class ClientRegistry:
    """Per-client ClientStates inside one MClockScheduler shard
    (reference client_profile_id_map).  Bounded: when more than
    ``max_clients`` states exist, idle ones (empty queue) are pruned
    oldest-``last_active``-first; states with queued ops are never
    pruned."""

    def __init__(self, max_clients: int = 1024, perf=None):
        self.max_clients = max(1, int(max_clients))
        self.states: Dict[str, ClientState] = {}
        self.perf = perf

    def get(self, client: str, params: QosParams,
            now: float) -> ClientState:
        st = self.states.get(client)
        if st is None:
            if len(self.states) >= self.max_clients:
                self._prune()
            st = self.states[client] = ClientState(
                reservation=params.reservation,
                weight=max(1e-9, params.weight),
                limit=params.limit,
                burst=max(0.0, params.burst))
        else:
            st.apply_params(params)
        st.last_active = now
        return st

    def _prune(self) -> None:
        idle = sorted((c for c, s in self.states.items() if not s.queue),
                      key=lambda c: self.states[c].last_active)
        # drop the oldest-idle half: amortizes the sort over many creates
        for c in idle[:max(1, len(idle) // 2)]:
            del self.states[c]
            if self.perf is not None:
                self.perf.inc("qos_evicted")

    def __len__(self) -> int:
        return len(self.states)


class QosTracker:
    """OSD-level admission tracker: per-client L-tags over the FULL
    offered rate, feeding the saturation-shed decision (who is the most
    over-limit client).  Thread-light (asyncio single-loop callers);
    bounded like the registry."""

    def __init__(self, max_clients: int = 4096,
                 clock=time.monotonic, perf=None,
                 arrears_cap: float = 2.0):
        self.max_clients = max(1, int(max_clients))
        self.clock = clock
        self.perf = perf
        # ceiling on accumulated over-limit arrears (seconds the L-tag
        # may run ahead of the clock): arrivals are observed even while
        # being shed — the OFFERED rate is the shed-ranking signal — so
        # without the cap a sustained flood would bank minutes of
        # arrears and keep an ex-flooder shed long after it quieted
        self.arrears_cap = max(0.0, float(arrears_cap))
        # client -> [l_tag, limit, last_active]
        self._state: Dict[str, List[float]] = {}
        # max-L-tag candidate: all L-tags live on the same clock axis,
        # so the largest L-tag IS the most over-limit client — observe()
        # maintains it incrementally and should_shed() answers in O(1)
        # (the shed gate runs per arriving op exactly while the OSD is
        # saturated, the worst moment for an O(clients) scan); a stale
        # candidate (pruned / gone unlimited) falls back to one scan
        self._worst: Optional[str] = None

    def observe(self, client: str, params: QosParams,
                cost: float = 1.0) -> None:
        """One arriving op from ``client`` under ``params``; advances
        its limit tag (no-op for unlimited clients beyond liveness
        bookkeeping)."""
        if not client:
            return
        now = self.clock()
        st = self._state.get(client)
        if st is None:
            if len(self._state) >= self.max_clients:
                self._prune(now)
            # a fresh (or long-idle, pruned) client opens with its full
            # burst credit banked — the same floor the update applies
            st = self._state[client] = [
                now - max(0.0, params.burst), params.limit, now]
        st[2] = now
        if params.limit > 0:
            st[1] = params.limit
            # the rho/delta burst floor: an idle client's L-tag may lag
            # `now` by up to burst seconds (banked credit for
            # burst*limit immediate ops) instead of clamping to now
            st[0] = min(max(st[0] + cost / params.limit,
                            now - max(0.0, params.burst)),
                        now + self.arrears_cap)
            w = self._state.get(self._worst) if self._worst else None
            if w is None or w[1] <= 0 or st[0] >= w[0]:
                self._worst = client
        # an op resolved through an UNLIMITED pool must not launder the
        # client's arrears (state is per client, params are per pool: a
        # flooder with access to any limit-free pool would reset its
        # L-tag with one op and dodge the QoS-directed shed forever) —
        # the limit and tag stand; arrears decay on their own, bounded
        # by arrears_cap, if the client was genuinely reconfigured

    def _prune(self, now: float) -> None:
        # evict the least-recently-active half; an evicted flooder
        # rebuilds its excess within ~limit ops, so eviction cannot be
        # used to launder a sustained overload
        victims = sorted(self._state, key=lambda c: self._state[c][2])
        for c in victims[:max(1, len(victims) // 2)]:
            del self._state[c]

    def excess(self, client: str) -> float:
        """Seconds of accumulated over-limit arrears for one client
        (<= 0: within limit)."""
        st = self._state.get(client)
        if st is None or st[1] <= 0:
            return 0.0
        return st[0] - self.clock()

    def worst_over_limit(self, grace: float = 0.0) -> Tuple[Optional[str], float]:
        """(client, excess) of the most over-limit client with excess >
        grace, or (None, 0.0) when every client is within its limit.
        O(1) via the max-L-tag candidate; falls back to one scan when
        the candidate went stale (pruned or no longer limited)."""
        now = self.clock()
        w = self._state.get(self._worst) if self._worst else None
        if w is not None and w[1] > 0:
            e = w[0] - now
            # the candidate holds the MAX L-tag: within limit => all are
            return (self._worst, e) if e > grace else (None, 0.0)
        # candidate stale (pruned): one rebuild scan.  The new candidate
        # is the max-L-tag client REGARDLESS of grace — storing None for
        # a within-grace max would re-scan on every saturated arrival,
        # exactly the hot path the candidate exists to protect.
        self._worst = None
        worst, worst_tag = None, 0.0
        for c, st in self._state.items():
            if st[1] <= 0:
                continue
            if worst is None or st[0] > worst_tag:
                worst, worst_tag = c, st[0]
        self._worst = worst
        if worst is not None and worst_tag - now > grace:
            return worst, worst_tag - now
        return None, 0.0

    def should_shed(self, client: str,
                    grace: float = 0.25) -> Tuple[bool, bool]:
        """Saturation-shed decision for one arriving op: (shed,
        qos_directed).  qos_directed=True when an over-limit client
        exists — then only ops of over-limit clients are shed (the
        reserved tenant sails through); False falls back to the legacy
        shed-the-arrival behavior (no identities / nobody over limit)."""
        worst, _ = self.worst_over_limit(grace)
        if worst is None:
            return True, False
        return self.excess(client) > grace, True

    def dump(self) -> Dict[str, Dict[str, float]]:
        now = self.clock()
        return {c: {"limit": st[1],
                    "excess_s": round(st[0] - now, 6) if st[1] > 0 else 0.0,
                    "idle_s": round(now - st[2], 3)}
                for c, st in self._state.items()}

    def __len__(self) -> int:
        return len(self._state)


def build_scheduler_perf() -> PerfCounters:
    """The ``osd_scheduler`` counter set — per-class queue flow and the
    dmClock serving split, registered with the OSD collection (rides
    perf dump -> mgr /metrics -> the BENCH record).  Schema:

      enqueue_<class> / dequeue_<class>  u64   ops through the sharded
                                               queue per op class
      queue_depth                        u64   ops queued now (gauge)
      qos_clients                        u64   per-client dmClock states
                                               alive across shards (gauge)
      served_reservation                 u64   dequeues granted by a due
                                               R-tag (guaranteed IOPS)
      served_weight                      u64   dequeues granted by P-tag
                                               order (surplus sharing)
      served_fallback                    u64   work-conserving dequeues
                                               (everything over limit)
      qos_shed                           u64   saturation sheds aimed at
                                               the most over-limit client
      qos_evicted                        u64   idle per-client states
                                               pruned by the bound
    """
    b = PerfCountersBuilder("osd_scheduler")
    for cls in ("client", "recovery", "rebalance", "scrub", "best_effort"):
        b.add_u64_counter(f"enqueue_{cls}", f"{cls} ops enqueued")
        b.add_u64_counter(f"dequeue_{cls}", f"{cls} ops dequeued")
    b.add_u64("queue_depth", "ops queued across shards (gauge)")
    b.add_u64("qos_clients", "per-client dmClock states alive (gauge)")
    b.add_u64_counter("served_reservation",
                      "dequeues granted by a due reservation tag")
    b.add_u64_counter("served_weight",
                      "dequeues granted by weighted sharing")
    b.add_u64_counter("served_fallback",
                      "work-conserving dequeues (all classes over limit)")
    b.add_u64_counter("qos_shed",
                      "saturation sheds aimed at the most over-limit "
                      "client (MOSDBackoff)")
    b.add_u64_counter("qos_evicted", "idle per-client states pruned")
    return b.create_perf_counters()
