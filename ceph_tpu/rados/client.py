"""RADOS client: computes placement itself and talks straight to primaries.

Role-equivalent of librados + Objecter (reference src/osdc/Objecter.cc:2257
op_submit / _calc_target): fetch the OSDMap from the mon, map
object -> PG -> primary locally, send the op to the primary, and on failure
refetch the map and resend (the Objecter's retry-across-epochs behavior,
idempotent by reqid).

Resend/backoff discipline (the Objecter-grade op-resilience layer):

- Every data op gets ONE reqid for its whole lifetime and a persistent
  in-flight record (target pg/primary, epoch the target was computed on,
  deadline).  The OSD's PG log dedupes by reqid, so resends are
  exactly-once no matter how many transports they cross.
- Ops RESEND, they do not fail, on transient trouble: wrong-primary /
  degraded replies (typed -ESTALE/-EAGAIN, with the reply's epoch as a
  re-target fence), transport death, per-attempt reply timeouts, and map
  epoch bumps (a refresh that moves an in-flight op's primary wakes its
  reply wait immediately — the Objecter's _scan_requests resend).
  Retry pacing is capped exponential backoff with jitter
  (client_backoff_base/_cap); only DEFINITIVE typed answers (-ENOENT,
  -EPERM, ...) or the op deadline (client_op_deadline) surface errors.
- MOSDBackoff: a blocked PG (peering below min_size, saturated dispatch
  queue) parks every op targeting it until the matching unblock — or
  until the block's duration expires / a map change moves the primary
  (the liveness bounds for a primary that dies holding blocks).
- Paused maps: while the osdmap carries "pausewr"/"full" (writes) or
  "pauserd" (reads), matching ops QUEUE and poll for the map that lifts
  the gate instead of failing (Objecter pauserd/pausewr handling).

The `objecter` perf set counts all of it (resends, timeouts,
backoffs_received, backoff_wait_s, paused_ops, map_kicks); read it via
``perf_dump()``."""

from __future__ import annotations

import asyncio
import errno
import random
import time
import uuid
from typing import Dict, List, Optional, Tuple

from ceph_tpu.common.perf_counters import PerfCounters, PerfCountersBuilder
from ceph_tpu.common.tracing import Tracer
from ceph_tpu.rados.clog import ClogEntry, LogClient, decode_entries
from ceph_tpu.rados.messenger import BufferList, Messenger
from ceph_tpu.rados.monclient import MonTargets
from ceph_tpu.rados.types import (
    MAuthTicket,
    MAuthTicketReply,
    MCommand,
    MCommandReply,
    MConfigGet,
    MCrashQuery,
    MCrashQueryReply,
    MGetHealth,
    MHealthMute,
    MHealthReply,
    MLog,
    MLogAck,
    MLogReply,
    MLogSubscribe,
    MNotifyAck,
    MWatchNotify,
    MConfigReply,
    MConfigSet,
    MCreatePool,
    MCreatePoolReply,
    MDeletePool,
    MGetMap,
    MMapReply,
    MOSDBackoff,
    MOSDSetFlag,
    MSetFullRatio,
    is_delete_only_multi,
    MPoolSet,
    MSetUpmap,
    MMarkDown,
    MOsdMembership,
    MCrushOp,
    MCrushOpReply,
    MOsdPredicate,
    MOsdPredicateReply,
    MOSDOp,
    MOSDOpReply,
    MSnapOp,
    MSnapOpReply,
    OSDMap,
    SNAP_SEP,
)


class RadosError(Exception):
    """Client-visible failure.  ``code`` is the negative errno from the
    reply (0 when the failure had no typed reply, e.g. transport errors),
    so services can branch on errno instead of message text."""

    def __init__(self, message: str, code: int = 0):
        super().__init__(message)
        self.code = code


# reply codes that are ANSWERS, not failures: the primary executed the op
# and the result is "no" — retrying would turn every expected miss into a
# multi-second epoch-barrier stall (reference: definitive errno from
# PrimaryLogPG are returned to the caller, not retried by the Objecter)
_DEFINITIVE_CODES = frozenset((
    -errno.ENOENT, -errno.EOPNOTSUPP, -errno.EINVAL, -errno.EPERM,
    -errno.EBADMSG, -errno.ENXIO, -errno.EEXIST, -errno.ERANGE,
    # compound-op asserts: cmpxattr mismatch / missing xattr are verdicts
    # about object state, not transients (reference rados_exec rvals)
    -errno.ECANCELED, -errno.ENODATA,
    # capacity: a FULL acting member / failsafe-full store refused the
    # write — resending into a full cluster cannot succeed (the cure is
    # deleting, which stays exempt from every fullness gate), so ENOSPC
    # surfaces typed and FAST instead of burning the op deadline
    -errno.ENOSPC,
))
# -ESTALE (not primary): the placement this op was computed on is WRONG —
# re-target only after fencing past our own epoch (a newer map exists or
# is imminent; recomputing on the stale one re-picks the same primary).
# -EAGAIN (degraded below min_size / shards unavailable): the cure is a
# MAP CHANGE (failure detection marking the dead member down, recovery
# re-seating shards) — fence past our epoch and wait for it, or the
# retries burn out inside the detection grace window.
# -EBUSY (sub-write ack shortfall): the write partially landed and a
# plain resend usually completes it — retry promptly WITHOUT an epoch
# wait (one dropped ack on a healthy cluster must not pay a multi-second
# epoch poll).

# ops that mutate object state: gated by the map's write-pause flags
# ("pausewr"/"full"); reads pause only under "pauserd".  Class calls and
# watch registration count as writes (the reference flags
# CEPH_OSD_OP_CALL/WATCH as WR ops — cls_rbd/cls_rgw mutations ride
# "call", so excluding it would let metadata writes through a write
# freeze).
_WRITE_OPS = frozenset(("write", "delete", "multi", "snap-trim",
                        "call", "watch", "unwatch"))


class _OpKick(Exception):
    """Internal: an in-flight op's reply wait was woken early — the map
    epoch advanced and its target moved, or an MOSDBackoff landed for its
    PG.  The submit loop re-targets (or parks) immediately instead of
    waiting out the reply timeout."""


class _OpRecord:
    """Persistent in-flight op record (the Objecter's op_t role): one per
    logical op for its whole lifetime, across every resend."""

    __slots__ = ("op", "pg", "target", "epoch", "deadline", "fut",
                 "paused_counted")

    def __init__(self, op: MOSDOp, deadline: float):
        self.op = op
        self.pg: Optional[int] = None          # target pg (last send)
        self.target: Optional[int] = None      # primary osd (last send)
        self.epoch = 0                         # epoch target was computed on
        self.deadline = deadline               # monotonic() ceiling
        self.fut: Optional[asyncio.Future] = None  # live reply wait
        self.paused_counted = False            # paused_ops bumped once


def _build_objecter_perf() -> PerfCounters:
    """The `objecter` counter set — client-side op-resilience telemetry
    (name -> meaning -> kind):

      op                 u64         logical data ops submitted
      resends            u64         op sends beyond the first (map change,
                                     timeout, transport death, backoff)
      timeouts           u64         per-attempt reply timeouts
      backoffs_received  u64         MOSDBackoff blocks received
      backoffs_released  u64         MOSDBackoff unblocks received
      backoff_wait_s     longrunavg  seconds ops spent parked under a block
      paused_ops         u64         ops queued on a paused map (pausewr/
                                     pauserd/full flags)
      map_kicks          u64         in-flight reply waits woken early
                                     (target moved / backoff landed)
      inflight           u64         ops currently in flight (gauge)
    """
    b = PerfCountersBuilder("objecter")
    b.add_u64_counter("op", "logical data ops submitted")
    b.add_u64_counter("resends", "op sends beyond the first")
    b.add_u64_counter("timeouts", "per-attempt reply timeouts")
    b.add_u64_counter("backoffs_received", "MOSDBackoff blocks received")
    b.add_u64_counter("backoffs_released", "MOSDBackoff unblocks received")
    b.add_time_avg("backoff_wait_s", "seconds parked under a PG backoff")
    b.add_u64_counter("paused_ops", "ops queued on a paused map")
    b.add_u64_counter("map_kicks", "in-flight waits woken by map/backoff")
    b.add_u64("inflight", "ops currently in flight (gauge)")
    return b.create_perf_counters()


class RadosClient:
    def __init__(self, mon_addr, conf: Optional[dict] = None):
        # one mon addr or a monmap list; RPCs rotate on mon failure
        self.mons = MonTargets(mon_addr)
        self.conf = conf or {}
        self.op_timeout = self.conf.get("client_op_timeout", 10.0)
        # overall per-op deadline: transient failures RESEND until this
        # long before surfacing an error (definitive typed answers still
        # return immediately) — the bound that keeps "never fail a
        # transient op" from becoming "hang forever on a dead cluster"
        self.op_deadline = float(
            self.conf.get("client_op_deadline", 0) or 0) \
            or max(3.0 * float(self.op_timeout), 15.0)
        # retry pacing: capped exponential backoff with jitter
        self.backoff_base = float(
            self.conf.get("client_backoff_base", 0.1) or 0.1)
        self.backoff_cap = float(
            self.conf.get("client_backoff_cap", 2.0) or 2.0)
        # park ceiling for a server backoff whose unblock never arrives
        self.backoff_park_max = float(
            self.conf.get("client_backoff_park_max", 3.0) or 3.0)
        # entity name riding every data op (MOSDOp v6 `client`): the
        # identity the OSD's per-client dmClock QoS keys on.  Format
        # client.<class>.<id> names a tenant class (pool qos_class:<name>
        # profiles); the default two-part name rides the pool's default
        # client profile.  Multi-tenant harnesses stamp per-op identities
        # through the `client=` kwarg on put/get/delete instead — one
        # client process carries many simulated tenants.
        self.name = str(self.conf.get("client_name", "")
                        or f"client.{uuid.uuid4().hex[:6]}")
        self.messenger = Messenger("client", self.conf, entity_type="client")
        # the `objecter` perf set (schema: _build_objecter_perf)
        self.perf = _build_objecter_perf()
        # client-side trace ring: every logical data op roots a span here
        # and propagates its context on the MOSDOp (ms_trace_propagation)
        # so the primary's and peers' spans stitch under it — the client
        # half of the end-to-end trace
        self.tracer = Tracer(max_spans=512, service="client")
        self._trace_on = bool(self.conf.get("ms_trace_propagation", True))
        self.osdmap: Optional[OSDMap] = None
        self._replies: Dict[str, asyncio.Future] = {}
        # reqid -> persistent op record; map changes and backoffs kick
        # matching in-flight waits (resend-on-map-change)
        self._inflight: Dict[str, _OpRecord] = {}
        # (pool, pg) -> {"event", "expiry", "epoch", "id", "from"}:
        # active MOSDBackoff blocks parking ops for that PG
        self._backoffs: Dict[Tuple[int, int], Dict] = {}
        self._mon_fut: Optional[asyncio.Future] = None
        self._mon_tid: str = ""
        # serialize mon RPCs: _mon_fut is a single slot, and concurrent ops
        # retrying through refresh_map() must not clobber each other
        self._mon_lock = asyncio.Lock()
        # (pool, oid) -> callback(oid, payload) for watch/notify
        self._watches: Dict = {}
        # linger state (reference Objecter::linger_watch, Objecter.cc:598):
        # (pool, oid) -> primary the watch was registered with; on a map
        # change that moves the primary, the watch re-registers itself
        self._watch_primaries: Dict[Tuple[int, int], Optional[int]] = {}
        self._relinger_task: Optional[asyncio.Task] = None
        self._linger_poll_task: Optional[asyncio.Task] = None
        # cluster-log watch (`ceph -w`): callback fed by inbound MLog
        # stream frames after watch_cluster_log() subscribed
        self._clog_cb = None
        # tid -> future for `ceph tell` MCommand round-trips
        self._tell_futs: Dict[str, asyncio.Future] = {}
        # lazy LogClient: client-side tools clog too (audit trails,
        # harness annotations) — created on first .clog use
        self._clog: Optional[LogClient] = None

    @property
    def clog(self) -> LogClient:
        """This client's cluster-log submitter (LogClient role for
        client-side tools); lazily created, flushed on stop()."""
        if self._clog is None:
            self._clog = LogClient(self.messenger, self.mons, self.name,
                                   self.conf)
            try:
                self._clog.start()
            except RuntimeError:
                pass  # no running loop yet: entries queue, flush() later
        return self._clog

    async def start(self) -> None:
        self.messenger.dispatcher = self._dispatch
        # rx batches resolve their reply futures in one pass (and the
        # batch's frames get ONE piggybacked ack instead of one each —
        # an op-reply flood from a busy primary costs a single flush)
        self.messenger.group_dispatcher = self._dispatch_group
        await self.messenger.bind()
        if self.conf.get("auth_cephx", False):
            await self._fetch_ticket()

    async def _fetch_ticket(self) -> None:
        """cephx-lite: obtain a service ticket over a BOOTSTRAP-
        authenticated mon connection; OSD dials present it instead of
        the cluster secret.  The mon refuses to mint tickets over
        ticket-authenticated conns (self-renewal would void the TTL), so
        drop any held ticket and live mon conns first — the re-dial then
        proves the cluster secret."""
        if self.messenger.ticket is not None:
            self.messenger.ticket = None
            self.messenger.session_key = None
            for addr in list(self.mons.addrs):
                await self.messenger.disconnect(addr)
        reply = await self._mon_rpc(
            MAuthTicket(entity="client", entity_type="client"))
        if getattr(reply, "denied", False):
            raise PermissionError("mon refused to mint a client ticket")
        self.messenger.ticket = bytes.fromhex(reply.ticket)
        self.messenger.session_key = bytes.fromhex(reply.session_key)

    async def stop(self) -> None:
        if self._clog is not None:
            await self._clog.stop()
        for t in (self._linger_poll_task, self._relinger_task):
            if t is not None and not t.done():
                t.cancel()
        await self.messenger.shutdown()

    async def _dispatch_group(self, conn, msgs) -> None:
        """A whole rx batch (already-buffered frames): replies resolve
        their futures back-to-back; per-message work is future-set cheap,
        so order-preserving serial dispatch is the right partition here —
        the win is the messenger's single cumulative ack for the batch.
        Per-message isolation matches the serve loop's: one raising
        message (e.g. a watch-ack dial failing) must not drop — and
        still ack — the rest of the batch."""
        for msg in msgs:
            try:
                await self._dispatch(conn, msg)
            except (asyncio.CancelledError, GeneratorExit):
                raise
            except Exception:
                import traceback

                traceback.print_exc()

    async def _dispatch(self, conn, msg) -> None:
        if isinstance(msg, MOSDBackoff):
            self._handle_backoff(conn, msg)
            return
        if isinstance(msg, MWatchNotify):
            # ack FIRST (delivery receipt — divergence from notify2, which
            # acks after processing): a slow callback must not look like a
            # dead watcher and get pruned; then run the callback
            try:
                await self.messenger.send(
                    tuple(msg.reply_to),
                    MNotifyAck(notify_id=msg.notify_id,
                               watcher=self.messenger.addr))
            except (ConnectionError, OSError):
                pass
            cb = self._watches.get((msg.pool_id, msg.oid))
            if cb is not None:
                try:
                    res = cb(msg.oid, msg.payload)
                    if asyncio.iscoroutine(res):
                        await res
                except Exception:
                    import traceback

                    traceback.print_exc()  # a broken callback must be loud
            return
        if isinstance(msg, MLog):
            # mon -> watcher stream frame (`ceph -w` subscription)
            cb = self._clog_cb
            if cb is not None:
                for e in decode_entries(msg.entries):
                    try:
                        res = cb(e)
                        if asyncio.iscoroutine(res):
                            await res
                    except Exception:
                        import traceback

                        traceback.print_exc()  # broken callback: be loud
            return
        if isinstance(msg, MLogAck):
            if self._clog is not None:
                self._clog.handle_ack(msg)
            return
        if isinstance(msg, MCommandReply):
            fut = self._tell_futs.pop(msg.tid, None)
            if fut is not None and not fut.done():
                fut.set_result(msg)
            return
        if isinstance(msg, (MMapReply, MCreatePoolReply, MConfigReply,
                            MAuthTicketReply, MSnapOpReply, MHealthReply,
                            MLogReply, MCrashQueryReply,
                            MCrushOpReply, MOsdPredicateReply)):
            # the mon echoes our per-RPC tid (like MOSDOp's reqid): a reply
            # landing after its RPC timed out has a stale tid and is dropped
            # instead of fulfilling the next RPC's future
            if (
                self._mon_fut
                and not self._mon_fut.done()
                and msg.tid == self._mon_tid
            ):
                self._mon_fut.set_result(msg)
        elif isinstance(msg, MOSDOpReply):
            fut = self._replies.pop(msg.reqid, None)
            if fut and not fut.done():
                fut.set_result(msg)

    # -- MOSDBackoff handling (reference Objecter::_handle_backoff) ----------

    def _handle_backoff(self, conn, msg: MOSDBackoff) -> None:
        key = (msg.pool_id, msg.pg)
        if msg.op == "block":
            self.perf.inc("backoffs_received")
            ent = self._backoffs.get(key)
            if ent is not None:
                if ent.get("id") == msg.id:
                    return  # duplicate block for the same interval
                # a NEW block (new interval/primary) displaces the old
                # one: release ops parked on the displaced event — they
                # re-enter the loop and park on the new block, instead
                # of sleeping out the dead entry's full expiry
                ent["event"].set()
            duration = msg.duration if msg.duration > 0 \
                else self.backoff_park_max
            self._backoffs[key] = {
                "event": asyncio.Event(),
                "expiry": time.monotonic() + duration,
                "epoch": msg.epoch,
                "id": msg.id,
                # who blocked us: a map change that moves the primary off
                # this addr releases the block (the new primary has no
                # backoff state for us)
                "from": tuple(conn.peer) if conn is not None
                and getattr(conn, "peer", None) else None,
            }
            # the op that triggered this block got DROPPED server-side:
            # wake its reply wait so it parks instead of timing out
            self._kick_pg(key)
        else:
            ent = self._backoffs.get(key)
            if ent is not None and (not msg.id or ent.get("id") == msg.id):
                self.perf.inc("backoffs_released")
                self._release_backoff(key)

    def _release_backoff(self, key: Tuple[int, int]) -> None:
        ent = self._backoffs.pop(key, None)
        if ent is not None:
            ent["event"].set()

    def _pg_primary(self, pool_id: int, pg: int) -> Optional[int]:
        pool = self.osdmap.pools.get(pool_id) if self.osdmap else None
        if pool is None or pg >= pool.pg_num:
            return None
        acting = self.osdmap.pg_to_acting(pool, pg)
        return self.osdmap.primary_of(acting, seed=(pool_id << 20) | pg)

    def _kick_pg(self, key: Tuple[int, int]) -> None:
        """Wake in-flight ops targeting a just-blocked PG: their reply is
        never coming (the OSD dropped the op), so the loop should park on
        the backoff now, not after a full reply timeout."""
        for rec in list(self._inflight.values()):
            if (rec.op.pool_id, rec.pg) == key and rec.fut is not None \
                    and not rec.fut.done():
                rec.fut.set_exception(_OpKick())

    def _kick_inflight(self) -> None:
        """Map epoch advanced: release backoffs whose blocking primary is
        no longer the PG's primary, and wake in-flight ops whose computed
        target moved so they resend NOW (the Objecter's _scan_requests
        resend-on-map-change, Objecter.cc:1142)."""
        for key, ent in list(self._backoffs.items()):
            p = self._pg_primary(*key)
            if p is None:
                continue  # PG unservable: keep parked, epoch fence cures
            if ent.get("from") and tuple(self.osdmap.addr_of(p)) \
                    != tuple(ent["from"]):
                self._release_backoff(key)
        for rec in list(self._inflight.values()):
            if rec.fut is None or rec.fut.done() \
                    or self.osdmap.epoch <= rec.epoch:
                continue
            pg, primary = self._calc_target(rec.op)
            if pg != rec.pg or primary != rec.target:
                rec.fut.set_exception(_OpKick())

    def perf_dump(self) -> Dict[str, Dict]:
        """Client-side `perf dump` role: the `objecter` set plus the
        messenger's `wire` set (clients own no admin socket — tools,
        benches, and embedding daemons read this)."""
        return {"objecter": self.perf.dump(),
                "wire": self.messenger.perf.dump()}

    @property
    def mon_addr(self) -> Tuple[str, int]:
        return self.mons.current

    async def _mon_rpc(self, msg):
        async with self._mon_lock:
            last: Exception = TimeoutError("no mon reachable")
            for _ in range(len(self.mons)):
                self._mon_tid = msg.tid = uuid.uuid4().hex
                self._mon_fut = asyncio.get_running_loop().create_future()
                try:
                    await self.messenger.send(self.mons.current, msg)
                    return await asyncio.wait_for(self._mon_fut, timeout=5)
                except (ConnectionError, OSError, asyncio.TimeoutError) as e:
                    last = e
                    self.mons.rotate()
            raise last

    async def refresh_map(self, min_epoch: int = 0) -> OSDMap:
        """Fetch the cluster map; with ``min_epoch``, poll until we hold
        AT LEAST that epoch (the Objecter's epoch barrier — a retryable
        error reply names the OSD's epoch, and re-targeting on anything
        older would recompute the same stale primary).  The mon answers
        with an incremental chain from our epoch when it can (subscriber
        protocol); otherwise a full map."""
        import pickle as _pickle

        prev_epoch = self.osdmap.epoch if self.osdmap is not None else -1
        for _ in range(20):
            since = self.osdmap.epoch if self.osdmap is not None else 0
            reply = await self._mon_rpc(MGetMap(min_epoch=since))
            if reply.osdmap is not None:
                self.osdmap = reply.osdmap
            elif getattr(reply, "incrementals", None) and self.osdmap is not None:
                # apply the delta chain to a copy; a broken chain falls
                # back to a full fetch next iteration
                m = _pickle.loads(_pickle.dumps(self.osdmap, protocol=5))
                if all(m.apply_incremental(inc) for inc in reply.incrementals):
                    self.osdmap = m
                else:
                    self.osdmap = (await self._mon_rpc(MGetMap())).osdmap
            if min_epoch <= 0 or (self.osdmap is not None
                                  and self.osdmap.epoch >= min_epoch):
                break
            await asyncio.sleep(0.1)
        if self.osdmap is not None and self.osdmap.epoch > prev_epoch:
            # resend-on-map-change: in-flight ops whose target moved
            # resend now; backoffs from deposed primaries release
            self._kick_inflight()
        if self._watches:
            self._kick_relinger()
        return self.osdmap

    async def create_pool(
        self, name: str, pool_type: str = "ec", pg_num: int = 8,
        profile: Optional[Dict[str, str]] = None,
    ) -> int:
        reply = await self._mon_rpc(
            MCreatePool(name=name, pool_type=pool_type, pg_num=pg_num,
                        profile=profile or {})
        )
        if not reply.ok:
            raise RadosError(reply.error)
        await self.refresh_map()
        return reply.pool_id

    async def config_set(self, key: str, value: str) -> None:
        """Centralized config: `ceph config set` equivalent (replicated by
        the mon quorum, distributed to daemons at boot)."""
        reply = await self._mon_rpc(MConfigSet(key=key, value=str(value)))
        if not reply.ok:
            raise RadosError(reply.error)

    async def config_get(self, key: str = "") -> Dict[str, str]:
        reply = await self._mon_rpc(MConfigGet(key=key))
        return reply.values

    async def set_upmap(self, pool_id: int, pg: int,
                        acting: Optional[List[int]] = None) -> None:
        """Install (or clear, with acting=None) a persistent placement
        override — `ceph osd pg-upmap-items` role."""
        await self._mon_rpc(MSetUpmap(pool_id=pool_id, pg=pg,
                                      acting=list(acting or [])))
        await self.refresh_map()

    async def pool_set(self, pool_id: int, key: str, value) -> None:
        """`ceph osd pool set` role (pg_num drives PG splitting)."""
        await self._mon_rpc(MPoolSet(pool_id=pool_id, key=key,
                                     value=str(value)))
        await self.refresh_map()

    async def delete_pool(self, pool_id: int, confirm_name: str) -> None:
        """`ceph osd pool rm` role: `confirm_name` must echo the pool's
        name (the reference's --yes-i-really-really-mean-it guard).
        OSDs purge the pool's data when they see it gone from the map."""
        reply = await self._mon_rpc(MDeletePool(pool_id=pool_id,
                                                confirm_name=confirm_name))
        if not reply.ok:
            raise RadosError(reply.error)
        await self.refresh_map()

    async def mark_osd_down(self, osd_id: int) -> None:
        """Admin: immediately mark an OSD down+out (test/thrash hook)."""
        await self._mon_rpc(MMarkDown(osd_id=osd_id))
        await self.refresh_map()

    async def _osd_membership(self, op: str, osd_id: int,
                              weight: float = 1.0) -> None:
        await self._mon_rpc(
            MOsdMembership(op=op, osd_id=int(osd_id),
                           weight=float(weight)))
        await self.refresh_map()

    async def osd_out(self, osd_id: int) -> None:
        """`ceph osd out <id>`: drop the OSD from placement (weight 0
        through the in_cluster gate) while it stays up — CRUSH remaps
        its PGs minimally and backfill drains it.  Sticky across the
        OSD's reboots until `osd in`."""
        await self._osd_membership("out", osd_id)

    async def osd_in(self, osd_id: int) -> None:
        """`ceph osd in <id>`: restore an out OSD to placement."""
        await self._osd_membership("in", osd_id)

    async def osd_reweight(self, osd_id: int, weight: float) -> None:
        """`ceph osd reweight <id> <0..1>`: the reweight overlay — a
        fractional multiplier on the OSD's crush weight (0 behaves
        like out)."""
        await self._osd_membership("reweight", osd_id, weight)

    async def osd_crush_reweight(self, osd_id: int,
                                 weight: float) -> None:
        """`ceph osd crush reweight osd.<id> <w>`: the straw2 crush
        weight (nominal device capacity share)."""
        await self._osd_membership("crush-reweight", osd_id, weight)

    async def osd_crush_op(self, op: str, name: str, *,
                           bucket_type: str = "", dest: str = "",
                           weight: float = 1.0,
                           force: bool = False) -> int:
        """`ceph osd crush add-bucket/add/set/move/rm`: runtime CRUSH
        hierarchy surgery.  Raises RadosError on refusal (validation is
        mon-side; a failure means the map is untouched); returns the
        post-mutation epoch."""
        reply = await self._mon_rpc(
            MCrushOp(op=op, name=name, bucket_type=bucket_type,
                     dest=dest, weight=float(weight), force=force))
        if not reply.ok:
            raise RadosError(reply.error)
        await self.refresh_map(min_epoch=reply.epoch)
        return reply.epoch

    async def osd_purge(self, osd_id: int, force: bool = False) -> None:
        """`ceph osd purge <id>`: remove the OSD from the map and crush
        permanently.  The mon refuses while the OSD is up or (unless
        ``force``) while safe-to-destroy says data could be lost; a
        refusal surfaces as RadosError (the id survives in the replied
        map)."""
        await self._osd_membership("purge-force" if force else "purge",
                                   osd_id)
        if self.osdmap is not None and osd_id in self.osdmap.osds:
            raise RadosError(
                f"osd.{osd_id} purge refused by the mon (still up, or "
                f"not safe-to-destroy — see the cluster log)")

    async def osd_predicate(self, op: str, osd_ids: List[int]):
        """`ceph osd safe-to-destroy / ok-to-stop`: the data-safety
        predicates, served as reads at ANY mon.  Returns the typed
        MOsdPredicateReply (safe, unsafe_ids, reasons, pgs_checked,
        dirty_blocked, dirty_keys)."""
        return await self._mon_rpc(
            MOsdPredicate(op=op, osd_ids=[int(i) for i in osd_ids]))

    async def osd_safe_to_destroy(self, osd_id: int):
        return await self.osd_predicate("safe-to-destroy", [osd_id])

    async def osd_ok_to_stop(self, *osd_ids: int):
        return await self.osd_predicate("ok-to-stop", list(osd_ids))

    def _parse_pgid(self, pgid: str) -> Tuple[int, int]:
        pool_part, pg_part = str(pgid).split(".", 1)
        return int(pool_part), int(pg_part, 16)

    async def _pg_tell(self, pgid: str, prefix: str,
                       timeout: float = 60.0):
        """Route a single-PG admin command to the PG's primary via the
        MCommand tell path (`ceph pg scrub/repair <pgid>`)."""
        if self.osdmap is None:
            await self.refresh_map()
        try:
            pool_id, pg = self._parse_pgid(pgid)
        except ValueError:
            raise RadosError(f"bad pgid {pgid!r} (want <pool>.<hexpg>)")
        pool = self.osdmap.pools.get(pool_id)
        if pool is None or pg < 0 or pg >= pool.pg_num:
            raise RadosError(f"no such pg {pgid!r}")
        primary = self._pg_primary(pool_id, pg)
        if primary is None:
            raise RadosError(f"pg {pgid} has no live primary")
        return await self.tell(f"osd.{primary}", prefix,
                               timeout=timeout, pgid=f"{pool_id}.{pg:x}")

    async def pg_scrub(self, pgid: str) -> Dict:
        """`ceph pg scrub <pgid>`: deep-scrub one PG on its primary."""
        return await self._pg_tell(pgid, "pg scrub")

    async def pg_repair(self, pgid: str) -> Dict:
        """`ceph pg repair <pgid>`: scrub + repair + verify one PG;
        a clean verify pass clears its PG_INCONSISTENT record."""
        return await self._pg_tell(pgid, "pg repair")

    async def get_health(self, detail: bool = False) -> Dict:
        """Cluster health from the mon's aggregation (reference `ceph
        health [detail]`): map-derived checks (OSD_DOWN, PG_DEGRADED,
        OSDMAP_FLAGS) plus daemon-reported ones (SLOW_OPS, BREAKER_OPEN,
        TIER_OVER_TARGET), with the mute lifecycle applied — the mon is
        the authority, not client-side osdmap math."""
        reply = await self._mon_rpc(MGetHealth(detail=detail))
        return reply.health

    async def health_mute(self, check: str, ttl: float = 0.0,
                          unmute: bool = False) -> Dict:
        """`ceph health mute/unmute <check> [ttl]`: a muted check keeps
        being tracked but no longer degrades the health status."""
        reply = await self._mon_rpc(
            MHealthMute(check=check, ttl=float(ttl), unmute=bool(unmute)))
        return reply.health

    async def log_last(self, n: int = 0, level: int = 0,
                       channel: str = "") -> List[ClogEntry]:
        """`ceph log last [n] [level] [channel]`: the mon's retained
        cluster-log tail (paxos-replicated), oldest first."""
        reply = await self._mon_rpc(
            MLogSubscribe(last_n=n, level=level, channel=channel))
        return decode_entries(reply.entries)

    async def watch_cluster_log(self, callback, level: int = 0,
                                channel: str = "",
                                last_n: int = 16) -> List[ClogEntry]:
        """`ceph -w`: subscribe this session to the cluster log — the
        mon streams every newly committed matching entry as MLog frames
        and ``callback(entry)`` runs per entry (sync or async).  Returns
        the current tail (the part `ceph -w` prints before following)."""
        self._clog_cb = callback
        reply = await self._mon_rpc(
            MLogSubscribe(last_n=last_n, level=level, channel=channel,
                          sub=True))
        return decode_entries(reply.entries)

    async def crash_ls(self) -> List[Dict]:
        """`ceph crash ls`: crash-report summaries, oldest first."""
        reply = await self._mon_rpc(MCrashQuery(op="ls"))
        if not reply.ok:
            raise RadosError(reply.error)
        return reply.crashes

    async def crash_info(self, crash_id: str) -> Dict:
        """`ceph crash info <id>`: one report in full, the spooled
        dump_recent ring decoded."""
        reply = await self._mon_rpc(MCrashQuery(op="info",
                                                crash_id=crash_id))
        if not reply.ok:
            raise RadosError(reply.error)
        return reply.crashes[0]

    async def crash_archive(self, crash_id: str = "") -> List[Dict]:
        """`ceph crash archive <id>` ('' = archive-all): acknowledged
        crashes stop raising RECENT_CRASH but stay listable."""
        reply = await self._mon_rpc(MCrashQuery(
            op="archive" if crash_id else "archive-all",
            crash_id=crash_id))
        if not reply.ok:
            raise RadosError(reply.error)
        return reply.crashes

    async def crash_prune(self, keep_seconds: float) -> List[Dict]:
        """`ceph crash prune`: drop reports older than keep_seconds."""
        reply = await self._mon_rpc(MCrashQuery(op="prune",
                                                keep=keep_seconds))
        if not reply.ok:
            raise RadosError(reply.error)
        return reply.crashes

    async def tell(self, target: str, prefix: str, timeout: float = 5.0,
                   **args):
        """`ceph tell <target> <cmd> [k=v...]` (reference MCommand):
        run an admin-socket command on a remote daemon.  Targets:
        ``osd.N`` (resolved via the osdmap), ``mon`` / ``mon.N`` (the
        monmap), ``mgr`` (the mgr_addr config key)."""
        if target.startswith("osd."):
            if self.osdmap is None:
                await self.refresh_map()
            osd_id = int(target.split(".", 1)[1])
            info = self.osdmap.osds.get(osd_id)
            if info is None or not info.up:
                raise RadosError(f"{target} is not up")
            addr = tuple(info.addr)
        elif target == "mon" or target.startswith("mon."):
            rank = int(target.split(".", 1)[1]) if "." in target else 0
            addr = self.mons.addrs[rank % len(self.mons.addrs)]
        elif target == "mgr":
            raw = str(self.conf.get("mgr_addr", "") or "")
            if not raw:
                reply = await self.config_get("mgr_addr")
                raw = reply.get("mgr_addr", "")
            if not raw:
                raise RadosError("no mgr_addr known")
            host, port = raw.rsplit(":", 1)
            addr = (host, int(port))
        else:
            raise RadosError(f"bad tell target {target!r} "
                             f"(want osd.N / mon[.N] / mgr)")
        tid = uuid.uuid4().hex
        fut = asyncio.get_running_loop().create_future()
        self._tell_futs[tid] = fut
        try:
            await self.messenger.send(
                addr, MCommand(tid=tid, target=target, prefix=prefix,
                               args=dict(args)))
            reply = await asyncio.wait_for(fut, timeout=timeout)
        finally:
            self._tell_futs.pop(tid, None)
        if not reply.ok:
            raise RadosError(reply.error)
        return reply.result

    async def osd_set_flag(self, flag: str, on: bool = True) -> None:
        """`ceph osd set/unset <flag>` role: toggle a cluster-wide op
        gate ("pausewr", "pauserd", "full") in the OSDMap.  Clients
        QUEUE matching ops while the flag is set (paused-map handling),
        so unsetting it releases the queued work rather than retrying
        failures."""
        await self._mon_rpc(MOSDSetFlag(flag=flag, set=bool(on)))
        await self.refresh_map()

    async def osd_set_full_ratio(self, which: str, ratio: float) -> None:
        """`ceph osd set-nearfull-ratio / set-backfillfull-ratio /
        set-full-ratio`: install a fullness threshold in the OSDMap.
        The mon validates the ladder ordering and answers a typed
        error on violation."""
        reply = await self._mon_rpc(
            MSetFullRatio(which=which, ratio=float(ratio)))
        if not getattr(reply, "ok", True):
            raise RadosError(reply.error, code=-errno.EINVAL)
        await self.refresh_map()

    async def osd_df(self) -> Dict[int, Dict]:
        """Per-OSD utilization + fullness from the MON's aggregated
        view (ONE MGetHealth-style query instead of N per-OSD statfs
        ops).  Falls back to direct per-OSD polling when the mon
        predates the fullness plane (no osd_utilization in its health
        document)."""
        health = await self.get_health()
        util = health.get("osd_utilization")
        if util is not None:
            return {int(k): dict(v) for k, v in util.items()}
        # old mon: poll each up OSD directly, CONCURRENTLY — one
        # unresponsive OSD must cost one timeout, not serialize the
        # sweep (the discipline of the pre-aggregation fan-out)
        await self.refresh_map()

        async def one(osd_id: int, info) -> Tuple[int, Dict]:
            row: Dict = {"up": info.up, "weight": info.weight,
                         "state": ""}
            if info.up:
                try:
                    st = await self.osd_statfs(osd_id)
                    total = int(st.get("total", 0) or 0)
                    used = int(st.get("used", 0) or 0)
                    row.update(
                        total=total, used=used,
                        avail=int(st.get("avail", 0) or 0),
                        num_objects=int(st.get("num_objects", 0) or 0),
                        ratio=round(used / total, 4) if total else 0.0)
                except Exception as e:
                    row["error"] = str(e)
            return osd_id, row

        return dict(await asyncio.gather(
            *(one(osd_id, info)
              for osd_id, info in sorted(self.osdmap.osds.items()))))

    # -- data ops -------------------------------------------------------------

    def _calc_target(self, op: MOSDOp) -> Tuple[Optional[int], Optional[int]]:
        """object -> (PG, primary) on the current map (reference
        Objecter::_calc_target, Objecter.cc:2764)."""
        pool = self.osdmap.pools.get(op.pool_id)
        if pool is None:
            return None, None
        pg = self.osdmap.object_to_pg(pool, op.oid)
        acting = self.osdmap.pg_to_acting(pool, pg)
        return pg, self.osdmap.primary_of(acting,
                                          seed=(op.pool_id << 20) | pg)

    def _retry_pause(self, attempt: int) -> float:
        """Retry pacing: capped exponential backoff with jitter —
        min(base * 2^attempt, cap) scaled by a uniform [0.5, 1.5) draw,
        so colliding clients decorrelate instead of re-colliding every
        backoff period (the Objecter's retry discipline + thundering-herd
        jitter)."""
        return min(self.backoff_base * (2 ** attempt), self.backoff_cap) \
            * (0.5 + random.random())

    def _paused_for(self, op: MOSDOp) -> bool:
        """Is this op gated by the map's pause flags? (reference
        Objecter::target_should_be_paused)  DELETES are exempt from the
        write gates: when the cluster pauses because it is FULL,
        deleting is the only way out — the delete path must thread
        through pausewr/full like it threads through the OSD's fullness
        gates."""
        flags = getattr(self.osdmap, "flags", None) or ()
        if op.op in ("delete", "snap-trim") \
                or (op.op == "multi" and is_delete_only_multi(op)):
            return False
        if op.op in _WRITE_OPS:
            return "pausewr" in flags or "full" in flags
        return "pauserd" in flags

    async def _wait_unpaused(self, rec: _OpRecord) -> None:
        """Paused ops QUEUE, they do not fail: poll the mon for the map
        that lifts the gate (the Objecter keeps paused ops queued and
        resubmits on the flag-clearing map)."""
        interval = 0.2
        while time.monotonic() < rec.deadline:
            await asyncio.sleep(interval)
            interval = min(interval * 1.5, 1.0)
            try:
                await self.refresh_map()
            except (ConnectionError, OSError, asyncio.TimeoutError):
                continue
            if not self._paused_for(rec.op):
                return
        # deadline reached: fall back to the loop, which raises

    async def _park_backoff(self, key: Tuple[int, int],
                            rec: _OpRecord) -> None:
        """Park until the PG's backoff releases — or until its duration
        expires / the op deadline nears (liveness when the unblock is
        lost).  Wait seconds land in the backoff_wait_s longrunavg."""
        ent = self._backoffs.get(key)
        if ent is None:
            return
        now = time.monotonic()
        if now >= ent["expiry"]:
            self._release_backoff(key)  # expired: resend anyway
            return
        timeout = max(0.01, min(ent["expiry"] - now, rec.deadline - now))
        with self.perf.time_avg("backoff_wait_s"):
            try:
                await asyncio.wait_for(ent["event"].wait(), timeout=timeout)
            except asyncio.TimeoutError:
                if self._backoffs.get(key) is ent:
                    self._release_backoff(key)
        # decorrelate the release burst: every op parked on this PG wakes
        # at once, and without jitter the resend order is stable cycle
        # after cycle — under repeated saturation sheds the same ops win
        # admission every time while the tail starves deterministically
        await asyncio.sleep(random.random() * 0.05)

    async def _op(self, op: MOSDOp,
                  retries: Optional[int] = None) -> MOSDOpReply:
        """Objecter-grade submit (reference op_submit/_calc_target/_send_op,
        Objecter.cc:2257,2764,3233): ONE reqid for the op's whole lifetime
        (server dedupe = exactly-once) and a persistent in-flight record;
        re-target on every map change (a refresh that moves the primary
        wakes the reply wait), epoch barriers on retryable errors, pause
        flags queue, MOSDBackoff parks, and capped-exponential-jitter
        pacing between resends.  Transient trouble NEVER fails the op
        before the deadline (client_op_deadline); ``retries`` caps
        attempts for callers that want the old bounded behavior."""
        if self.osdmap is None:
            await self.refresh_map()
        # ONE reqid per logical op: resends carry the same id so the PG
        # log's dup detection can recognize them (reference osd_reqid_t)
        op.reqid = uuid.uuid4().hex
        if not getattr(op, "client", ""):
            op.client = self.name
        rec = _OpRecord(op, time.monotonic() + self.op_deadline)
        # root span for the whole logical op (across every resend); its
        # context rides the MOSDOp so the primary's osd_op span — and
        # through it the k+m sub-write peers — stitch under ONE trace_id
        span = None
        if self._trace_on:
            span = self.tracer.new_trace(f"client_op {op.op} {op.oid}")
            span.tag("reqid", op.reqid).tag("pool", op.pool_id)
            op.trace_id, op.span_id = span.context()
        self.perf.inc("op")
        self._inflight[op.reqid] = rec
        self.perf.set("inflight", len(self._inflight))
        try:
            reply = await self._op_submit(op, rec, retries, span)
            if span is not None:
                span.tag("ok", True)
            return reply
        except BaseException as e:
            if span is not None:
                span.tag("ok", False).tag("error", type(e).__name__)
            raise
        finally:
            if span is not None:
                span.finish()
            self._inflight.pop(op.reqid, None)
            self.perf.set("inflight", len(self._inflight))

    async def _op_submit(self, op: MOSDOp, rec: _OpRecord,
                         retries: Optional[int],
                         span=None) -> MOSDOpReply:
        loop = asyncio.get_running_loop()
        last_error = "no attempt"
        last_code = 0
        fence = 0  # minimum epoch the next target may be computed on
        refresh_next = False  # one refresh owed (transport blip)
        attempt = 0  # attempts CONSUMED (sends + failed refreshes)
        sends = 0
        # the deadline governs from the moment ANY work happened (a send
        # OR a consumed attempt); the virgin first iteration is always
        # admitted so a deadline in the past still tries once
        while (retries is None or attempt < retries) \
                and (time.monotonic() < rec.deadline
                     or (attempt == 0 and sends == 0)):
            if fence > self.osdmap.epoch or (attempt and fence == 0) \
                    or refresh_next:
                refresh_next = False
                try:
                    await self.refresh_map(min_epoch=fence)
                except (ConnectionError, OSError, asyncio.TimeoutError):
                    last_error = "map refresh failed"
                    await asyncio.sleep(self._retry_pause(attempt))
                    attempt += 1
                    continue
            if self._paused_for(op):
                # paused map (pausewr/pauserd/full): queue, don't fail —
                # and consume no attempt (the cluster asked us to wait)
                if not rec.paused_counted:
                    rec.paused_counted = True
                    self.perf.inc("paused_ops")
                last_error = "osdmap paused"
                await self._wait_unpaused(rec)
                if self._paused_for(op):
                    break  # deadline ran out still paused
                continue
            pool = self.osdmap.pools.get(op.pool_id)
            if pool is None:
                # a lagging mon may have served us a pre-creation map:
                # refresh-and-retry (Objecter catches up across epochs)
                last_error = (
                    f"pool {op.pool_id} not in map epoch {self.osdmap.epoch}")
                last_code = -errno.ENOENT
                fence = self.osdmap.epoch + 1
                await asyncio.sleep(self._retry_pause(attempt))
                attempt += 1
                continue
            pg, primary = self._calc_target(op)
            if primary is None:
                last_error = "no primary (all acting osds down)"
                last_code = 0
                fence = self.osdmap.epoch + 1
                await asyncio.sleep(self._retry_pause(attempt))
                attempt += 1
                continue
            rec.pg = pg
            if (op.pool_id, pg) in self._backoffs:
                # the PG told us to hold off: park until release/expiry,
                # then re-target (no attempt consumed — server-directed)
                last_error = f"backoff on pg {op.pool_id}.{pg}"
                await self._park_backoff((op.pool_id, pg), rec)
                if time.monotonic() >= rec.deadline:
                    break
                continue
            rec.target = primary
            rec.epoch = self.osdmap.epoch
            op.epoch = self.osdmap.epoch
            fut: asyncio.Future = loop.create_future()
            rec.fut = fut
            self._replies[op.reqid] = fut
            try:
                if sends:
                    self.perf.inc("resends")
                sends += 1
                if span is not None:
                    span.event("resend" if sends > 1
                               else f"sent to osd.{primary}")
                await self.messenger.send(self.osdmap.addr_of(primary), op)
                timeout = min(float(self.op_timeout),
                              max(0.05, rec.deadline - time.monotonic()))
                reply = await asyncio.wait_for(fut, timeout=timeout)
                if reply.ok:
                    return reply
                last_error = reply.error
                # classification is by TYPED code (reference 0/-errno):
                # a reworded error string can never silently change an
                # op's retry behavior
                code = last_code = getattr(reply, "code", 0)
                if code in _DEFINITIVE_CODES:
                    raise RadosError(
                        f"op {op.op} {op.oid} failed: {reply.error}",
                        code=code)
                # epoch barrier: never re-target on a map older than the
                # replying OSD's (it refused exactly because placement
                # moved — recomputing on our stale map re-picks it)
                fence = max(fence, getattr(reply, "map_epoch", 0))
                if code in (-errno.ESTALE, -errno.EAGAIN):
                    # placement moved / PG degraded: both are cured by a
                    # newer map — fence PAST our own epoch, growing window
                    # while detection + recovery move seats.  A server-
                    # provided backoff hint extends the pause: the PG told
                    # us how long it wants.
                    fence = max(fence, self.osdmap.epoch + 1)
                    pause = max(getattr(reply, "backoff", 0.0),
                                self._retry_pause(attempt) if attempt
                                else 0.0)
                    if pause:
                        await asyncio.sleep(pause)
                    attempt += 1
                    continue
                # -EBUSY and anything unclassified: prompt plain retry
                await asyncio.sleep(self._retry_pause(attempt))
                attempt += 1
            except _OpKick:
                # the map moved our target, or a backoff landed for our
                # PG: re-enter the loop NOW (re-target / park) — no
                # attempt consumed, no pause (the kicker knows better)
                self.perf.inc("map_kicks")
            except PermissionError:
                # expired/rotated-away ticket: fetch a fresh one and retry
                last_error = "ticket rejected"
                try:
                    await self._fetch_ticket()
                except Exception:
                    await asyncio.sleep(self._retry_pause(attempt))
                attempt += 1
            except asyncio.TimeoutError:
                # per-op reply timeout: the target may be wedged or the
                # reply lost — refresh to the CURRENT map and resend
                # (dedupe-safe); only the deadline fails the op
                self.perf.inc("timeouts")
                last_error = "op timed out"
                last_code = 0
                refresh_next = True
                await asyncio.sleep(self._retry_pause(attempt))
                attempt += 1
            except (ConnectionError, OSError) as e:
                last_error = f"{type(e).__name__}: {e}"
                last_code = 0  # transport failure: no typed OSD answer
                # the target may have died — but a transport blip has NO
                # map change coming, so the next attempt refreshes to the
                # CURRENT map (one RPC at loop top), not a future epoch
                # (a 2s poll per blip).  If the target is unchanged the
                # resend is dedupe-safe; if the OSD really died, failure
                # detection bumps the epoch and re-targets us.
                refresh_next = True
                await asyncio.sleep(self._retry_pause(attempt))
                attempt += 1
            finally:
                # a kick may have raced a send() error into the same
                # iteration: mark any unawaited exception retrieved so
                # the abandoned future never logs at GC
                if fut.done() and not fut.cancelled():
                    fut.exception()
                rec.fut = None
                self._replies.pop(op.reqid, None)
        raise RadosError(f"op {op.op} {op.oid} failed: {last_error}",
                         code=last_code)

    @staticmethod
    def _check_oid(oid: str) -> None:
        if SNAP_SEP in oid:
            raise RadosError("oid contains the reserved snap separator",
                             code=-errno.EINVAL)

    def _write_snapc(self, pool_id: int, snapc):
        """The SnapContext a write carries: the caller's, or — for a
        pool in pool-snaps mode — the POOL's own context from the
        osdmap (reference IoCtxImpl: the ioctx snapc defaults to the
        pool snapc), so every writer path clones pre-snap heads without
        knowing pool snapshots exist."""
        if snapc:
            return snapc
        pool = self.osdmap.pools.get(pool_id) if self.osdmap else None
        if pool is not None and getattr(pool, "snap_mode", "") == "pool":
            return pool.pool_snapc()
        return (0, [])

    async def put(self, pool_id: int, oid: str, data: bytes,
                  offset: Optional[int] = None,
                  snapc: Optional[Tuple[int, List[int]]] = None,
                  client: str = "") -> None:
        """Full-object write, or a partial overwrite at `offset` (the
        primary takes the read-modify-write path).  ``snapc`` is a
        self-managed snap context (seq, snaps-descending): the primary
        clones the head before the first write past a new snap
        (reference SnapContext on every write).  ``client`` overrides
        the entity name this op carries (simulated-tenant identity for
        the macro traffic harness; default: this client's name)."""
        self._check_oid(oid)
        seq, snaps = self._write_snapc(pool_id, snapc)
        await self._op(MOSDOp(op="write", pool_id=pool_id, oid=oid, data=data,
                              offset=-1 if offset is None else int(offset),
                              snapc_seq=seq, snapc_snaps=list(snaps),
                              client=client))

    async def multi(self, pool_id: int, oid: str, ops,
                    snapc: Optional[Tuple[int, List[int]]] = None):
        """Compound atomic op (reference MOSDOp vector<OSDOp> /
        ObjectWriteOperation): `ops` is an ordered list of (name, kwargs)
        sub-ops executed all-or-nothing on one object.  Returns
        (per-sub-op results, object version the op observed); a failing
        sub-op raises RadosError with its typed code and nothing
        applied."""
        import pickle as _pickle

        self._check_oid(oid)
        seq, snaps = self._write_snapc(pool_id, snapc)
        reply = await self._op(MOSDOp(op="multi", pool_id=pool_id, oid=oid,
                                      ops=list(ops), snapc_seq=seq,
                                      snapc_snaps=list(snaps)))
        return _pickle.loads(reply.data), reply.version

    # -- self-managed snapshots (reference IoCtxImpl selfmanaged_snap_*) ----

    async def selfmanaged_snap_create(self, pool_id: int) -> int:
        """Allocate a new cluster-unique snap id (the mon is the
        allocator)."""
        reply = await self._mon_rpc(MSnapOp(pool_id=pool_id, op="create"))
        if not reply.ok:
            raise RadosError(reply.error, code=reply.code)
        await self.refresh_map()
        return reply.snap_id

    async def selfmanaged_snap_remove(self, pool_id: int,
                                      snap_id: int) -> None:
        """Mark the snap removed in the pool and trim its clones
        (reference snap trimmer).  Trim is best-effort immediate and
        idempotent: an OSD that was down during the fan-out keeps its
        clones until this call is re-run (the mon records the removal
        first, so re-running re-trims everywhere)."""
        reply = await self._mon_rpc(
            MSnapOp(pool_id=pool_id, op="remove", snap_id=snap_id))
        if not reply.ok:
            raise RadosError(reply.error, code=reply.code)
        await self.refresh_map()
        for osd_id in self._pg_primaries(pool_id):
            try:
                await self._op_direct(osd_id, MOSDOp(
                    op="snap-trim", pool_id=pool_id, snap_id=snap_id))
            except RadosError:
                continue

    # -- pool-managed snapshots (reference `ceph osd pool mksnap`,
    # OSDMonitor pool-op SNAP_CREATE/SNAP_RM; mixing with self-managed
    # snaps is a typed -EINVAL at the mon) ----------------------------------

    async def pool_snap_create(self, pool_id: int, name: str) -> int:
        """Create a mon-managed pool snapshot; every subsequent write
        carries the pool's SnapContext, so heads clone lazily on first
        overwrite (the same make_writeable machinery as self-managed
        snaps)."""
        reply = await self._mon_rpc(
            MSnapOp(pool_id=pool_id, op="mksnap", name=name))
        if not reply.ok:
            raise RadosError(reply.error, code=reply.code)
        await self.refresh_map()
        return reply.snap_id

    async def pool_snap_remove(self, pool_id: int, name: str) -> None:
        """Remove a pool snapshot and trim its clones (same fan-out
        discipline as selfmanaged_snap_remove: mon records first, trim
        is idempotent best-effort)."""
        reply = await self._mon_rpc(
            MSnapOp(pool_id=pool_id, op="rmsnap", name=name))
        if not reply.ok:
            raise RadosError(reply.error, code=reply.code)
        await self.refresh_map()
        for osd_id in self._pg_primaries(pool_id):
            try:
                await self._op_direct(osd_id, MOSDOp(
                    op="snap-trim", pool_id=pool_id,
                    snap_id=reply.snap_id))
            except RadosError:
                continue

    async def rollback_object(self, pool_id: int, oid: str, snap_id: int,
                              snapc=None) -> None:
        """Restore one object's head to its state at `snap_id`
        (reference rollback: read-at-snap -> write head; an object
        absent at the snap is removed).  The ONE implementation behind
        ioctx self-managed rollback, pool-snap rollback, and the rados
        CLI."""
        try:
            old = await self.get(pool_id, oid, snap=snap_id)
        except RadosError as e:
            if e.code != -errno.ENOENT:
                raise
            await self.delete(pool_id, oid, snapc=snapc)
            return
        await self.put(pool_id, oid, old, snapc=snapc)

    async def pool_snap_list(self, pool_id: int) -> Dict[str, int]:
        await self.refresh_map()
        pool = self.osdmap.pools.get(pool_id)
        if pool is None:
            raise RadosError(f"pool {pool_id} does not exist",
                             code=-errno.ENOENT)
        return dict(getattr(pool, "pool_snaps", {}) or {})

    async def osd_statfs(self, osd_id: int) -> Dict:
        """One OSD's store utilization (reference ObjectStore::statfs
        feeding `ceph osd df`)."""
        import json as _json

        reply = await self._op_direct(osd_id, MOSDOp(op="statfs"))
        return _json.loads(reply.data)

    async def deep_scrub(self, pool_id: int) -> Dict[str, int]:
        """Ask every up OSD to deep-scrub the PGs it leads; sums the
        per-primary summaries."""
        import pickle as _pickle

        total = {"scrubbed": 0, "errors": 0, "repaired": 0}
        for osd_id in self._pg_primaries(pool_id):
            try:
                reply = await self._op_direct(
                    osd_id, MOSDOp(op="deep-scrub", pool_id=pool_id))
                for k, v in _pickle.loads(reply.data).items():
                    total[k] = total.get(k, 0) + v
            except RadosError:
                continue
        return total

    async def get(self, pool_id: int, oid: str, snap: int = 0,
                  fadvise: str = "", client: str = "") -> bytes:
        """Read the head, or the object's state AT a snap id (resolved
        through the primary's SnapSet clone list).  ``fadvise`` is
        cache-tier advice (reference librados FADVISE_DONTNEED/WILLNEED
        op flags): "dontneed" keeps this read out of the hit sets and
        off the promotion path (scans, backups); "willneed" asks the
        primary to promote the object to device residency on this read
        regardless of its recency (still promotion-throttled)."""
        self._check_oid(oid)
        reply = await self._op(MOSDOp(op="read", pool_id=pool_id, oid=oid,
                                      snap_read=int(snap),
                                      fadvise=fadvise, client=client))
        data = reply.data
        if isinstance(data, BufferList):
            # colocated fastpath hands the primary's scatter-gather read
            # reply over by reference; materialize at the API boundary
            # (the wire path already delivered one contiguous buffer)
            data = data.tobytes()
        return data

    async def delete(self, pool_id: int, oid: str,
                     snapc: Optional[Tuple[int, List[int]]] = None,
                     client: str = "") -> None:
        """Delete the head; under a snap context the primary clones
        first and leaves a whiteout so snapshots keep resolving."""
        self._check_oid(oid)
        seq, snaps = self._write_snapc(pool_id, snapc)
        await self._op(MOSDOp(op="delete", pool_id=pool_id, oid=oid,
                              snapc_seq=seq, snapc_snaps=list(snaps),
                              client=client))

    async def watch(self, pool_id: int, oid: str, callback) -> None:
        """Register a notify callback on oid (librados watch2 role).
        Watches are LINGER ops (reference Objecter::linger_watch): the
        client tracks the registered primary and automatically
        re-registers when a map refresh shows the primary moved — the
        new primary has no watcher state for us until then."""
        import pickle as _pickle

        self._watches[(pool_id, oid)] = callback
        try:
            await self._op(MOSDOp(op="watch", pool_id=pool_id, oid=oid,
                                  data=_pickle.dumps(self.messenger.addr)))
        except BaseException:
            self._watches.pop((pool_id, oid), None)  # registration failed
            raise
        self._watch_primaries[(pool_id, oid)] = self._primary_for(pool_id, oid)
        if self._linger_poll_task is None or self._linger_poll_task.done():
            # an IDLE watcher issues no ops, so nothing would ever pull a
            # new map: poll while watches exist (reference: the Objecter
            # subscribes to maps; this is the polling analog)
            self._linger_poll_task = asyncio.get_running_loop().create_task(
                self._linger_poll())

    async def _linger_poll(self) -> None:
        interval = float(self.conf.get("client_linger_poll", 1.0) or 1.0)
        while self._watches:
            await asyncio.sleep(interval)
            if not self._watches:
                break
            try:
                await self.refresh_map()  # _kick_relinger rides this
            except (ConnectionError, OSError, asyncio.TimeoutError):
                pass

    def _primary_for(self, pool_id: int, oid: str) -> Optional[int]:
        pool = self.osdmap.pools.get(pool_id) if self.osdmap else None
        if pool is None:
            return None
        pg = self.osdmap.object_to_pg(pool, oid)
        acting = self.osdmap.pg_to_acting(pool, pg)
        return self.osdmap.primary_of(acting, seed=(pool_id << 20) | pg)

    def _kick_relinger(self) -> None:
        """After a map change: re-register watches whose primary moved
        (on a task of its own — refresh_map runs inside op retries and
        must not recurse into more ops)."""
        stale = [key for key, registered in self._watch_primaries.items()
                 if key in self._watches
                 and self._primary_for(*key) not in (None, registered)]
        if not stale or (self._relinger_task
                         and not self._relinger_task.done()):
            return

        async def _relinger() -> None:
            import pickle as _pickle

            for pool_id, oid in stale:
                if (pool_id, oid) not in self._watches:
                    continue  # unwatched meanwhile
                try:
                    await self._op(MOSDOp(
                        op="watch", pool_id=pool_id, oid=oid,
                        data=_pickle.dumps(self.messenger.addr)))
                    self._watch_primaries[(pool_id, oid)] = \
                        self._primary_for(pool_id, oid)
                except RadosError:
                    pass  # next map change retries

        self._relinger_task = asyncio.get_running_loop().create_task(
            _relinger())

    async def unwatch(self, pool_id: int, oid: str) -> None:
        import pickle as _pickle

        await self._op(MOSDOp(op="unwatch", pool_id=pool_id, oid=oid,
                              data=_pickle.dumps(self.messenger.addr)))
        self._watches.pop((pool_id, oid), None)  # only after the OSD agreed
        self._watch_primaries.pop((pool_id, oid), None)

    async def notify(self, pool_id: int, oid: str,
                     payload: bytes = b"") -> List:
        """Notify watchers; returns the list of watcher addrs that acked
        (librados notify2 reply role)."""
        import pickle as _pickle

        reply = await self._op(MOSDOp(op="notify", pool_id=pool_id, oid=oid,
                                      data=payload))
        return _pickle.loads(reply.data)

    async def list_objects(self, pool_id: int,
                           nspace: str = "") -> List[str]:
        """Paginated per-PG-primary listing (reference pgls/do_pgnls):
        admin listings scale with PG count, never cluster size.  Falls
        back to the all-OSD union for a PG whose primary cannot answer
        (mid-peering) — correctness over elegance for admin tooling.
        `nspace` filters server-side ("" = default namespace,
        ALL_NSPACES = everything); returned names are WIRE names — the
        IoCtx strips its namespace prefix for its callers."""
        if self.osdmap is None:
            await self.refresh_map()
        pool = self.osdmap.pools.get(pool_id)
        if pool is None:
            # our map may predate the pool: one refresh before concluding
            await self.refresh_map()
            pool = self.osdmap.pools.get(pool_id)
        if pool is None:
            raise RadosError(f"pool {pool_id} does not exist",
                             code=-errno.ENOENT)
        oids: set = set()
        fallback = False
        for pg in range(pool.pg_num):
            acting = self.osdmap.pg_to_acting(pool, pg)
            primary = self.osdmap.primary_of(acting,
                                             seed=(pool_id << 20) | pg)
            if primary is None:
                fallback = True
                continue
            cursor = ""
            while True:
                try:
                    reply = await self._op_direct(primary, MOSDOp(
                        op="pgls", pool_id=pool_id, pg=pg, cursor=cursor,
                        nspace=nspace))
                except RadosError:
                    fallback = True
                    break
                oids.update(reply.oids)
                cursor = getattr(reply, "cursor", "")
                if not cursor:
                    break
        if fallback:
            # degraded path: union of per-OSD listings covers the holes
            for osd in self.osdmap.osds.values():
                if not osd.up:
                    continue
                try:
                    reply = await self._op_direct(
                        osd.osd_id, MOSDOp(op="list", pool_id=pool_id,
                                           nspace=nspace))
                    oids.update(reply.oids)
                except RadosError:
                    continue
        return sorted(oids)

    def _pg_primaries(self, pool_id: int) -> List[int]:
        """The distinct primaries of a pool's PGs — the scrub/repair
        fan-out set (per-PG primaries, not every OSD in the cluster)."""
        pool = self.osdmap.pools.get(pool_id)
        if pool is None:
            return []
        primaries = set()
        for pg in range(pool.pg_num):
            acting = self.osdmap.pg_to_acting(pool, pg)
            p = self.osdmap.primary_of(acting, seed=(pool_id << 20) | pg)
            if p is not None:
                primaries.add(p)
        return sorted(primaries)

    async def repair_pool(self, pool_id: int) -> None:
        """Primary-led repair, fanned out to the pool's PG primaries."""
        for osd_id in self._pg_primaries(pool_id):
            try:
                await self._op_direct(osd_id,
                                      MOSDOp(op="repair", pool_id=pool_id))
            except RadosError:
                continue

    async def _op_direct(self, osd_id: int, op: MOSDOp) -> MOSDOpReply:
        op.reqid = uuid.uuid4().hex
        if not getattr(op, "client", ""):
            op.client = self.name
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._replies[op.reqid] = fut
        try:
            await self.messenger.send(self.osdmap.addr_of(osd_id), op)
            reply = await asyncio.wait_for(fut, timeout=self.op_timeout)
        except (ConnectionError, OSError, asyncio.TimeoutError) as e:
            raise RadosError(str(e))
        finally:
            self._replies.pop(op.reqid, None)
        if not reply.ok:
            raise RadosError(reply.error, code=getattr(reply, "code", 0))
        return reply
